"""The MVCC state store: generation-stamped immutable roots, lock-free
snapshots, single-writer transactions, watches, plan application.

Reference behavior: nomad/state/state_store.go (6,611 LoC) -- the subset
that the scheduler, brokers, and API depend on. Tables mirror
schema.go:50-72: nodes, jobs, job_version, evals, allocs, deployments,
index, scheduler_config (plus more added as subsystems land).

Concurrency model (go-memdb parity, PAPER.md layer 2): every table is a
persistent structural-sharing map (state/pmap.py); the whole store
state lives in ONE immutable :class:`StoreRoot` stamped with a
monotonically-increasing generation id. Writes run inside a
single-writer transaction (``_txn``) that accumulates per-table
overlays and commits by building a NEW root (one bulk path-copy per
touched table) and swapping the store's root pointer — atomic under
CPython's attribute-store semantics. Readers never lock anything:
``snapshot()`` is one attribute read, a snapshot is frozen forever,
and a writer never waits for (or invalidates) a reader. The seed
store's copy-on-write table marking (the old COW flag machinery), its
whole-table copies on the write after a snapshot, and the reader/writer
convoy on ``_lock`` are all gone.

Watches fire per-table on commit, giving blocking queries the same
index+watch contract as memdb WatchSets (state_store.go blocking-query
support, rpc.go:808). Because the root (with its per-table commit
indexes) is published BEFORE callbacks fire, a woken waiter always
observes the index that triggered the notify — the seed's
registration-race spurious wakeups cannot happen.

Roots are registered by generation in a process-wide weak registry:
``snapshot_at(generation)`` rehydrates any still-live generation, the
runway for handing snapshots to other worker processes by id alone
(ROADMAP open item 1). Dropping every reference to a snapshot releases
exactly its private subtrees (structural sharing; property-tested in
tests/test_mvcc_store.py).
"""

from __future__ import annotations

import itertools
import pickle
import threading
import time
import weakref
from contextlib import contextmanager
from typing import Callable, Dict, Iterator, List, Optional, Set, Tuple

from nomad_tpu.state.pmap import EMPTY, PMap, TOMBSTONE, pmap_diff
from nomad_tpu.structs import consts
from nomad_tpu.structs.alloc import Allocation
from nomad_tpu.structs.eval_plan import Deployment, Evaluation, Plan, PlanResult
from nomad_tpu.utils.witness import witness_lock


class SchedulerConfiguration:
    """Runtime-mutable scheduler config (reference structs.go
    SchedulerConfiguration; stored in raft, schema.go:65)."""

    def __init__(self) -> None:
        self.scheduler_algorithm = consts.SCHEDULER_ALGORITHM_BINPACK
        self.preemption_system_enabled = True
        self.preemption_batch_enabled = False
        self.preemption_service_enabled = False
        self.memory_oversubscription_enabled = False
        self.pause_eval_broker = False

    def effective_algorithm(self) -> str:
        return self.scheduler_algorithm

    def preemption_enabled(self, scheduler_type: str) -> bool:
        return {
            consts.JOB_TYPE_SERVICE: self.preemption_service_enabled,
            consts.JOB_TYPE_BATCH: self.preemption_batch_enabled,
            consts.JOB_TYPE_SYSTEM: self.preemption_system_enabled,
            consts.JOB_TYPE_SYSBATCH: self.preemption_system_enabled,
        }.get(scheduler_type, False)


class WatchStats:
    """Blocking-query wakeup accounting (ISSUE 11): how many watchers
    ``block_until`` currently holds parked, how often they wake for a
    real index advance vs spuriously (a shared Event set without the
    watched tables' index actually advancing past the waiter's floor),
    and how many waits expire. The serving plane is mostly reads and
    watches — without these counters a fleet-scale watch storm is
    invisible in every exposition surface."""

    __slots__ = ("_lock", "held", "wakeups", "spurious", "timeouts")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.held = 0
        self.wakeups = 0
        self.spurious = 0
        self.timeouts = 0

    def enter(self) -> None:
        with self._lock:
            self.held += 1

    def leave(self) -> None:
        with self._lock:
            self.held -= 1

    def note_wakeup(self, spurious: bool) -> None:
        with self._lock:
            if spurious:
                self.spurious += 1
            else:
                self.wakeups += 1

    def note_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "held_watchers": self.held,
                "wakeups": self.wakeups,
                "spurious_wakeups": self.spurious,
                "timeouts": self.timeouts,
            }

    def reset_stats(self) -> None:
        """Counters only; the held gauge tracks live waiters."""
        with self._lock:
            self.wakeups = 0
            self.spurious = 0
            self.timeouts = 0


#: process-wide (every StateStore's block_until feeds it; exported as
#: nomad_tpu_watch_* and ridden into TRACE_DECOMP's serving section)
watch_stats = WatchStats()


class StoreStats:
    """MVCC plumbing counters, exported as ``nomad_tpu_store_*``.

    Deliberately lock-free: the snapshot counter is bumped on the
    read path, which this subsystem promises never blocks — a plain
    ``+=`` under the GIL can drop the odd increment under thread races,
    which is acceptable for a monotone monitoring counter and nothing
    else reads it for correctness. Write-side counters are bumped under
    the write lock and are exact."""

    __slots__ = ("write_txns", "snapshots", "restores", "last_generation")

    def __init__(self) -> None:
        self.write_txns = 0
        self.snapshots = 0
        self.restores = 0
        self.last_generation = 0

    def note_write(self, generation: int) -> None:
        self.write_txns += 1
        self.last_generation = generation

    def note_restore(self, generation: int) -> None:
        self.restores += 1
        self.last_generation = generation

    def note_snapshot(self) -> None:
        self.snapshots += 1

    def snapshot(self) -> Dict:
        leased = leased_generation_count()
        total = len(_ROOT_REGISTRY)
        return {
            "write_txns": self.write_txns,
            "snapshots": self.snapshots,
            "restores": self.restores,
            "last_generation": self.last_generation,
            "live_roots": total,
            # split (ISSUE 17): roots alive only because a worker
            # process leased them vs roots some in-process reader
            # still holds. A root can be both; the split attributes
            # it to the lease (the lease is what would retain it if
            # every in-process reader dropped).
            "live_roots_leased": leased,
            "live_roots_in_process": max(total - leased, 0),
        }

    def reset_stats(self) -> None:
        """Rate counters only; the generation high-water mark is
        identity, not a rate, and survives the window reset."""
        self.write_txns = 0
        self.snapshots = 0
        self.restores = 0


#: process-wide (multiple stores feed it; bench cells window it like
#: the other *_stats singletons via telemetry.reset_window_stats)
store_stats = StoreStats()

#: process-wide generation ids: unique across every store in the
#: process so a generation id alone names a root in the registry
#: (the cross-process-worker runway wants ids that never collide)
_GENERATIONS = itertools.count(1)

#: generation -> StoreRoot, weak on the root: a generation stays
#: rehydratable exactly as long as SOMETHING still references its root
#: (the store's current pointer, a live StateSnapshot, a pinned
#: serialization). Dropping the last reference releases the root and
#: every subtree not shared with a newer generation.
_ROOT_REGISTRY: "weakref.WeakValueDictionary[int, StoreRoot]" = \
    weakref.WeakValueDictionary()


def snapshot_at(generation: int) -> Optional["StateSnapshot"]:
    """Rehydrate a snapshot from a still-live generation id; None if
    that generation's root has been released."""
    root = _ROOT_REGISTRY.get(generation)
    if root is None:
        return None
    return StateSnapshot(root)


# --- cross-process generation leases (ISSUE 17) ------------------------
#
# The weak registry frees a root the moment no IN-PROCESS reader holds
# it — but a worker process reading a snapshot it reconstructed from a
# ``(gen, delta)`` frame holds nothing in the owner's process, so the
# owner could release the very root the next delta must diff against.
# A lease is an explicit STRONG pin, keyed by (owner, generation), with
# a liveness-bounded TTL: the supervisor renews its workers' leases on
# their heartbeats and releases them on advance or death; a wedged
# supervisor's pins expire rather than retaining roots forever.

#: default lease TTL — several heartbeat intervals of slack
LEASE_TTL_S = 30.0

_lease_lock = threading.Lock()
#: (owner, generation) -> [root strong ref, expires_at (monotonic)]
_GENERATION_LEASES: Dict[Tuple[str, int], List] = {}


def _expire_leases_locked(now: float) -> int:
    doomed = [k for k, (_root, exp) in _GENERATION_LEASES.items()
              if exp <= now]
    for k in doomed:
        del _GENERATION_LEASES[k]
    return len(doomed)


def lease_generation(generation: int, owner: str,
                     ttl_s: float = LEASE_TTL_S) -> bool:
    """Pin ``generation``'s root for ``owner`` (a worker-process id);
    False when the root is already gone. Renewing an existing lease
    just extends its expiry."""
    now = time.monotonic()
    root = _ROOT_REGISTRY.get(generation)
    with _lease_lock:
        _expire_leases_locked(now)
        if root is None:
            return False
        _GENERATION_LEASES[(owner, generation)] = [root, now + ttl_s]
    return True


def release_generation_lease(generation: int, owner: str) -> bool:
    with _lease_lock:
        return _GENERATION_LEASES.pop((owner, generation), None) is not None


def release_owner_leases(owner: str) -> int:
    """Drop every lease held by ``owner`` (worker death / shutdown)."""
    with _lease_lock:
        doomed = [k for k in _GENERATION_LEASES if k[0] == owner]
        for k in doomed:
            del _GENERATION_LEASES[k]
    return len(doomed)


def renew_owner_leases(owner: str, ttl_s: float = LEASE_TTL_S) -> int:
    """Heartbeat-driven renewal: extend every lease ``owner`` holds."""
    now = time.monotonic()
    with _lease_lock:
        _expire_leases_locked(now)
        n = 0
        for (o, _gen), row in _GENERATION_LEASES.items():
            if o == owner:
                row[1] = now + ttl_s
                n += 1
    return n


def expire_generation_leases() -> int:
    """Drop expired leases (the supervisor's liveness sweep calls this;
    every lease call expires lazily too). Returns the drop count."""
    with _lease_lock:
        return _expire_leases_locked(time.monotonic())


def leased_generation_count() -> int:
    """Distinct generations currently pinned by a live lease."""
    now = time.monotonic()
    with _lease_lock:
        _expire_leases_locked(now)
        return len({gen for (_o, gen) in _GENERATION_LEASES})


# --- snapshot transport frames (ISSUE 17) ------------------------------
#
# The wire shapes for feeding worker-process replicas: one ``bootstrap``
# frame at attach (the only full-state ship), then ``(gen, delta)``
# frames — per-table overlays computed by pmap_diff's identity-pruned
# walk, O(changes) not O(store). Frames adopt the OWNER's generation
# ids, so a worker-side snapshot names the same state the owner's
# registry does, and the replica's usage planes are advanced by
# replaying the same transitions the owner's write paths took — the
# `usage_rebuild_diff` bit-identity invariant holds on both sides.


def bootstrap_frame(store: "StateStore", pin_owner: Optional[str] = None,
                    ttl_s: float = LEASE_TTL_S) -> Dict:
    """Full-state frame off ONE root, lock-free (the to_snapshot_bytes
    discipline). With ``pin_owner`` the target generation is leased
    while the root is still strongly held here — no window where a
    commit storm could release it before the pin lands."""
    root = store._root
    frame = {
        "kind": "bootstrap",
        "generation": root.generation,
        "index": root.index,
        "tables": {name: root.tables[name].to_dict()
                   for name in _TABLE_NAMES},
        "table_indexes": dict(root.table_indexes),
        "scheduler_config": root.scheduler_config,
        "autopilot_config": dict(root.autopilot_config),
        "draining_nodes": root.draining_nodes,
    }
    if pin_owner is not None:
        lease_generation(root.generation, pin_owner, ttl_s)
    return frame


def delta_frame(store: "StateStore", from_generation: int,
                pin_owner: Optional[str] = None,
                ttl_s: float = LEASE_TTL_S) -> Optional[Dict]:
    """The ``(gen, delta)`` frame turning ``from_generation``'s root
    into the store's current root; None when the base root is gone
    (caller falls back to a bootstrap frame) or nothing changed.
    Never re-pickles the whole store: per-table overlays come from
    pmap_diff, and unchanged config/draining fields ship as None."""
    new_root = store._root
    if new_root.generation == from_generation:
        return None
    old_root = _ROOT_REGISTRY.get(from_generation)
    if old_root is None:
        return None
    tables: Dict[str, Dict] = {}
    for name in _TABLE_NAMES:
        ot, nt = old_root.tables[name], new_root.tables[name]
        if ot is nt:
            continue
        changes = pmap_diff(ot, nt)
        if not changes:
            continue
        # TOMBSTONE is an unpicklable-by-identity sentinel: encode
        # deletes as a key list instead
        sets = {k: v for k, v in changes.items() if v is not TOMBSTONE}
        dels = [k for k, v in changes.items() if v is TOMBSTONE]
        tables[name] = {"set": sets, "del": dels}
    frame = {
        "kind": "delta",
        "from_generation": from_generation,
        "generation": new_root.generation,
        "index": new_root.index,
        "tables": tables,
        "table_indexes": (dict(new_root.table_indexes)
                          if new_root.table_indexes
                          is not old_root.table_indexes else None),
        "scheduler_config": (new_root.scheduler_config
                             if new_root.scheduler_config
                             is not old_root.scheduler_config else None),
        "autopilot_config": (dict(new_root.autopilot_config)
                             if new_root.autopilot_config
                             is not old_root.autopilot_config else None),
        "draining_nodes": (new_root.draining_nodes
                           if new_root.draining_nodes
                           is not old_root.draining_nodes else None),
    }
    if pin_owner is not None:
        lease_generation(new_root.generation, pin_owner, ttl_s)
    return frame


def apply_frame(store: "StateStore", frame: Dict) -> None:
    """Apply a transport frame to a REPLICA store (a worker process's
    follower copy). Adopts the owner's generation id — the replica's
    snapshot at gen G is the owner's state at gen G — and replays
    node/alloc transitions through the replica's UsageIndex exactly as
    the owner's write paths did, so ``usage_rebuild_diff`` stays empty
    on the replica. Delta frames must apply in order: a frame whose
    base is not the replica's current generation raises (the transport
    serializes frames per connection, so this only fires on a protocol
    bug). Replica roots are NOT registered in the process-wide
    generation registry: the replica is a follower view, not a root
    provider."""
    kind = frame.get("kind")
    if kind == "bootstrap":
        tables = {name: PMap.from_dict(frame["tables"][name])
                  for name in _TABLE_NAMES}
        with store._write_lock:
            store.usage.rebuild(frame["tables"]["nodes"].values(),
                                frame["tables"]["allocs"].values())
            root = StoreRoot(
                generation=frame["generation"],
                index=frame["index"],
                tables=tables,
                table_indexes=dict(frame["table_indexes"]),
                usage=store.usage.planes_copy(),
                scheduler_config=frame["scheduler_config"],
                autopilot_config=dict(frame["autopilot_config"]),
                draining_nodes=frame["draining_nodes"],
            )
            store._root = root
        return
    if kind != "delta":
        raise ValueError(f"unknown frame kind {kind!r}")
    with store._write_lock:
        base = store._root
        if frame["from_generation"] != base.generation:
            raise ValueError(
                f"out-of-order delta frame: base gen "
                f"{frame['from_generation']} != replica gen "
                f"{base.generation}")
        tables = dict(base.tables)
        allocs_before = base.tables["allocs"]
        for name in _TABLE_NAMES:
            chg = frame["tables"].get(name)
            if chg is None:
                continue
            overlay = dict(chg["set"])
            for k in chg["del"]:
                overlay[k] = TOMBSTONE
            if name == "nodes":
                # same transitions the owner's node write paths took
                # (delete before upsert: a recycled node id must land
                # in a fresh row, not inherit the old one's planes)
                for nid in chg["del"]:
                    store.usage.drop_node(nid)
                for nid in chg["set"]:
                    store.usage.node_row(nid)
                    store.usage.note_node_change(nid)
            elif name == "allocs":
                for aid in chg["del"]:
                    old_a = allocs_before.get(aid)
                    if old_a is not None:
                        store.usage.alloc_changed(old_a, None)
                for aid, new_a in chg["set"].items():
                    store.usage.alloc_changed(
                        allocs_before.get(aid), new_a)
            tables[name] = tables[name].update_with(overlay)
        root = StoreRoot(
            generation=frame["generation"],
            index=frame["index"],
            tables=tables,
            table_indexes=(dict(frame["table_indexes"])
                           if frame["table_indexes"] is not None
                           else base.table_indexes),
            usage=store.usage.planes_copy(),
            scheduler_config=(frame["scheduler_config"]
                              if frame["scheduler_config"] is not None
                              else base.scheduler_config),
            autopilot_config=(dict(frame["autopilot_config"])
                              if frame["autopilot_config"] is not None
                              else base.autopilot_config),
            draining_nodes=(frame["draining_nodes"]
                            if frame["draining_nodes"] is not None
                            else base.draining_nodes),
        )
        store._root = root


#: every table in a root, in payload order. Index tables (allocs_by_*)
#: hold immutable frozenset values; scaling_events holds tuples — row
#: values are never mutated in place anywhere, only replaced.
_TABLE_NAMES = (
    "nodes", "jobs", "job_versions", "evals", "allocs", "deployments",
    "allocs_by_job", "allocs_by_node", "allocs_by_eval", "csi_volumes",
    "namespaces", "scaling_events", "acl_policies", "acl_tokens",
    "services", "one_time_tokens", "periodic_launches", "regions",
)

#: tables whose watchers fire on restore (restored ACLs must bump
#: their table indexes, or the token resolver's index-keyed
#: compiled-ACL cache keeps serving pre-restore policies)
_RESTORE_NOTIFY = (
    "nodes", "jobs", "evals", "allocs", "deployment",
    "scheduler_config", "csi_volumes", "services",
    "acl_policy", "acl_token",
)


class StoreRoot:
    """One immutable point-in-time state of the whole store.

    Everything a reader can observe hangs off the root: the PMap
    tables, the per-watch-key commit indexes, the frozen usage planes,
    the config objects, and the derived draining-node set. A root is
    never mutated after publication; a commit builds a new one. The
    ``__weakref__`` slot is what lets the generation registry hold
    roots without pinning them."""

    __slots__ = ("generation", "index", "tables", "table_indexes",
                 "usage", "scheduler_config", "autopilot_config",
                 "draining_nodes", "__weakref__")

    def __init__(self, generation: int, index: int,
                 tables: Dict[str, PMap], table_indexes: Dict[str, int],
                 usage, scheduler_config, autopilot_config: Dict,
                 draining_nodes: frozenset) -> None:
        self.generation = generation
        self.index = index
        self.tables = tables
        self.table_indexes = table_indexes
        self.usage = usage
        self.scheduler_config = scheduler_config
        self.autopilot_config = autopilot_config
        self.draining_nodes = draining_nodes


class _WriteTxn:
    """Single-writer transaction: per-table ``{key: row-or-TOMBSTONE}``
    overlays over a base root. Reads through the txn see the overlay
    first (a txn observes its own writes, like memdb's write txn);
    commit folds each overlay into its table with one bulk path-copy
    (``PMap.update_with``) and swaps the root.

    Inside a :meth:`StateStore.batch_txn` scope the txn carries the
    enclosing ``parent`` accumulator: reads fall through its own
    overlay to the batch's (earlier entries in the same batch are
    visible, exactly as if each had committed), and a clean exit folds
    into the accumulator instead of swapping the root."""

    __slots__ = ("base", "parent", "index", "overlays", "notify",
                 "scheduler_config", "autopilot_config", "aborted")

    def __init__(self, base: StoreRoot, parent=None) -> None:
        self.base = base
        self.parent = parent
        self.index = (parent.index if parent is not None
                      else base.index) + 1
        self.overlays: Dict[str, Dict] = {}
        self.notify: List[str] = []
        self.scheduler_config = None
        self.autopilot_config: Optional[Dict] = None
        self.aborted = False

    def get(self, table: str, key, default=None):
        ov = self.overlays.get(table)
        if ov is not None and key in ov:
            val = ov[key]
            return default if val is TOMBSTONE else val
        if self.parent is not None:
            return self.parent.get(table, key, default)
        return self.base.tables[table].get(key, default)

    def set(self, table: str, key, value) -> None:
        self.overlays.setdefault(table, {})[key] = value

    def delete(self, table: str, key) -> None:
        self.overlays.setdefault(table, {})[key] = TOMBSTONE

    def items(self, table: str) -> Iterator[Tuple]:
        ov = self.overlays.get(table)
        pov = (self.parent.overlays.get(table)
               if self.parent is not None else None)
        if not ov and not pov:
            yield from self.base.tables[table].items()
            return
        merged = dict(pov) if pov else {}
        if ov:
            merged.update(ov)
        for k, v in self.base.tables[table].items():
            if k not in merged:
                yield k, v
        for k, v in merged.items():
            if v is not TOMBSTONE:
                yield k, v

    def values(self, table: str) -> Iterator:
        for _k, v in self.items(table):
            yield v

    def abort(self) -> None:
        """Commit nothing: no index bump, no generation, no notify
        (the seed's early-return-current-index write paths)."""
        self.aborted = True


class _BatchTxn:
    """Accumulator behind :meth:`StateStore.batch_txn`: N inner write
    txns fold into ONE root swap. The batched raft apply loop (ISSUE
    18) runs a whole committed range through this — one write-lock
    span, one ``update_with`` fold per touched table, one generation,
    one watcher notify at the batch's newest index.

    Per-table ``notify_indexes`` keep each table's commit index EXACT
    (the index of the last inner txn that touched it) — a blocking
    query's fast path keys on table indexes, and rounding them all up
    to the batch index would wake/pass waiters whose table never
    changed (the busy-loop hazard ``block_until`` is built to avoid).

    ``owner`` is the batching thread's ident: only that thread (the
    raft apply loop running FSM handlers) reads through the pending
    overlays via the ``*_direct`` accessors — every other reader keeps
    MVCC isolation on the last published root."""

    __slots__ = ("base", "owner", "overlays", "notify",
                 "notify_indexes", "scheduler_config",
                 "autopilot_config", "txn_count")

    def __init__(self, base: StoreRoot) -> None:
        self.base = base
        self.owner = threading.get_ident()
        self.overlays: Dict[str, Dict] = {}
        self.notify: Set[str] = set()
        self.notify_indexes: Dict[str, int] = {}
        self.scheduler_config = None
        self.autopilot_config: Optional[Dict] = None
        self.txn_count = 0

    @property
    def index(self) -> int:
        return self.base.index + self.txn_count

    def get(self, table: str, key, default=None):
        ov = self.overlays.get(table)
        if ov is not None and key in ov:
            val = ov[key]
            return default if val is TOMBSTONE else val
        return self.base.tables[table].get(key, default)

    def fold(self, txn: "_WriteTxn") -> None:
        """Absorb a clean inner txn (called under the write lock)."""
        self.txn_count += 1
        for name, overlay in txn.overlays.items():
            self.overlays.setdefault(name, {}).update(overlay)
        for t in txn.notify:
            self.notify.add(t)
            self.notify_indexes[t] = txn.index
        if txn.scheduler_config is not None:
            self.scheduler_config = txn.scheduler_config
        if txn.autopilot_config is not None:
            self.autopilot_config = txn.autopilot_config


class StateSnapshot:
    """A point-in-time read view (memdb Snapshot analog).

    Implements the scheduler's ``State`` interface
    (reference scheduler/scheduler.go:67-141).

    Construction is O(1) and LOCK-FREE: it wraps one immutable
    :class:`StoreRoot` — no table copies, no COW marking, no writer
    coordination of any kind. The snapshot is frozen at its generation
    forever; later writes build new roots and cannot reach it.
    """

    def __init__(self, root) -> None:
        if isinstance(root, StateStore):    # back-compat construction
            root = root._root
        self._root = root
        self.generation = root.generation
        self.index = root.index
        tables = root.tables
        self._nodes = tables["nodes"]
        self._jobs = tables["jobs"]
        self._job_versions = tables["job_versions"]
        self._evals = tables["evals"]
        self._allocs = tables["allocs"]
        self._deployments = tables["deployments"]
        self._allocs_by_job = tables["allocs_by_job"]
        self._allocs_by_node = tables["allocs_by_node"]
        self._allocs_by_eval = tables["allocs_by_eval"]
        self._csi_volumes = tables["csi_volumes"]
        self.scheduler_config = root.scheduler_config
        # frozen utilization planes for the scheduler fast path
        # (state/usage.py), captured at this generation's commit —
        # consistent with the tables by construction
        self.usage = root.usage

    def stamp(self) -> Dict[str, int]:
        """The read plane's provenance stamp: which frozen root this
        view serves (ISSUE 20 generation-stamped reads)."""
        return {"generation": self.generation, "index": self.index}

    # --- State interface (scheduler.go:67-141) ---

    def nodes(self) -> List:
        return list(self._nodes.values())

    def node_by_id(self, node_id: str):
        return self._nodes.get(node_id)

    def ready_nodes_in_pool(self, pool: str = "default") -> List:
        return [n for n in self._nodes.values() if n.ready()]

    def job_by_id(self, namespace: str, job_id: str):
        return self._jobs.get((namespace, job_id))

    def job_by_id_and_version(self, namespace: str, job_id: str, version: int):
        return self._job_versions.get((namespace, job_id, version))

    def jobs(self) -> List:
        return list(self._jobs.values())

    def eval_by_id(self, eval_id: str):
        return self._evals.get(eval_id)

    def evals_iter(self):
        return self._evals.values()

    def evals_by_job(self, namespace: str, job_id: str) -> List[Evaluation]:
        return [
            e for e in self._evals.values()
            if e.namespace == namespace and e.job_id == job_id
        ]

    def allocs_by_job(self, namespace: str, job_id: str, anyCreateIndex: bool = True) -> List[Allocation]:
        ids = self._allocs_by_job.get((namespace, job_id), ())
        return [self._allocs[i] for i in ids]

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        ids = self._allocs_by_node.get(node_id, ())
        return [self._allocs[i] for i in ids]

    def allocs_by_node_terminal(self, node_id: str, terminal: bool) -> List[Allocation]:
        return [a for a in self.allocs_by_node(node_id) if a.terminal_status() == terminal]

    def allocs_by_eval(self, eval_id: str) -> List[Allocation]:
        ids = self._allocs_by_eval.get(eval_id, ())
        return [self._allocs[i] for i in ids]

    def alloc_by_id(self, alloc_id: str):
        return self._allocs.get(alloc_id)

    def allocs_iter(self):
        return self._allocs.values()

    def latest_deployment_by_job_id(self, namespace: str, job_id: str):
        best = None
        for d in self._deployments.values():
            if d.namespace == namespace and d.job_id == job_id:
                if best is None or d.create_index > best.create_index:
                    best = d
        return best

    def deployments_by_job_id(self, namespace: str, job_id: str) -> List[Deployment]:
        return [
            d for d in self._deployments.values()
            if d.namespace == namespace and d.job_id == job_id
        ]

    def deployment_by_id(self, deployment_id: str):
        return self._deployments.get(deployment_id)

    def deployments_iter(self):
        return self._deployments.values()

    def csi_volume_by_id(self, namespace: str, volume_id: str):
        return self._csi_volumes.get((namespace, volume_id))

    def csi_volumes_iter(self):
        return self._csi_volumes.values()

    def latest_index(self) -> int:
        return self.index


class StateStore:
    """The writable store. One per server; FSM applies Raft entries here."""

    def __init__(self) -> None:
        from nomad_tpu.state.usage import UsageIndex

        # the ONLY lock on the data path, held by writers for the span
        # of one transaction. Readers never touch it: every read
        # accessor below starts from one atomic `self._root` load.
        self._write_lock = witness_lock("store_write_txn", rlock=True)
        # watcher registration only (never nested with the write lock
        # held in either direction on the commit path: callbacks are
        # collected under it and fired outside both locks)
        self._watch_lock = witness_lock("store_watch")
        # incrementally-scattered per-node utilization planes; every
        # alloc/node mutation routes its transition through it UNDER
        # THE WRITE LOCK, and each commit freezes planes_copy() (cached
        # — free when the txn didn't touch usage) into the new root
        self.usage = UsageIndex()
        # table name -> [callback(index)]; fired outside all locks
        self._watchers: Dict[str, List[Callable[[int], None]]] = {}
        # active batch accumulator (batch_txn scope); guarded by the
        # write RLock — only the owning thread ever sees a non-None
        # value from inside a _txn it opened
        self._batch: Optional[_BatchTxn] = None
        root = StoreRoot(
            generation=next(_GENERATIONS),
            index=0,
            tables={name: EMPTY for name in _TABLE_NAMES},
            table_indexes={},
            usage=self.usage.planes_copy(),
            scheduler_config=SchedulerConfiguration(),
            # autopilot config (schema.go autopilot-config)
            autopilot_config={
                "cleanup_dead_servers": True,
                "last_contact_threshold_s": 10.0,
                "server_stabilization_time_s": 10.0,
            },
            draining_nodes=frozenset(),
        )
        _ROOT_REGISTRY[root.generation] = root
        self._root = root

    # --- infrastructure ---

    def snapshot(self) -> StateSnapshot:
        """O(1), lock-free: one root-pointer read."""
        store_stats.note_snapshot()
        return StateSnapshot(self._root)

    def current_generation(self) -> int:
        return self._root.generation

    def snapshot_at(self, generation: int) -> Optional[StateSnapshot]:
        """Rehydrate a still-live generation by id (module-level
        ``snapshot_at`` reaches across stores; this is the same
        registry)."""
        return snapshot_at(generation)

    def latest_index(self) -> int:
        return self._root.index

    def read_stamp(self) -> Tuple[int, int]:
        """``(generation, index)`` from ONE atomic root load — the
        generation-stamped read the read plane serves against
        (ISSUE 20). Reading ``current_generation()`` and
        ``latest_index()`` separately can straddle a root swap; this
        cannot."""
        root = self._root
        return root.generation, root.index

    @property
    def scheduler_config(self) -> SchedulerConfiguration:
        """The current root's scheduler config. The OBJECT is shared
        across generations until ``set_scheduler_config`` replaces it
        (reference semantics: operator flags take effect immediately,
        they are config, not versioned state)."""
        return self._root.scheduler_config

    @property
    def autopilot_config(self) -> Dict:
        return self._root.autopilot_config

    def watch(self, table: str, cb: Callable[[int], None]) -> Callable[[], None]:
        """Register a commit callback for a table; returns unwatch fn."""
        with self._watch_lock:
            self._watchers.setdefault(table, []).append(cb)

        def unwatch() -> None:
            with self._watch_lock:
                lst = self._watchers.get(table, [])
                if cb in lst:
                    lst.remove(cb)

        return unwatch

    def _fire(self, tables: List[str], index: int) -> None:
        """Run watch callbacks for a committed txn — OUTSIDE both
        locks, and strictly AFTER the new root (with its advanced
        table_indexes) is published, so a woken waiter's index read
        always sees the commit that woke it."""
        cbs: List[Callable[[int], None]] = []
        with self._watch_lock:
            for t in tables:
                cbs.extend(self._watchers.get(t, ()))
        for cb in cbs:
            cb(index)

    def table_index(self, tables: List[str]) -> int:
        """Highest commit index across the given tables (lock-free)."""
        ti = self._root.table_indexes
        return max((ti.get(t, 0) for t in tables), default=0)

    @contextmanager
    def _txn(self):
        """Single-writer transaction scope. The body stages writes on
        the txn; a normal exit commits (new root, generation bump,
        watcher notify); an exception or ``txn.abort()`` commits
        nothing. graftcheck R4's txn-scope rule keys on this being the
        only mutation doorway.

        Inside an enclosing :meth:`batch_txn` (same thread — the write
        RLock makes the nesting reentrant) a clean exit folds into the
        batch accumulator instead: no root swap, no notify — those
        happen once when the batch closes."""
        self._write_lock.acquire()
        batch = self._batch
        if batch is not None and batch.owner == threading.get_ident():
            try:
                txn = _WriteTxn(self._root, parent=batch)
                yield txn
                if not txn.aborted:
                    batch.fold(txn)
            finally:
                self._write_lock.release()
            return
        t0 = time.perf_counter()
        try:
            txn = _WriteTxn(self._root)
            yield txn
            if not txn.aborted:
                self._commit(txn)
        finally:
            self._write_lock.release()
        if not txn.aborted:
            _record_write_txn(time.perf_counter() - t0)
            if txn.notify:
                self._fire(txn.notify, txn.index)

    @contextmanager
    def batch_txn(self):
        """Batch N write transactions into ONE root swap + ONE watcher
        notify (the batched raft apply loop's doorway). Every ``_txn``
        opened by this thread inside the scope folds into the batch;
        the scope exit publishes one root at the batch's newest index
        and fires each touched table's watchers once, carrying that
        table's own newest index. An empty batch (every inner txn
        aborted, or none opened) publishes nothing."""
        self._write_lock.acquire()
        if self._batch is not None:
            # nested batches collapse into the outer one
            self._write_lock.release()
            yield
            return
        t0 = time.perf_counter()
        batch = _BatchTxn(self._root)
        self._batch = batch
        try:
            yield
            if batch.txn_count:
                self._commit_batch(batch)
        finally:
            self._batch = None
            self._write_lock.release()
        if batch.txn_count:
            _record_write_txn(time.perf_counter() - t0)
            if batch.notify:
                self._fire(sorted(batch.notify), batch.index)

    def _commit(self, txn: _WriteTxn) -> None:
        """Fold one txn's overlays into a new root and publish it.
        Caller holds the write lock."""
        self._publish_root(
            txn.base, txn.overlays,
            {t: txn.index for t in txn.notify}, txn.index,
            txn.scheduler_config, txn.autopilot_config)

    def _commit_batch(self, batch: _BatchTxn) -> None:
        """Fold the whole accumulator into ONE new root. Caller holds
        the write lock."""
        self._publish_root(
            batch.base, batch.overlays, batch.notify_indexes,
            batch.index, batch.scheduler_config, batch.autopilot_config)

    def _publish_root(self, base: StoreRoot, overlays: Dict[str, Dict],
                      notify_indexes: Dict[str, int], index: int,
                      scheduler_config, autopilot_config) -> None:
        """Fold overlays into new tables (one bulk path-copy each),
        build the next root, publish it. Caller holds the write lock;
        the publication itself is one attribute store. Shared by the
        single-txn and batch commit paths — per-table indexes advance
        to each table's OWN newest index (== the txn index on the
        single path), never past it."""
        tables = base.tables
        if overlays:
            tables = dict(tables)
            for name, overlay in overlays.items():
                tables[name] = tables[name].update_with(overlay)
        if notify_indexes:
            table_indexes = dict(base.table_indexes)
            for t, t_idx in notify_indexes.items():
                if table_indexes.get(t, 0) < t_idx:
                    table_indexes[t] = t_idx
        else:
            table_indexes = base.table_indexes
        nodes_overlay = overlays.get("nodes")
        if nodes_overlay:
            draining = set(base.draining_nodes)
            for nid, node in nodes_overlay.items():
                if node is TOMBSTONE or not getattr(node, "drain", False):
                    draining.discard(nid)
                else:
                    draining.add(nid)
            draining = frozenset(draining)
        else:
            draining = base.draining_nodes
        generation = next(_GENERATIONS)
        root = StoreRoot(
            generation=generation,
            index=index,
            tables=tables,
            table_indexes=table_indexes,
            usage=self.usage.planes_copy(),
            scheduler_config=(scheduler_config
                              or base.scheduler_config),
            autopilot_config=(autopilot_config
                              if autopilot_config is not None
                              else base.autopilot_config),
            draining_nodes=draining,
        )
        _ROOT_REGISTRY[generation] = root
        self._root = root
        store_stats.note_write(generation)

    def has_draining_nodes(self) -> bool:
        """O(1) lock-free pre-check for the drainer: the root carries
        the draining-node id set, maintained incrementally at commit."""
        return bool(self._root.draining_nodes)

    def csi_volume_count(self) -> int:
        """O(1) lock-free pre-check for the volume watcher."""
        return len(self._root.tables["csi_volumes"])

    def node_by_id_direct(self, node_id: str):
        """Lock-free read of one node row at the current generation.
        Kept (with its *_direct name) as the blessed single-row
        accessor graftcheck R4 points callers at; rows are replaced,
        never mutated, so handing one out is safe. The batch-owning
        thread reads through the pending batch overlay (its earlier
        entries must be visible to later handlers, exactly as if each
        had committed); everyone else sees the published root."""
        batch = self._batch
        if batch is not None and batch.owner == threading.get_ident():
            return batch.get("nodes", node_id)
        return self._root.tables["nodes"].get(node_id)

    def alloc_by_id_direct(self, alloc_id: str):
        """Lock-free read of one alloc row at the current generation
        (batch-overlay-aware for the owning thread, like
        ``node_by_id_direct``)."""
        batch = self._batch
        if batch is not None and batch.owner == threading.get_ident():
            return batch.get("allocs", alloc_id)
        return self._root.tables["allocs"].get(alloc_id)

    def job_by_id_direct(self, namespace: str, job_id: str):
        """Lock-free read of one job row at the current generation
        (batch-overlay-aware for the owning thread — the FSM's
        stop-without-purge deregister must see a register earlier in
        the same applied batch)."""
        batch = self._batch
        if batch is not None and batch.owner == threading.get_ident():
            return batch.get("jobs", (namespace, job_id))
        return self._root.tables["jobs"].get((namespace, job_id))

    def allocs_by_node_direct(self, node_id: str) -> List:
        """Lock-free read of one node's alloc rows, all from ONE root:
        the id-set and the rows it points at are the same generation,
        so the list can never contain a dangling id (the seed needed
        its lock for that guarantee)."""
        root = self._root
        ids = root.tables["allocs_by_node"].get(node_id, ())
        allocs = root.tables["allocs"]
        return [allocs[i] for i in ids]

    def allocs_by_job_direct(self, namespace: str, job_id: str) -> List:
        """Lock-free read of one job's alloc rows, all from ONE root
        (the ``allocs_by_node_direct`` shape keyed by job): the plan
        applier's duplicate-slot guard needs a job's live slots
        job-wide — a redelivered eval can re-place a slot on a
        different node than the committed original."""
        root = self._root
        ids = root.tables["allocs_by_job"].get((namespace, job_id), ())
        allocs = root.tables["allocs"]
        return [allocs[i] for i in ids]

    def with_usage_view(self, fn):
        """Run ``fn(planes, allocs)``: the frozen utilization planes
        (state/usage.py) and the alloc table of ONE root — both
        READ-ONLY to the callee and mutually consistent BY
        CONSTRUCTION (they were frozen by the same commit). The plan
        applier's group checker folds in-flight plan results against
        this pair; under the seed store the pairing needed the store
        lock held across both reads (server/plan_apply._GroupFitChecker)."""
        root = self._root
        return fn(root.usage, root.tables["allocs"])

    def with_allocs(self, fn):
        """Run ``fn(allocs)`` with one root's alloc table (READ-ONLY
        to the callee) — ``with_usage_view`` without the planes, for
        callers that only need consistent per-alloc liveness reads."""
        return fn(self._root.tables["allocs"])

    def block_until(self, tables: List[str], min_index: int, timeout: float) -> int:
        """Block until one of `tables` commits past min_index or the
        timeout passes; returns those tables' current index. This is the
        memdb WatchSet + min-index contract behind blocking queries
        (reference rpc.go:808 blockingRPC). Keyed on per-table indexes
        so unrelated commits don't wake every watcher."""
        idx = self.table_index(tables)
        if idx > min_index or timeout <= 0:
            return max(idx, min_index)
        event = threading.Event()
        # the notify carries its commit index into this cell, so a
        # wakeup re-checks against the index THAT TRIGGERED IT — and
        # because the root publishes before callbacks fire, the
        # lock-free floor read below can never lag the notify (the
        # seed's registration race, its main spurious-wakeup source)
        cell = [idx]

        def _woken(i: int, _cell=cell, _event=event) -> None:
            if i > _cell[0]:
                _cell[0] = i
            _event.set()

        unwatchers = [self.watch(t, _woken) for t in tables]
        watch_stats.enter()
        try:
            deadline = time.time() + timeout
            # re-check after registration: a commit may have landed
            # between the first check and the watch registration
            idx = max(cell[0], self.table_index(tables))
            while idx <= min_index:
                remaining = deadline - time.time()
                if remaining <= 0:
                    watch_stats.note_timeout()
                    break
                woke = event.wait(remaining)
                event.clear()
                # both reads are lock-free: the cell is the index that
                # fired the event, the table_index a monotone floor
                idx = max(cell[0], self.table_index(tables))
                if woke:
                    watch_stats.note_wakeup(spurious=idx <= min_index)
            return max(idx, min_index)
        finally:
            watch_stats.leave()
            for unwatch in unwatchers:
                unwatch()

    # --- aux tables: namespaces / scaling / ACL / stability -------------

    def upsert_namespace(self, ns) -> int:
        with self._txn() as txn:
            txn.set("namespaces", ns.name, ns)
            txn.notify = ["namespaces"]
        return txn.index

    def delete_namespace(self, name: str) -> int:
        with self._txn() as txn:
            if any(key[0] == name for key, _ in txn.items("jobs")):
                raise ValueError(f"namespace '{name}' has registered jobs")
            txn.delete("namespaces", name)
            txn.notify = ["namespaces"]
        return txn.index

    def namespaces(self) -> List:
        return list(self._root.tables["namespaces"].values())

    def namespace_by_name(self, name: str):
        return self._root.tables["namespaces"].get(name)

    def record_scaling_event(self, namespace: str, job_id: str, group: str,
                             event: Dict) -> int:
        """state_store.go UpsertScalingEvent (bounded history per group).
        History rows are immutable tuples: each event REPLACES the
        tuple (MVCC discipline — older generations keep theirs)."""
        with self._txn() as txn:
            event = dict(event)
            event.setdefault("task_group", group)
            key = (namespace, job_id)
            events = (event,) + txn.get("scaling_events", key, ())
            # structs.go JobTrackedScalingEvents
            txn.set("scaling_events", key, events[:20])
            txn.notify = ["scaling_event"]
        return txn.index

    def scaling_events(self, namespace: str, job_id: str) -> List[Dict]:
        return list(self._root.tables["scaling_events"]
                    .get((namespace, job_id), ()))

    def scaling_policies(self) -> List[Dict]:
        """Derived view: one policy per task group with a scaling stanza
        (reference stores these in a table keyed by target; deriving
        from the jobs table keeps them trivially consistent)."""
        out = []
        for (ns, jid), job in self._root.tables["jobs"].items():
            for tg in job.task_groups:
                if tg.scaling is not None:
                    out.append({
                        "id": f"{ns}/{jid}/{tg.name}",
                        "namespace": ns, "job_id": jid, "group": tg.name,
                        "policy": tg.scaling, "enabled": tg.scaling.enabled,
                    })
        return out

    def scaling_policy_by_id(self, policy_id: str):
        for p in self.scaling_policies():
            if p["id"] == policy_id:
                return p
        return None

    def set_job_stability(self, namespace: str, job_id: str, version: int,
                          stable: bool) -> int:
        with self._txn() as txn:
            idx = txn.index
            job = txn.get("job_versions", (namespace, job_id, version))
            if job is not None:
                # copy-on-write (the seed flipped the flag on the live
                # row, mutating state already visible to snapshots);
                # the jobs-table row is the same logical object when
                # the stabilized version is current, so both tables
                # take the new row
                job = job.copy()
                job.stable = stable
                job.modify_index = idx
                txn.set("job_versions", (namespace, job_id, version), job)
                current = txn.get("jobs", (namespace, job_id))
                if current is not None and current.version == version:
                    txn.set("jobs", (namespace, job_id), job)
            txn.notify = ["jobs"]
        return txn.index

    def upsert_acl_policy(self, policy) -> int:
        with self._txn() as txn:
            txn.set("acl_policies", policy.name, policy)
            txn.notify = ["acl_policy"]
        return txn.index

    def delete_acl_policy(self, name: str) -> int:
        with self._txn() as txn:
            txn.delete("acl_policies", name)
            txn.notify = ["acl_policy"]
        return txn.index

    def acl_policies(self) -> List:
        return list(self._root.tables["acl_policies"].values())

    def acl_policy_by_name(self, name: str):
        return self._root.tables["acl_policies"].get(name)

    def deployment_by_id(self, deployment_id: str):
        """Lock-free read of one deployment row at the current
        generation."""
        return self._root.tables["deployments"].get(deployment_id)

    def active_deployments(self) -> List[Deployment]:
        """Lock-free read of the active deployment rows: the
        deployments watcher polls this on every state change, and rows
        are replaced (never mutated) on update, so handing them out is
        safe."""
        return [d for d in self._root.tables["deployments"].values()
                if d.active()]

    def multiregion_terminal_deployment_ids(self) -> List[str]:
        """Ids of terminal multiregion deployments (the candidates for
        cross-region kicks) — the cheap gate that lets the watcher skip
        whole-state snapshots when there is no multiregion work."""
        return [
            d.id for d in self._root.tables["deployments"].values()
            if d.is_multiregion and d.status in (
                consts.DEPLOYMENT_STATUS_SUCCESSFUL,
                consts.DEPLOYMENT_STATUS_FAILED,
            )
        ]

    def upsert_acl_token(self, token) -> int:
        with self._txn() as txn:
            txn.set("acl_tokens", token.accessor_id, token)
            txn.notify = ["acl_token"]
        return txn.index

    def delete_acl_token(self, accessor_id: str) -> int:
        with self._txn() as txn:
            txn.delete("acl_tokens", accessor_id)
            txn.notify = ["acl_token"]
        return txn.index

    def acl_tokens(self) -> List:
        return list(self._root.tables["acl_tokens"].values())

    def acl_token_by_accessor(self, accessor_id: str):
        return self._root.tables["acl_tokens"].get(accessor_id)

    def acl_token_by_secret(self, secret_id: str):
        for t in self._root.tables["acl_tokens"].values():
            if t.secret_id == secret_id:
                return t
        return None

    # --- CSI volumes (state_store.go UpsertCSIVolume/CSIVolumeClaim) ----

    def upsert_csi_volumes(self, volumes: List) -> int:
        with self._txn() as txn:
            idx = txn.index
            for v in volumes:
                existing = txn.get("csi_volumes", (v.namespace, v.id))
                if existing is not None:
                    # re-register keeps live claims (csi_endpoint.go
                    # Register merge semantics)
                    v.read_claims = existing.read_claims
                    v.write_claims = existing.write_claims
                    v.past_claims = existing.past_claims
                    v.create_index = existing.create_index
                else:
                    v.create_index = idx
                v.modify_index = idx
                txn.set("csi_volumes", (v.namespace, v.id), v)
            txn.notify = ["csi_volumes"]
        return txn.index

    def csi_volume_deregister(self, namespace: str, volume_id: str,
                              force: bool = False) -> int:
        with self._txn() as txn:
            vol = txn.get("csi_volumes", (namespace, volume_id))
            if vol is None:
                raise ValueError(f"volume not found: {volume_id}")
            if vol.in_use() and not force:
                raise ValueError(f"volume in use: {volume_id}")
            txn.delete("csi_volumes", (namespace, volume_id))
            txn.notify = ["csi_volumes"]
        return txn.index

    def csi_volume_claim(self, namespace: str, volume_id: str, claim) -> int:
        """Apply a claim transition copy-on-write (state_store.go
        CSIVolumeClaim)."""
        with self._txn() as txn:
            vol = txn.get("csi_volumes", (namespace, volume_id))
            if vol is None:
                raise ValueError(f"volume not found: {volume_id}")
            vol = vol.copy()
            vol.claim(claim)
            vol.modify_index = txn.index
            txn.set("csi_volumes", (namespace, volume_id), vol)
            txn.notify = ["csi_volumes"]
        return txn.index

    def csi_volumes(self) -> List:
        return list(self._root.tables["csi_volumes"].values())

    def csi_volume_by_id(self, namespace: str, volume_id: str):
        return self._root.tables["csi_volumes"].get((namespace, volume_id))

    def csi_volumes_by_plugin(self, plugin_id: str) -> List:
        return [v for v in self._root.tables["csi_volumes"].values()
                if v.plugin_id == plugin_id]

    # --- service registrations (state_store_service_registration.go) ----

    def upsert_service_registrations(self, regs: List) -> int:
        with self._txn() as txn:
            idx = txn.index
            for r in regs:
                existing = txn.get("services", r.id)
                r.create_index = existing.create_index if existing else idx
                r.modify_index = idx
                txn.set("services", r.id, r)
            txn.notify = ["services"]
        return txn.index

    def delete_service_registration(self, reg_id: str) -> int:
        with self._txn() as txn:
            if txn.get("services", reg_id) is None:
                raise ValueError(f"service registration not found: {reg_id}")
            txn.delete("services", reg_id)
            txn.notify = ["services"]
        return txn.index

    def delete_service_registrations_by_alloc(self, alloc_ids: List[str]) -> int:
        """Client dereg batches + alloc GC
        (DeleteServiceRegistrationByAllocID)."""
        doomed_allocs = set(alloc_ids)
        with self._txn() as txn:
            doomed = [r.id for r in txn.values("services")
                      if r.alloc_id in doomed_allocs]
            if not doomed:
                txn.abort()
                return self._root.index
            for rid in doomed:
                txn.delete("services", rid)
            txn.notify = ["services"]
        return txn.index

    def delete_service_registrations_by_node(self, node_id: str) -> int:
        """Node down/deregister reaping (DeleteServiceRegistrationByNodeID)."""
        with self._txn() as txn:
            doomed = [r.id for r in txn.values("services")
                      if r.node_id == node_id]
            if not doomed:
                txn.abort()
                return self._root.index
            for rid in doomed:
                txn.delete("services", rid)
            txn.notify = ["services"]
        return txn.index

    def service_registrations(self, namespace: str = "*") -> List:
        return [r for r in self._root.tables["services"].values()
                if namespace in ("*", r.namespace)]

    def service_registrations_by_name(self, namespace: str, name: str) -> List:
        return [r for r in self._root.tables["services"].values()
                if r.namespace == namespace and r.service_name == name]

    def service_registration_by_id(self, reg_id: str):
        return self._root.tables["services"].get(reg_id)

    # --- one-time tokens (state_store.go UpsertOneTimeToken) -----------

    def upsert_one_time_token(self, ott: Dict) -> int:
        with self._txn() as txn:
            txn.set("one_time_tokens", ott["one_time_secret_id"], dict(ott))
            txn.notify = ["one_time_token"]
        return txn.index

    def one_time_token_by_secret(self, secret: str):
        return self._root.tables["one_time_tokens"].get(secret)

    def delete_one_time_tokens(self, secrets: List[str]) -> int:
        with self._txn() as txn:
            for s in secrets:
                txn.delete("one_time_tokens", s)
            txn.notify = ["one_time_token"]
        return txn.index

    def expire_one_time_tokens(self, now: float) -> List[str]:
        items = self._root.tables["one_time_tokens"].items()
        batch = self._batch
        if batch is not None and batch.owner == threading.get_ident():
            ov = batch.overlays.get("one_time_tokens")
            if ov:
                merged = dict(items)
                merged.update(ov)
                items = [(s, t) for s, t in merged.items()
                         if t is not TOMBSTONE]
        return [s for s, t in items
                if t.get("expires_at", 0) <= now]

    # --- periodic launch ledger (state_store.go UpsertPeriodicLaunch) ---

    def upsert_periodic_launch(self, namespace: str, job_id: str,
                               launch_time: float) -> int:
        with self._txn() as txn:
            txn.set("periodic_launches", (namespace, job_id), launch_time)
            txn.notify = ["periodic_launch"]
        return txn.index

    def delete_periodic_launch(self, namespace: str, job_id: str) -> int:
        with self._txn() as txn:
            txn.delete("periodic_launches", (namespace, job_id))
            txn.notify = ["periodic_launch"]
        return txn.index

    def periodic_launch_by_id(self, namespace: str, job_id: str) -> float:
        return self._root.tables["periodic_launches"] \
            .get((namespace, job_id), 0.0)

    # --- federation registry --------------------------------------------

    def upsert_region(self, region: str, http_addr: str) -> int:
        with self._txn() as txn:
            txn.set("regions", region, http_addr)
            txn.notify = ["regions"]
        return txn.index

    def regions(self) -> Dict[str, str]:
        return self._root.tables["regions"].to_dict()

    # --- autopilot config (state_store.go AutopilotConfig) --------------

    def set_autopilot_config(self, config: Dict) -> int:
        with self._txn() as txn:
            txn.autopilot_config = dict(config)
            txn.notify = ["autopilot-config"]
        return txn.index

    # --- snapshot persist/restore (fsm.go:1393 Snapshot, :1407 Restore) -

    def to_snapshot_bytes(self) -> bytes:
        """Serialize every table for raft snapshots / operator backup.

        Pins ONE root and serializes it with no locks at all: writers
        keep committing new generations while a multi-second C2M dump
        pickles this one (the seed held its lock to assemble the
        payload; before PR 9's fix it held it for the whole pickle).
        The payload is plain dicts/sets — the same shape the seed
        wrote, so WAL/snapshot files stay readable both ways."""
        root = self._root
        t = root.tables
        payload = {
            "index": root.index,
            "nodes": t["nodes"].to_dict(),
            "jobs": t["jobs"].to_dict(),
            "job_versions": t["job_versions"].to_dict(),
            "evals": t["evals"].to_dict(),
            "allocs": t["allocs"].to_dict(),
            "deployments": t["deployments"].to_dict(),
            "allocs_by_job": {k: set(v)
                              for k, v in t["allocs_by_job"].items()},
            "allocs_by_node": {k: set(v)
                               for k, v in t["allocs_by_node"].items()},
            "allocs_by_eval": {k: set(v)
                               for k, v in t["allocs_by_eval"].items()},
            "scheduler_config": root.scheduler_config,
            "namespaces": t["namespaces"].to_dict(),
            "scaling_events": {k: list(v)
                               for k, v in t["scaling_events"].items()},
            "acl_policies": t["acl_policies"].to_dict(),
            "acl_tokens": t["acl_tokens"].to_dict(),
            "csi_volumes": t["csi_volumes"].to_dict(),
            "services": t["services"].to_dict(),
            "one_time_tokens": t["one_time_tokens"].to_dict(),
            "periodic_launches": t["periodic_launches"].to_dict(),
            "autopilot_config": dict(root.autopilot_config),
            "regions": t["regions"].to_dict(),
        }
        return pickle.dumps(payload)

    def restore_from_bytes(self, data: bytes) -> None:
        payload = pickle.loads(data)
        # bulk-build the PMaps before taking the write lock (restore
        # has no concurrent writers by protocol, but a reader-visible
        # half-restored root must never exist either way)
        tables = {
            "nodes": PMap.from_dict(payload["nodes"]),
            "jobs": PMap.from_dict(payload["jobs"]),
            "job_versions": PMap.from_dict(payload["job_versions"]),
            "evals": PMap.from_dict(payload["evals"]),
            "allocs": PMap.from_dict(payload["allocs"]),
            "deployments": PMap.from_dict(payload["deployments"]),
            "allocs_by_job": PMap.from_dict(
                {k: frozenset(v)
                 for k, v in payload["allocs_by_job"].items()}),
            "allocs_by_node": PMap.from_dict(
                {k: frozenset(v)
                 for k, v in payload["allocs_by_node"].items()}),
            "allocs_by_eval": PMap.from_dict(
                {k: frozenset(v)
                 for k, v in payload["allocs_by_eval"].items()}),
            "namespaces": PMap.from_dict(payload.get("namespaces", {})),
            "scaling_events": PMap.from_dict(
                {k: tuple(v)
                 for k, v in payload.get("scaling_events", {}).items()}),
            "acl_policies": PMap.from_dict(payload.get("acl_policies", {})),
            "acl_tokens": PMap.from_dict(payload.get("acl_tokens", {})),
            "csi_volumes": PMap.from_dict(payload.get("csi_volumes", {})),
            "services": PMap.from_dict(payload.get("services", {})),
            "one_time_tokens": PMap.from_dict(
                payload.get("one_time_tokens", {})),
            "periodic_launches": PMap.from_dict(
                payload.get("periodic_launches", {})),
            "regions": PMap.from_dict(payload.get("regions", {})),
        }
        draining = frozenset(
            nid for nid, n in payload["nodes"].items()
            if getattr(n, "drain", False))
        with self._write_lock:
            self.usage.rebuild(payload["nodes"].values(),
                               payload["allocs"].values())
            base = self._root
            table_indexes = dict(base.table_indexes)
            for t in _RESTORE_NOTIFY:
                if table_indexes.get(t, 0) < payload["index"]:
                    table_indexes[t] = payload["index"]
            generation = next(_GENERATIONS)
            root = StoreRoot(
                generation=generation,
                index=payload["index"],
                tables=tables,
                table_indexes=table_indexes,
                usage=self.usage.planes_copy(),
                scheduler_config=payload["scheduler_config"],
                autopilot_config=dict(payload.get(
                    "autopilot_config", base.autopilot_config)),
                draining_nodes=draining,
            )
            _ROOT_REGISTRY[generation] = root
            self._root = root
            store_stats.note_restore(generation)
        self._fire(list(_RESTORE_NOTIFY), payload["index"])

    # --- writes (FSM apply targets, fsm.go:194-280 dispatch) ---

    def upsert_node(self, node) -> int:
        with self._txn() as txn:
            idx = txn.index
            if not node.computed_class:
                node.compute_class()
            node.modify_index = idx
            if node.create_index == 0:
                node.create_index = idx
            existing = txn.get("nodes", node.id)
            if existing is not None:
                # re-registration keeps OPERATOR intent (state_store.go
                # upsertNodeTxn): a client restarting — including one
                # whose server restarted underneath it (ISSUE 13) —
                # sends a fresh Node struct, but drain state and
                # scheduling eligibility were set through the drain/
                # eligibility endpoints and must survive it
                node.drain = existing.drain
                node.drain_strategy = existing.drain_strategy
                node.scheduling_eligibility = existing.scheduling_eligibility
                if node.create_index == idx:
                    node.create_index = existing.create_index
            txn.set("nodes", node.id, node)
            self.usage.node_row(node.id)
            self.usage.note_node_change(node.id)
            txn.notify = ["nodes"]
        return txn.index

    def delete_node(self, node_id: str) -> int:
        with self._txn() as txn:
            txn.delete("nodes", node_id)
            self.usage.drop_node(node_id)
            txn.notify = ["nodes"]
        return txn.index

    def update_node_status(self, node_id: str, status: str) -> int:
        with self._txn() as txn:
            node = txn.get("nodes", node_id)
            if node is not None:
                node = node.copy()
                node.status = status
                node.modify_index = txn.index
                txn.set("nodes", node_id, node)
                self.usage.note_node_change(node_id)
            txn.notify = ["nodes"]
        return txn.index

    def update_node_eligibility(self, node_id: str, eligibility: str) -> int:
        with self._txn() as txn:
            node = txn.get("nodes", node_id)
            if node is not None:
                node = node.copy()
                node.scheduling_eligibility = eligibility
                node.modify_index = txn.index
                txn.set("nodes", node_id, node)
                self.usage.note_node_change(node_id)
            txn.notify = ["nodes"]
        return txn.index

    def update_node_drain(self, node_id: str, drain: bool, strategy=None,
                          mark_eligible: bool = True) -> int:
        with self._txn() as txn:
            node = txn.get("nodes", node_id)
            if node is not None:
                node = node.copy()
                node.drain = drain
                node.drain_strategy = strategy
                if drain or not mark_eligible:
                    # drain completion keeps the node ineligible until
                    # the operator re-enables (drainer semantics)
                    node.scheduling_eligibility = consts.NODE_SCHEDULING_INELIGIBLE
                else:
                    node.scheduling_eligibility = consts.NODE_SCHEDULING_ELIGIBLE
                node.modify_index = txn.index
                txn.set("nodes", node_id, node)
                self.usage.note_node_change(node_id)
            txn.notify = ["nodes"]
        return txn.index

    def upsert_job(self, job) -> int:
        """UpsertJob: bumps version when the spec changed
        (state_store.go upsertJobImpl semantics)."""
        with self._txn() as txn:
            idx = txn.index
            key = (job.namespace, job.id)
            existing = txn.get("jobs", key)
            if existing is not None:
                if existing.spec_hash() != job.spec_hash():
                    job.version = existing.version + 1
                else:
                    job.version = existing.version
                job.create_index = existing.create_index
            else:
                job.create_index = idx
                job.version = 0
            job.modify_index = idx
            job.job_modify_index = idx
            job.status = _job_status(job)
            txn.set("jobs", key, job)
            txn.set("job_versions", (job.namespace, job.id, job.version), job)
            txn.notify = ["jobs"]
        return txn.index

    def delete_job(self, namespace: str, job_id: str) -> int:
        with self._txn() as txn:
            txn.delete("jobs", (namespace, job_id))
            # purge version history too (state_store.go DeleteJobTxn
            # deletes from the job_version table)
            for key, _ in txn.items("job_versions"):
                if key[0] == namespace and key[1] == job_id:
                    txn.delete("job_versions", key)
            txn.notify = ["jobs"]
        return txn.index

    def upsert_evals(self, evals: List[Evaluation]) -> int:
        with self._txn() as txn:
            idx = txn.index
            for e in evals:
                e.modify_index = idx
                if e.create_index == 0:
                    e.create_index = idx
                txn.set("evals", e.id, e)
            txn.notify = ["evals"]
        return txn.index

    def delete_evals(self, eval_ids: List[str]) -> int:
        with self._txn() as txn:
            for eid in eval_ids:
                txn.delete("evals", eid)
            txn.notify = ["evals"]
        return txn.index

    def upsert_allocs(self, allocs: List[Allocation]) -> int:
        with self._txn() as txn:
            dep_touched = False
            for a in allocs:
                dep_touched |= self._upsert_alloc_txn(txn, a)
            txn.notify = (["allocs", "deployment"] if dep_touched
                          else ["allocs"])
        return txn.index

    def _upsert_alloc_txn(self, txn: _WriteTxn, a: Allocation) -> bool:
        """Returns True when the upsert also wrote a deployment row."""
        idx = txn.index
        existing = txn.get("allocs", a.id)
        if existing is not None:
            # merge client-only fields if this is a server-side update
            a.create_index = existing.create_index
            if a.job is None:
                a.job = existing.job
        else:
            a.create_index = idx
        a.modify_index = idx
        txn.set("allocs", a.id, a)
        self.usage.alloc_changed(existing, a)
        dep_touched = self._update_deployment_with_alloc_txn(
            txn, existing, a)
        for table, key in (
            ("allocs_by_job", (a.namespace, a.job_id)),
            ("allocs_by_node", a.node_id),
            ("allocs_by_eval", a.eval_id),
        ):
            ids = txn.get(table, key)
            if ids is None or a.id not in ids:
                # frozenset replacement, never in-place (older
                # generations keep their id-sets)
                txn.set(table, key, (ids or frozenset()) | {a.id})
        return dep_touched

    def update_allocs_from_client(self, allocs: List[Allocation]) -> int:
        """Client status updates (state_store.go UpdateAllocsFromClient)."""
        with self._txn() as txn:
            idx = txn.index
            dep_touched = False
            for update in allocs:
                existing = txn.get("allocs", update.id)
                if existing is None:
                    continue
                new = existing.copy_skip_job()
                new.client_status = update.client_status
                new.client_description = update.client_description
                new.task_states = dict(update.task_states)
                if update.deployment_status is not None:
                    new.deployment_status = update.deployment_status
                if update.network_status is not None:
                    new.network_status = update.network_status
                new.modify_index = idx
                new.modify_time_ns = update.modify_time_ns
                txn.set("allocs", new.id, new)
                self.usage.alloc_changed(existing, new)
                # health transitions roll up into the deployment
                # (state_store.go updateDeploymentWithAlloc)
                dep_touched |= self._update_deployment_with_alloc_txn(
                    txn, existing, new)
            txn.notify = (["allocs", "deployment"] if dep_touched
                          else ["allocs"])
        return txn.index

    def _update_deployment_with_alloc_txn(
        self, txn: _WriteTxn, old: Optional[Allocation], new: Allocation
    ) -> bool:
        """Bump DeploymentState counters on placement/health changes
        (state_store.go updateDeploymentWithAlloc). Returns True when a
        deployment row was actually written — callers notify the
        "deployment" table only then, so the deployments watcher's
        index-gated early-out actually fires on deployment-less
        placement bursts (the common case)."""
        if not new.deployment_id:
            return False
        d = txn.get("deployments", new.deployment_id)
        if d is None or not d.active():
            return False
        state = d.task_groups.get(new.task_group)
        if state is None:
            return False
        placed = 1 if old is None else 0
        old_h = old.deployment_status.healthy \
            if old is not None and old.deployment_status is not None else None
        new_h = new.deployment_status.healthy \
            if new.deployment_status is not None else None
        d_healthy = (1 if new_h is True else 0) - (1 if old_h is True else 0)
        d_unhealthy = (1 if new_h is False else 0) - (1 if old_h is False else 0)
        if not (placed or d_healthy or d_unhealthy):
            return False
        d = d.copy()
        state = d.task_groups[new.task_group]
        state.placed_allocs += placed
        state.healthy_allocs += d_healthy
        state.unhealthy_allocs += d_unhealthy
        d.modify_index = txn.index
        txn.set("deployments", d.id, d)
        return True

    def update_allocs_desired_transition(self, transitions: Dict[str, object], evals: List[Evaluation]) -> int:
        """{alloc_id: DesiredTransition} -- drainer/operator migrate
        requests (state_store.go UpdateAllocsDesiredTransitions)."""
        with self._txn() as txn:
            idx = txn.index
            for alloc_id, transition in transitions.items():
                existing = txn.get("allocs", alloc_id)
                if existing is None:
                    continue
                new = existing.copy_skip_job()
                new.desired_transition = transition
                new.modify_index = idx
                txn.set("allocs", alloc_id, new)
                self.usage.alloc_changed(existing, new)
            for e in evals:
                e.modify_index = idx
                if e.create_index == 0:
                    e.create_index = idx
                txn.set("evals", e.id, e)
            txn.notify = ["allocs", "evals"]
        return txn.index

    def stop_alloc(self, alloc_id: str, evals: List[Evaluation]) -> int:
        """Mark one alloc desired=stop (`nomad alloc stop`;
        state_store.go UpdateAllocDesiredTransition + stop)."""
        with self._txn() as txn:
            idx = txn.index
            existing = txn.get("allocs", alloc_id)
            if existing is not None:
                new = existing.copy_skip_job()
                new.desired_status = consts.ALLOC_DESIRED_STOP
                new.modify_index = idx
                txn.set("allocs", alloc_id, new)
                self.usage.alloc_changed(existing, new)
            for e in evals:
                e.modify_index = idx
                if e.create_index == 0:
                    e.create_index = idx
                txn.set("evals", e.id, e)
            txn.notify = ["allocs", "evals"]
        return txn.index

    def upsert_deployment(self, d: Deployment) -> int:
        with self._txn() as txn:
            d.modify_index = txn.index
            if d.create_index == 0:
                d.create_index = txn.index
            txn.set("deployments", d.id, d)
            txn.notify = ["deployment"]
        return txn.index

    def update_deployment_status(self, deployment_id: str, status: str, description: str = "") -> int:
        with self._txn() as txn:
            d = txn.get("deployments", deployment_id)
            if d is not None:
                d = d.copy()
                d.status = status
                d.status_description = description or d.status_description
                d.modify_index = txn.index
                txn.set("deployments", deployment_id, d)
            txn.notify = ["deployment"]
        return txn.index

    def delete_allocs(self, alloc_ids: List[str]) -> int:
        """GC path (state_store.go DeleteEval also reaps allocs; service
        registrations of reaped allocs go with them)."""
        with self._txn() as txn:
            doomed = set(alloc_ids)
            for aid in alloc_ids:
                a = txn.get("allocs", aid)
                if a is None:
                    continue
                txn.delete("allocs", aid)
                self.usage.alloc_changed(a, None)
                for table, key in (
                    ("allocs_by_job", (a.namespace, a.job_id)),
                    ("allocs_by_node", a.node_id),
                    ("allocs_by_eval", a.eval_id),
                ):
                    ids = txn.get(table, key)
                    if ids and aid in ids:
                        remaining = ids - {aid}
                        if remaining:
                            txn.set(table, key, remaining)
                        else:
                            txn.delete(table, key)
            stale_regs = [r.id for r in txn.values("services")
                          if r.alloc_id in doomed]
            for rid in stale_regs:
                txn.delete("services", rid)
            txn.notify = (["allocs", "services"] if stale_regs
                          else ["allocs"])
        return txn.index

    def delete_deployments(self, deployment_ids: List[str]) -> int:
        with self._txn() as txn:
            for did in deployment_ids:
                txn.delete("deployments", did)
            txn.notify = ["deployment"]
        return txn.index

    def update_deployment_alloc_health(
        self,
        deployment_id: str,
        healthy_ids: List[str],
        unhealthy_ids: List[str],
        deployment_update: Optional[Dict] = None,
        evals: Optional[List[Evaluation]] = None,
    ) -> int:
        """state_store.go UpdateDeploymentAllocHealth: record per-alloc
        deployment health and bump the DeploymentState counters."""
        from nomad_tpu.structs.alloc import AllocDeploymentStatus

        with self._txn() as txn:
            idx = txn.index
            d = txn.get("deployments", deployment_id)
            if d is not None:
                d = d.copy()
                for aid, healthy in [(i, True) for i in healthy_ids] + [
                    (i, False) for i in unhealthy_ids
                ]:
                    a = txn.get("allocs", aid)
                    if a is None:
                        continue
                    new = a.copy_skip_job()
                    new.job = a.job
                    status = new.deployment_status or AllocDeploymentStatus()
                    was = status.healthy
                    status.healthy = healthy
                    status.modify_index = idx
                    new.deployment_status = status
                    new.modify_index = idx
                    txn.set("allocs", aid, new)
                    self.usage.alloc_changed(a, new)
                    state = d.task_groups.get(new.task_group)
                    if state is not None and was != healthy:
                        if healthy:
                            state.healthy_allocs += 1
                            if was is False:
                                state.unhealthy_allocs -= 1
                        else:
                            state.unhealthy_allocs += 1
                            if was is True:
                                state.healthy_allocs -= 1
                d.modify_index = idx
                if deployment_update:
                    d.status = deployment_update.get("status", d.status)
                    d.status_description = deployment_update.get(
                        "status_description", d.status_description
                    )
                txn.set("deployments", deployment_id, d)
            for e in evals or []:
                e.modify_index = idx
                if e.create_index == 0:
                    e.create_index = idx
                txn.set("evals", e.id, e)
            txn.notify = ["allocs", "deployment", "evals"]
        return txn.index

    def update_deployment_promotion(
        self, deployment_id: str, groups: Optional[List[str]] = None,
        evals: Optional[List[Evaluation]] = None,
    ) -> int:
        """state_store.go UpdateDeploymentPromotion: mark canaries
        promoted for all (or the given) groups."""
        with self._txn() as txn:
            idx = txn.index
            d = txn.get("deployments", deployment_id)
            if d is not None:
                d = d.copy()
                for name, state in d.task_groups.items():
                    if groups is None or name in groups:
                        state.promoted = True
                d.modify_index = idx
                txn.set("deployments", deployment_id, d)
            for e in evals or []:
                e.modify_index = idx
                if e.create_index == 0:
                    e.create_index = idx
                txn.set("evals", e.id, e)
            txn.notify = ["deployment", "evals"]
        return txn.index

    def set_scheduler_config(self, config: SchedulerConfiguration) -> int:
        with self._txn() as txn:
            txn.scheduler_config = config
            txn.notify = ["scheduler_config"]
        return txn.index

    # --- plan application (FSM ApplyPlanResults, fsm.go applyPlanResults) ---

    def upsert_plan_results(
        self,
        alloc_index: int,
        plan: Plan,
        node_allocation: Dict[str, List[Allocation]],
        node_update: Dict[str, List[Allocation]],
        node_preemptions: Dict[str, List[Allocation]],
        deployment: Optional[Deployment] = None,
        deployment_updates: Optional[List[Dict]] = None,
    ) -> int:
        """Commit one (possibly partial) plan the applier validated."""
        return self.upsert_plan_results_batch(alloc_index, [{
            "plan": plan,
            "node_allocation": node_allocation,
            "node_update": node_update,
            "node_preemptions": node_preemptions,
            "deployment": deployment,
            "deployment_updates": deployment_updates,
        }])

    def upsert_plan_results_batch(self, alloc_index: int,
                                  plans: List[Dict]) -> int:
        """Commit a batch of evaluated plans as ONE transaction / index
        bump / watcher notification (the applier merges a burst of
        plans into one raft entry; fsm.go applyPlanResults semantics
        per plan, applied in batch order). A wave of hundreds of alloc
        upserts folds into the alloc table with one bulk path-copy at
        commit (PMap.update_with)."""
        with self._txn() as txn:
            idx = txn.index
            dep_touched = False
            for p in plans:
                plan = p["plan"]
                for allocs in p["node_update"].values():
                    for a in allocs:
                        dep_touched |= self._upsert_alloc_txn(txn, a)
                for allocs in p["node_preemptions"].values():
                    for a in allocs:
                        dep_touched |= self._upsert_alloc_txn(txn, a)
                for allocs in p["node_allocation"].values():
                    for a in allocs:
                        if a.job is None:
                            a.job = plan.job
                        dep_touched |= self._upsert_alloc_txn(txn, a)
                deployment = p.get("deployment")
                if deployment is not None:
                    deployment.modify_index = idx
                    if deployment.create_index == 0:
                        deployment.create_index = idx
                    txn.set("deployments", deployment.id, deployment)
                    dep_touched = True
                for du in p.get("deployment_updates") or []:
                    d = txn.get("deployments", du.get("deployment_id"))
                    if d is not None:
                        d = d.copy()
                        d.status = du.get("status", d.status)
                        d.status_description = du.get(
                            "status_description", d.status_description)
                        d.modify_index = idx
                        txn.set("deployments", d.id, d)
                        dep_touched = True
            # notify "deployment" only when a row actually changed: the
            # deployments watcher's idle gate keys on this index, and a
            # deployment-less placement burst (the common case) must not
            # defeat it by bumping the index on every plan commit
            txn.notify = (["allocs", "deployment"] if dep_touched
                          else ["allocs"])
        return txn.index


def _record_write_txn(dt: float) -> None:
    """One histogram sample per committed transaction (the bench store
    cell's store_write_txn_p99_us reads this distribution)."""
    try:
        from nomad_tpu.telemetry.histogram import histograms

        histograms.get("store_write_txn").record(dt)
    except Exception:                           # noqa: BLE001 - metric only
        pass


def _job_status(job) -> str:
    if job.stop:
        return consts.JOB_STATUS_DEAD
    return consts.JOB_STATUS_PENDING
