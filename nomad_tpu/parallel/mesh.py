"""Device mesh construction for the scheduler kernel.

Axis semantics (SURVEY.md section 2.10/2.11 TPU mapping):

- ``evals``: data parallelism over independent evaluations (the analog
  of Nomad's N-servers x M-workers horizontal scheduler parallelism,
  reference nomad/worker.go:386).
- ``nodes``: the cluster node axis sharded over ICI (the analog of the
  10k-node table that reference scheduler/feasible.go iterates; here a
  tensor axis split across the slice).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_EVALS = "evals"   # dp axis
AXIS_NODES = "nodes"   # sp/long-context axis


def make_mesh(
    n_devices: Optional[int] = None,
    evals_parallel: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a 2D (evals, nodes) mesh over the available devices.

    ``evals_parallel`` fixes the dp-axis size; by default it is 2 when
    the device count is even and >=4 (so both axes are exercised) and
    1 otherwise.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = n_devices if n_devices is not None else len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    devs = devs[:n]
    if evals_parallel is None:
        evals_parallel = 2 if (n % 2 == 0 and n >= 4) else 1
    if n % evals_parallel != 0:
        raise ValueError(f"{n} devices not divisible by evals axis {evals_parallel}")
    nodes_parallel = n // evals_parallel
    grid = np.asarray(devs).reshape(evals_parallel, nodes_parallel)
    return Mesh(grid, (AXIS_EVALS, AXIS_NODES))
