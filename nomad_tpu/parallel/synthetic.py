"""Synthetic placement problems for benchmarks, dryruns, and entry points.

Shapes mirror the reference benchmark grid (scheduler/benchmarks/
benchmarks_test.go:71-124): mock-node clusters (4000 MHz / 8192 MB,
mock.go defaults) with rack attributes for spread stanzas, and service
asks of 500 MHz / 256 MB (mock.Job defaults).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from nomad_tpu.ops.kernel import KernelIn, build_kernel_in
from nomad_tpu.tensors.schema import (
    MAX_DEV_REQS,
    PORT_WORDS,
    SPREAD_BUCKETS,
    AskTensor,
    ClusterTensors,
    EvalTensors,
    SpreadTensor,
    pad_bucket,
)


def synthetic_cluster(
    n_nodes: int,
    cpu: float = 4000.0,
    mem: float = 8192.0,
    disk: float = 100 * 1024.0,
    seed: int = 0,
    n_pad: Optional[int] = None,
    n_racks: int = 50,
) -> ClusterTensors:
    """Node planes without the structs round-trip (bench fast path).

    ``n_pad`` overrides the power-of-two bucket when the node axis must
    divide a non-power-of-two mesh axis (e.g. a 6-device slice).
    """
    rng = np.random.default_rng(seed)
    npad = n_pad if n_pad is not None else pad_bucket(n_nodes)
    if npad < n_nodes:
        raise ValueError(f"n_pad {npad} < n_nodes {n_nodes}")
    ready = np.zeros(npad, bool)
    ready[:n_nodes] = True
    cap_cpu = np.zeros(npad, np.float32)
    cap_mem = np.zeros(npad, np.float32)
    cap_disk = np.zeros(npad, np.float32)
    cap_cpu[:n_nodes] = cpu
    cap_mem[:n_nodes] = mem
    cap_disk[:n_nodes] = disk
    free_cores = np.zeros(npad, np.int32)
    free_cores[:n_nodes] = 4
    spc = np.zeros(npad, np.float32)
    spc[:n_nodes] = cpu / 4.0
    free_dyn = np.zeros(npad, np.int32)
    free_dyn[:n_nodes] = 12001
    ids = [f"node-{i:06d}" for i in range(n_nodes)]
    racks = rng.integers(0, n_racks, size=n_nodes)
    return ClusterTensors(
        n_real=n_nodes,
        n_pad=npad,
        node_ids=ids,
        index={nid: i for i, nid in enumerate(ids)},
        cap_cpu=cap_cpu,
        cap_mem=cap_mem,
        cap_disk=cap_disk,
        ready=ready,
        port_words=np.zeros((npad, PORT_WORDS), np.uint32),
        free_dyn=free_dyn,
        free_cores=free_cores,
        shares_per_core=spc,
        datacenters=[f"dc{r % 3}" for r in racks],
        node_classes=[""] * n_nodes,
        computed_classes=[f"rack-{r}" for r in racks],
        node_pools=["default"] * n_nodes,
    )


def synthetic_eval(
    cluster: ClusterTensors,
    ask_cpu: float = 500.0,
    ask_mem: float = 256.0,
    ask_disk: float = 150.0,
    desired_count: int = 10,
    with_spread: bool = False,
    used_frac: float = 0.0,
    seed: int = 0,
) -> EvalTensors:
    """One task group's eval planes over ``cluster``.

    ``used_frac`` pre-loads utilization (a partially packed cluster);
    ``with_spread`` adds one even-spread stanza over the rack attribute
    (the reference bench's spread configuration).
    """
    rng = np.random.default_rng(seed + 1)
    n = cluster.n_pad
    ask = AskTensor(
        cpu=ask_cpu,
        mem=ask_mem,
        disk=ask_disk,
        cores=0,
        n_dyn_ports=0,
        reserved_ports=[],
        port_mask=np.zeros(PORT_WORDS, np.uint32),
        n_dev_reqs=0,
        dev_counts=np.zeros(MAX_DEV_REQS, np.int32),
        total_mbits=0,
    )
    used_cpu = np.zeros(n, np.float32)
    used_mem = np.zeros(n, np.float32)
    if used_frac > 0.0:
        used_cpu[: cluster.n_real] = (
            cluster.cap_cpu[: cluster.n_real]
            * rng.uniform(0, used_frac, cluster.n_real)
        ).astype(np.float32)
        used_mem[: cluster.n_real] = (
            cluster.cap_mem[: cluster.n_real]
            * rng.uniform(0, used_frac, cluster.n_real)
        ).astype(np.float32)

    spreads: List[SpreadTensor] = []
    if with_spread:
        bucket_id = np.full(n, -1, np.int32)
        for i in range(cluster.n_real):
            rack = int(cluster.computed_classes[i].split("-")[1])
            bucket_id[i] = rack % SPREAD_BUCKETS
        spreads.append(
            SpreadTensor(
                bucket_id=bucket_id,
                counts=np.zeros(SPREAD_BUCKETS, np.float32),
                desired=np.full(SPREAD_BUCKETS, -1.0, np.float32),
                weight_frac=1.0,
                even=True,
            )
        )

    return EvalTensors(
        base_mask=cluster.ready.copy(),
        used_cpu=used_cpu,
        used_mem=used_mem,
        used_disk=np.zeros(n, np.float32),
        used_mbits=np.zeros(n, np.int32),
        avail_mbits=np.full(n, 1000, np.int32),
        used_cores=np.zeros(n, np.int32),
        port_conflict_words=np.zeros((n, PORT_WORDS), np.uint32),
        free_dyn_delta=np.zeros(n, np.int32),
        dev_free=np.zeros((n, MAX_DEV_REQS), np.float32),
        dev_aff_score=np.zeros(n, np.float32),
        has_dev_affinity=False,
        job_tg_count=np.zeros(n, np.int32),
        job_any_count=np.zeros(n, np.int32),
        distinct_hosts_job=False,
        distinct_hosts_tg=False,
        penalty=np.zeros(n, bool),
        aff_score=np.zeros(n, np.float32),
        has_affinities=False,
        spreads=spreads,
        ask=ask,
        desired_count=desired_count,
        algorithm="binpack",
    )


def synthetic_kernel_in(
    n_nodes: int = 300,
    n_steps: int = 16,
    with_spread: bool = False,
    used_frac: float = 0.5,
    seed: int = 0,
    n_pad: Optional[int] = None,
) -> KernelIn:
    cluster = synthetic_cluster(n_nodes, seed=seed, n_pad=n_pad)
    ev = synthetic_eval(
        cluster, with_spread=with_spread, used_frac=used_frac, seed=seed
    )
    return build_kernel_in(cluster, ev, n_steps)
