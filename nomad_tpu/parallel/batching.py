"""Eval batching: many evaluations, one kernel launch.

This is the TPU-idiomatic throughput path (SURVEY.md section 7 step 5):
the broker groups compatible evaluations — same cluster snapshot, same
padded node bucket — and launches them as one batched kernel call. The
cluster's node planes stay device-resident between launches; only the
per-eval planes (utilization deltas, eligibility masks, ask scalars)
cross PCIe per batch, which is what amortizes dispatch overhead over
the reference's one-eval-at-a-time worker loop (nomad/worker.go:386).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from nomad_tpu.ops.kernel import (
    FULL_FEATURES,
    KernelFeatures,
    KernelIn,
    place_taskgroup,
    place_taskgroup_topk,
)


def device_put_shared(kin: KernelIn) -> KernelIn:
    """Stage the shared planes on device once."""
    return jax.tree_util.tree_map(jnp.asarray, kin)


@functools.lru_cache(maxsize=32)
def make_schedule_apply_step(k_steps: int, features: KernelFeatures = FULL_FEATURES):
    """Fused batch-schedule + plan-apply with device-resident state.

    The TPU-native steady-state loop: the cluster's utilization planes
    live on device and are the carry; a batch of B evaluations is
    scheduled against that snapshot (optimistic concurrency — evals in
    a batch do not see each other's placements, exactly like reference
    workers scheduling against a shared SnapshotMinIndex snapshot,
    nomad/worker.go:537), then every accepted placement is committed as
    a scatter-add delta (the plan applier's state update,
    nomad/plan_apply.go:209, as on-device algebra). Per-batch host
    traffic is just ask scalars and the result rows.

    Returns fn(shared, used_cpu, used_mem, ask_cpu[B], ask_mem[B],
    n_steps[B]) -> (KernelOut[B], used_cpu', used_mem').
    """

    def step(shared: KernelIn, used_cpu, used_mem, ask_cpu, ask_mem, n_steps):
        def run_one(a_cpu, a_mem, ns):
            kin = shared._replace(
                used_cpu=used_cpu,
                used_mem=used_mem,
                ask_cpu=a_cpu,
                ask_mem=a_mem,
                n_steps=ns,
            )
            return place_taskgroup(kin, k_steps, features)

        out = jax.vmap(run_one)(ask_cpu, ask_mem, n_steps)
        used_cpu2, used_mem2 = commit_placements(
            used_cpu, used_mem, out.chosen, out.found, ask_cpu, ask_mem)
        return out, used_cpu2, used_mem2

    return jax.jit(step, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=32)
def make_schedule_apply_loop(k_steps: int,
                             features: KernelFeatures = FULL_FEATURES,
                             topk: bool = False,
                             backend: str = "xla",
                             interpret: bool = False,
                             reset_every: int = 0):
    """Multi-batch fused loop: T batches of B evals in ONE device call.

    ``lax.scan`` over the batch axis keeps the utilization planes in
    the carry, so a whole measurement burst (or a steady-state window
    of the live system) is a single dispatch — on a remote-device
    transport, per-dispatch round trips otherwise dominate and measure
    the link instead of the scheduler (the round-1 grid pathology).

    ``backend``: "xla" uses the vmapped XLA kernels (full-width, or
    candidate-set when ``topk``); "pallas_topk" uses the fused pallas
    candidate scan (ops/pallas_kernel.pallas_topk_place_batch) — the
    full-width pass and approx_max_k stay XLA, the K-step deduction
    scan runs as one pallas program instead of ~30 XLA ops per step.

    ``reset_every``: restore the INITIAL utilization planes every that
    many batches (0 = never) — the native baseline's periodic reset
    (bench/baseline_binpack.cc), so a long measurement burst schedules
    against the persisted cluster state instead of saturating it.

    Returns fn(shared, used_cpu, used_mem, ask_cpu[T,B], ask_mem[T,B],
    n_steps[B]) -> (score_sum, placed, invalid, used_cpu', used_mem').
    ``invalid`` counts evals whose candidate-set bound broke (always 0
    without ``topk``); the caller reschedules those via the full path.
    """
    def with_reset(one_batch):
        if not reset_every:
            return lambda carry, asks, uc0, um0: one_batch(
                carry[:2], asks)

        def wrapped(carry, asks, uc0, um0):
            uc, um, t = carry
            hit = (t % reset_every) == 0
            uc = jnp.where(hit, uc0, uc)
            um = jnp.where(hit, um0, um)
            (uc2, um2), stats = one_batch((uc, um), asks)
            return (uc2, um2, t + 1), stats

        return wrapped

    def scan_loop(one_batch, used_cpu, used_mem, ask_cpu, ask_mem):
        body = with_reset(one_batch)
        if reset_every:
            # reset needs the pristine planes as scan constants; the
            # carry planes are donated working copies
            uc0 = used_cpu + 0.0
            um0 = used_mem + 0.0
            init = (used_cpu, used_mem, jnp.asarray(0, jnp.int32))
            (uc, um, _), stats = jax.lax.scan(
                lambda c, a: body(c, a, uc0, um0),
                init, (ask_cpu, ask_mem))
        else:
            (uc, um), stats = jax.lax.scan(
                lambda c, a: body(c, a, None, None),
                (used_cpu, used_mem), (ask_cpu, ask_mem))
        scores, placed, invalid = stats
        return (jnp.sum(scores), jnp.sum(placed), jnp.sum(invalid),
                uc, um)

    if backend == "pallas_topk":
        from nomad_tpu.ops.pallas_kernel import pallas_topk_place_batch

        def loop(shared: KernelIn, used_cpu, used_mem,
                 ask_cpu, ask_mem, n_steps):
            def one_batch(carry, asks):
                uc, um = carry
                a_cpu, a_mem = asks
                chosen, scores, found, valid = pallas_topk_place_batch(
                    shared.cap_cpu, shared.cap_mem, shared.cap_disk,
                    uc, um, shared.used_disk,
                    shared.base_mask, shared.job_tg_count,
                    shared.penalty, shared.aff_score,
                    a_cpu, a_mem, shared.ask_disk,
                    n_steps, shared.desired_count,
                    shared.algorithm_spread,
                    k_steps=k_steps, interpret=interpret,
                )
                found = found & valid[:, None]
                uc2, um2 = commit_placements(
                    uc, um, chosen, found, a_cpu, a_mem)
                stats = (
                    jnp.sum(jnp.where(found, scores, 0.0)),
                    jnp.sum(found),
                    jnp.sum(~valid),
                )
                return (uc2, um2), stats

            return scan_loop(one_batch, used_cpu, used_mem,
                             ask_cpu, ask_mem)

        return jax.jit(loop, donate_argnums=(1, 2))

    def loop(shared: KernelIn, used_cpu, used_mem, ask_cpu, ask_mem, n_steps):
        def one_batch(carry, asks):
            uc, um = carry
            a_cpu, a_mem = asks

            def run_one(ac, am, ns):
                kin = shared._replace(
                    used_cpu=uc, used_mem=um,
                    ask_cpu=ac, ask_mem=am, n_steps=ns,
                )
                if topk:
                    out, ok = place_taskgroup_topk(kin, k_steps, features)
                    return out, ok
                return place_taskgroup(kin, k_steps, features), jnp.asarray(True)

            out, ok = jax.vmap(run_one)(a_cpu, a_mem, n_steps)
            # invalid evals (bound breach) are fully excluded: their
            # placements neither commit nor count — the caller re-runs
            # them via the full-width path
            found = out.found & ok[:, None]
            uc2, um2 = commit_placements(
                uc, um, out.chosen, found, a_cpu, a_mem)
            stats = (
                jnp.sum(jnp.where(found, out.scores, 0.0)),
                jnp.sum(found),
                jnp.sum(~ok),
            )
            return (uc2, um2), stats

        return scan_loop(one_batch, used_cpu, used_mem, ask_cpu, ask_mem)

    return jax.jit(loop, donate_argnums=(1, 2))


def commit_placements(used_cpu, used_mem, chosen, found, ask_cpu, ask_mem):
    """The plan applier's state update as on-device algebra
    (nomad/plan_apply.go:209): scatter every accepted placement's ask
    into the cluster utilization planes. Shared by the XLA and pallas
    step builders. ``chosen`` i32[B,K] node rows, ``found`` bool[B,K]."""
    rows = chosen.reshape(-1)                           # i32[B*K]
    ok = found.reshape(-1)
    w_cpu = (jnp.broadcast_to(ask_cpu[:, None], chosen.shape)
             .reshape(-1) * ok)
    w_mem = (jnp.broadcast_to(ask_mem[:, None], chosen.shape)
             .reshape(-1) * ok)
    safe = jnp.where(ok, rows, 0)
    used_cpu2 = used_cpu.at[safe].add(jnp.where(ok, w_cpu, 0.0))
    used_mem2 = used_mem.at[safe].add(jnp.where(ok, w_mem, 0.0))
    return used_cpu2, used_mem2
