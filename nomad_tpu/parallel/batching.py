"""Eval batching: many evaluations, one kernel launch.

This is the TPU-idiomatic throughput path (SURVEY.md section 7 step 5):
the broker groups compatible evaluations — same cluster snapshot, same
padded node bucket — and launches them as one batched kernel call. The
cluster's node planes stay device-resident between launches; only the
per-eval planes (utilization deltas, eligibility masks, ask scalars)
cross PCIe per batch, which is what amortizes dispatch overhead over
the reference's one-eval-at-a-time worker loop (nomad/worker.go:386).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nomad_tpu.ops.kernel import FULL_FEATURES, KernelFeatures, KernelIn, place_taskgroup


def device_put_shared(kin: KernelIn) -> KernelIn:
    """Stage the shared planes on device once."""
    return jax.tree_util.tree_map(jnp.asarray, kin)


def make_schedule_apply_step(k_steps: int, features: KernelFeatures = FULL_FEATURES):
    """Fused batch-schedule + plan-apply with device-resident state.

    The TPU-native steady-state loop: the cluster's utilization planes
    live on device and are the carry; a batch of B evaluations is
    scheduled against that snapshot (optimistic concurrency — evals in
    a batch do not see each other's placements, exactly like reference
    workers scheduling against a shared SnapshotMinIndex snapshot,
    nomad/worker.go:537), then every accepted placement is committed as
    a scatter-add delta (the plan applier's state update,
    nomad/plan_apply.go:209, as on-device algebra). Per-batch host
    traffic is just ask scalars and the result rows.

    Returns fn(shared, used_cpu, used_mem, ask_cpu[B], ask_mem[B],
    n_steps[B]) -> (KernelOut[B], used_cpu', used_mem').
    """

    def step(shared: KernelIn, used_cpu, used_mem, ask_cpu, ask_mem, n_steps):
        def run_one(a_cpu, a_mem, ns):
            kin = shared._replace(
                used_cpu=used_cpu,
                used_mem=used_mem,
                ask_cpu=a_cpu,
                ask_mem=a_mem,
                n_steps=ns,
            )
            return place_taskgroup(kin, k_steps, features)

        out = jax.vmap(run_one)(ask_cpu, ask_mem, n_steps)
        used_cpu2, used_mem2 = commit_placements(
            used_cpu, used_mem, out, ask_cpu, ask_mem)
        return out, used_cpu2, used_mem2

    return jax.jit(step, donate_argnums=(1, 2))


def commit_placements(used_cpu, used_mem, out, ask_cpu, ask_mem):
    """The plan applier's state update as on-device algebra
    (nomad/plan_apply.go:209): scatter every accepted placement's ask
    into the cluster utilization planes. Shared by the XLA and pallas
    step builders."""
    rows = out.chosen.reshape(-1)                       # i32[B*K]
    ok = out.found.reshape(-1)
    w_cpu = (jnp.broadcast_to(ask_cpu[:, None], out.chosen.shape)
             .reshape(-1) * ok)
    w_mem = (jnp.broadcast_to(ask_mem[:, None], out.chosen.shape)
             .reshape(-1) * ok)
    safe = jnp.where(ok, rows, 0)
    used_cpu2 = used_cpu.at[safe].add(jnp.where(ok, w_cpu, 0.0))
    used_mem2 = used_mem.at[safe].add(jnp.where(ok, w_mem, 0.0))
    return used_cpu2, used_mem2
