"""Eval batching: many evaluations, one kernel launch.

This is the TPU-idiomatic throughput path (SURVEY.md section 7 step 5):
the broker groups compatible evaluations — same cluster snapshot, same
padded node bucket — and launches them as one batched kernel call. The
cluster's node planes stay device-resident between launches; only the
per-eval planes (utilization deltas, eligibility masks, ask scalars)
cross PCIe per batch, which is what amortizes dispatch overhead over
the reference's one-eval-at-a-time worker loop (nomad/worker.go:386).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from nomad_tpu.ops.kernel import (
    FULL_FEATURES,
    KernelFeatures,
    KernelIn,
    place_taskgroup,
    place_taskgroup_topk,
)


def device_put_shared(kin: KernelIn) -> KernelIn:
    """Stage the shared planes on device once."""
    return jax.tree_util.tree_map(jnp.asarray, kin)


def _jit_donating(fn, donate_argnums):
    """``jax.jit`` with donation, taking OWNERSHIP of the donated args.

    ``jnp.asarray(numpy_plane)`` is zero-copy on the CPU backend when
    the allocator happens to hand back an aligned block — the device
    buffer then ALIASES memory the caller still owns. Donating such a
    buffer lets the runtime write the loop's carry in place into the
    caller's numpy array (observed through the pallas interpret path:
    the 1-in-5 ``test_pallas_kernel`` top-k parity flake — the first
    loop call silently rewrote the test's ``used`` planes before the
    second backend ran). Aliasing is undetectable from the array, so
    every donated arg is copied into a buffer this wrapper owns; the
    copy is O(plane) once per loop call, noise against the T-batch scan
    it feeds, and donation still aliases the carry inside the loop.
    """
    if not donate_argnums:
        return jax.jit(fn)
    jitted = jax.jit(fn, donate_argnums=donate_argnums)
    donated = frozenset(donate_argnums)

    @functools.wraps(fn)
    def call(*args, **kwargs):
        args = tuple(
            jnp.array(a, copy=True) if i in donated else a
            for i, a in enumerate(args)
        )
        return jitted(*args, **kwargs)

    return call


def _bound_fallback(valid, primary, full_thunk):
    """Candidate-set bound contract: evals whose bound broke are served
    by the full-width kernel INSIDE the loop. Batch-level ``lax.cond``:
    a batch with no breach pays nothing; a breached batch computes the
    full-width results once and each eval keeps whichever is exact for
    it. ``primary``/``full_thunk()`` are matching pytrees with leading
    batch axis; ``valid`` is bool[B]."""
    def merge(_):
        full = full_thunk()
        return jax.tree_util.tree_map(
            lambda t, f: jnp.where(
                valid.reshape((-1,) + (1,) * (t.ndim - 1)), t, f),
            primary, full)

    return jax.lax.cond(jnp.all(valid), lambda _: primary, merge, None)


@functools.lru_cache(maxsize=32)
def make_schedule_apply_step(k_steps: int, features: KernelFeatures = FULL_FEATURES):
    """Fused batch-schedule + plan-apply with device-resident state.

    The TPU-native steady-state loop: the cluster's utilization planes
    live on device and are the carry; a batch of B evaluations is
    scheduled against that snapshot (optimistic concurrency — evals in
    a batch do not see each other's placements, exactly like reference
    workers scheduling against a shared SnapshotMinIndex snapshot,
    nomad/worker.go:537), then every accepted placement is committed as
    a scatter-add delta (the plan applier's state update,
    nomad/plan_apply.go:209, as on-device algebra). Per-batch host
    traffic is just ask scalars and the result rows.

    Returns fn(shared, used_cpu, used_mem, ask_cpu[B], ask_mem[B],
    n_steps[B]) -> (KernelOut[B], used_cpu', used_mem').
    """

    def step(shared: KernelIn, used_cpu, used_mem, ask_cpu, ask_mem, n_steps):
        def run_one(a_cpu, a_mem, ns):
            kin = shared._replace(
                used_cpu=used_cpu,
                used_mem=used_mem,
                ask_cpu=a_cpu,
                ask_mem=a_mem,
                n_steps=ns,
            )
            return place_taskgroup(kin, k_steps, features)

        out = jax.vmap(run_one)(ask_cpu, ask_mem, n_steps)
        used_cpu2, used_mem2 = commit_placements(
            used_cpu, used_mem, out.chosen, out.found, ask_cpu, ask_mem)
        return out, used_cpu2, used_mem2

    return _jit_donating(step, (1, 2))


@functools.lru_cache(maxsize=32)
def make_schedule_apply_loop(k_steps: int,
                             features: KernelFeatures = FULL_FEATURES,
                             topk: bool = False,
                             backend: str = "xla",
                             interpret: bool = False,
                             reset_every: int = 0):
    """Multi-batch fused loop: T batches of B evals in ONE device call.

    ``lax.scan`` over the batch axis keeps the utilization planes in
    the carry, so a whole measurement burst (or a steady-state window
    of the live system) is a single dispatch — on a remote-device
    transport, per-dispatch round trips otherwise dominate and measure
    the link instead of the scheduler (the round-1 grid pathology).

    ``backend``: "xla" uses the vmapped XLA kernels (full-width, or
    candidate-set when ``topk``); "pallas_topk" uses the fused pallas
    candidate scan (ops/pallas_kernel.pallas_topk_place_batch) — the
    full-width pass and approx_max_k stay XLA, the K-step deduction
    scan runs as one pallas program instead of ~30 XLA ops per step.

    ``reset_every``: restore the INITIAL utilization planes every that
    many batches (0 = never) — the native baseline's periodic reset
    (bench/baseline_binpack.cc), so a long measurement burst schedules
    against the persisted cluster state instead of saturating it.

    Returns fn(shared, used_cpu, used_mem, ask_cpu[T,B], ask_mem[T,B],
    n_steps[B]) -> (score_sum, placed, fallback, used_cpu', used_mem').
    ``fallback`` counts evals whose candidate-set bound broke and were
    therefore served by the full-width kernel INSIDE the loop (a
    batch-level ``lax.cond``: a batch with no breach pays nothing, a
    batch with one re-runs full-width and merges per eval) — always 0
    without ``topk``, and no eval is ever dropped: committed totals
    are exact for every ask.
    """
    def with_reset(one_batch):
        if not reset_every:
            return lambda carry, asks, uc0, um0: one_batch(
                carry[:2], asks)

        def wrapped(carry, asks, uc0, um0):
            uc, um, t = carry
            hit = (t % reset_every) == 0
            uc = jnp.where(hit, uc0, uc)
            um = jnp.where(hit, um0, um)
            (uc2, um2), stats = one_batch((uc, um), asks)
            return (uc2, um2, t + 1), stats

        return wrapped

    def scan_loop(one_batch, used_cpu, used_mem, ask_cpu, ask_mem):
        body = with_reset(one_batch)
        if reset_every:
            # reset needs the pristine planes as scan constants; the
            # carry planes are donated working copies
            uc0 = used_cpu + 0.0
            um0 = used_mem + 0.0
            init = (used_cpu, used_mem, jnp.asarray(0, jnp.int32))
            (uc, um, _), stats = jax.lax.scan(
                lambda c, a: body(c, a, uc0, um0),
                init, (ask_cpu, ask_mem))
        else:
            (uc, um), stats = jax.lax.scan(
                lambda c, a: body(c, a, None, None),
                (used_cpu, used_mem), (ask_cpu, ask_mem))
        scores, placed, invalid = stats
        return (jnp.sum(scores), jnp.sum(placed), jnp.sum(invalid),
                uc, um)

    # donation is only usable when the donated planes' buffers can
    # alias the returned carry. With ``reset_every`` the body swaps the
    # carry for the pristine copies (``p + 0``) on the very first
    # batch, so the ORIGINAL donated buffers never reach an output and
    # device backends warn "Some donated buffers were not usable"
    # (promoted to an error in tests) — donate nothing then.
    donate = () if reset_every else (1, 2)

    if backend == "pallas_topk":
        from nomad_tpu.ops.pallas_kernel import pallas_topk_place_batch

        def loop(shared: KernelIn, used_cpu, used_mem,
                 ask_cpu, ask_mem, n_steps):
            def one_batch(carry, asks):
                uc, um = carry
                a_cpu, a_mem = asks
                chosen, scores, found, valid = pallas_topk_place_batch(
                    shared.cap_cpu, shared.cap_mem, shared.cap_disk,
                    uc, um, shared.used_disk,
                    shared.base_mask, shared.job_tg_count,
                    shared.penalty, shared.aff_score,
                    a_cpu, a_mem, shared.ask_disk,
                    n_steps, shared.desired_count,
                    shared.algorithm_spread,
                    k_steps=k_steps, interpret=interpret,
                )
                def run_full(ac, am, ns):
                    kin = shared._replace(
                        used_cpu=uc, used_mem=um,
                        ask_cpu=ac, ask_mem=am, n_steps=ns,
                    )
                    out = place_taskgroup(kin, k_steps, features)
                    return (out.chosen, out.scores, out.found)

                chosen, scores, found = _bound_fallback(
                    valid, (chosen, scores, found),
                    lambda: jax.vmap(run_full)(a_cpu, a_mem, n_steps))
                uc2, um2 = commit_placements(
                    uc, um, chosen, found, a_cpu, a_mem)
                stats = (
                    jnp.sum(jnp.where(found, scores, 0.0)),
                    jnp.sum(found),
                    jnp.sum(~valid),
                )
                return (uc2, um2), stats

            return scan_loop(one_batch, used_cpu, used_mem,
                             ask_cpu, ask_mem)

        return _jit_donating(loop, donate)

    def loop(shared: KernelIn, used_cpu, used_mem, ask_cpu, ask_mem, n_steps):
        def one_batch(carry, asks):
            uc, um = carry
            a_cpu, a_mem = asks

            def run_one(ac, am, ns):
                kin = shared._replace(
                    used_cpu=uc, used_mem=um,
                    ask_cpu=ac, ask_mem=am, n_steps=ns,
                )
                if topk:
                    out, ok = place_taskgroup_topk(kin, k_steps, features)
                    return out, ok
                return place_taskgroup(kin, k_steps, features), jnp.asarray(True)

            out, ok = jax.vmap(run_one)(a_cpu, a_mem, n_steps)
            if topk:
                def run_full(ac, am, ns):
                    kin = shared._replace(
                        used_cpu=uc, used_mem=um,
                        ask_cpu=ac, ask_mem=am, n_steps=ns,
                    )
                    return place_taskgroup(kin, k_steps, features)

                out = _bound_fallback(
                    ok, out,
                    lambda: jax.vmap(run_full)(a_cpu, a_mem, n_steps))
            uc2, um2 = commit_placements(
                uc, um, out.chosen, out.found, a_cpu, a_mem)
            stats = (
                jnp.sum(jnp.where(out.found, out.scores, 0.0)),
                jnp.sum(out.found),
                jnp.sum(~ok),
            )
            return (uc2, um2), stats

        return scan_loop(one_batch, used_cpu, used_mem, ask_cpu, ask_mem)

    return _jit_donating(loop, donate)


def _scan_with_reset(one_batch, planes, asks, reset_every: int):
    """Shared multi-batch scan harness for the timed cell loops:
    ``planes`` is the carried plane tuple, ``asks`` the tuple of
    [T, ...] per-batch inputs. With ``reset_every``, the pristine
    planes re-enter the carry every that many batches (the replay
    benches' baseline-matching reset cadence)."""
    if reset_every:
        init_planes = tuple(p + 0 for p in planes)

        def body(carry, a):
            *ps, t = carry
            hit = (t % reset_every) == 0
            ps = tuple(jnp.where(hit, i, p)
                       for p, i in zip(ps, init_planes))
            ps2, stats = one_batch(tuple(ps), a)
            return (*ps2, t + 1), stats

        (*out, _t), stats = jax.lax.scan(
            body, (*planes, jnp.asarray(0, jnp.int32)), asks)
        return tuple(out), stats
    out, stats = jax.lax.scan(one_batch, planes, asks)
    return tuple(out), stats


@functools.lru_cache(maxsize=8)
def make_device_apply_loop(k_steps: int, reset_every: int = 0):
    """Timed GPU-device cell: BASELINE.md's "GPU device-plugin jobs on
    a heterogeneous pool" config as a fused multi-batch loop.

    Same shape as ``make_schedule_apply_loop`` but the carry includes
    the per-node free-device plane (``dev_free``): the kernel deducts
    device asks between its K steps (rank.go AssignDevice semantics,
    device.go:32) and accepted placements commit their device ask
    across batches with the same scatter algebra as cpu/mem.

    Returns fn(shared, used_cpu, used_mem, dev_free, ask_cpu[T,B],
    ask_mem[T,B], ask_gpu[T,B], n_steps[B]) ->
    (score_sum, placed, used_cpu', used_mem', dev_free').
    """
    from nomad_tpu.ops.kernel import MAX_DEV_REQS

    features = KernelFeatures(
        n_spreads=0, with_topk=False, with_devices=True,
        with_ports=False, with_cores=False, with_network=False,
        with_distinct=False, with_step_penalties=False,
        with_preferred=False,
    )

    def loop(shared: KernelIn, used_cpu, used_mem, dev_free,
             ask_cpu, ask_mem, ask_gpu, n_steps):
        def one_batch(carry, asks):
            uc, um, df = carry
            a_cpu, a_mem, a_gpu = asks

            def run_one(ac, am, ag, ns):
                ad = jnp.zeros((MAX_DEV_REQS,), jnp.float32).at[0].set(ag)
                kin = shared._replace(
                    used_cpu=uc, used_mem=um, dev_free=df,
                    ask_cpu=ac, ask_mem=am, ask_dev=ad, n_steps=ns,
                )
                return place_taskgroup(kin, k_steps, features)

            out = jax.vmap(run_one)(a_cpu, a_mem, a_gpu, n_steps)
            uc2, um2 = commit_placements(
                uc, um, out.chosen, out.found, a_cpu, a_mem)
            rows = out.chosen.reshape(-1)
            ok = out.found.reshape(-1)
            w_gpu = (jnp.broadcast_to(a_gpu[:, None], out.chosen.shape)
                     .reshape(-1) * ok)
            safe = jnp.where(ok, rows, 0)
            df2 = df.at[safe, 0].add(-jnp.where(ok, w_gpu, 0.0))
            stats = (
                jnp.sum(jnp.where(out.found, out.scores, 0.0)),
                jnp.sum(out.found),
            )
            return (uc2, um2, df2), stats

        (uc, um, df), stats = _scan_with_reset(
            one_batch, (used_cpu, used_mem, dev_free),
            (ask_cpu, ask_mem, ask_gpu), reset_every)
        scores, placed = stats
        return jnp.sum(scores), jnp.sum(placed), uc, um, df

    # with reset_every, _scan_with_reset consumes COPIES of the planes
    # (``p + 0``) and the originals never reach an output — donation
    # would be unusable (device backends warn; tests error). Donate
    # only in the no-reset steady loop, where carry in aliases carry
    # out (BENCH_r05's "donated buffers were not usable" tail came
    # from exactly this misalignment).
    return _jit_donating(loop, () if reset_every else (1, 2, 3))


@functools.lru_cache(maxsize=8)
def make_preemption_apply_loop(k_steps: int, reset_every: int = 0):
    """Timed preemption cell: BASELINE.md's "preemption-enabled service
    jobs at 10K nodes" config as a fused multi-batch loop.

    Each placement first tries a normal binpack fit; when NO node has
    free capacity, eligible nodes (those with preemptible lower-
    priority capacity, preemption.go:96 Preemptor eligibility) are
    scored ``(binpack_fit_after_evict + preemption_score) / 2`` — the
    exact device-wide scoring the live path's ``select_preempting``
    computes (scheduler/stack.py, mirroring rank.go:799
    PreemptionScoringIterator) — and the chosen node's preemptible
    capacity is freed (full-eviction upper bound; the live system's
    host-side greedy pass evicts a subset, never more).

    ``pre_cpu/pre_mem`` are per-node planes of capacity held by allocs
    whose priority is more than PRIORITY_DELTA below the placing job's
    (scheduler/preemption.preemptible_planes); ``pre_score`` is the
    net-priority-derived plane (rank.go:858 preemptionScore).

    Returns fn(shared, used_cpu, used_mem, pre_cpu, pre_mem, pre_score,
    ask_cpu[T,B], ask_mem[T,B], n_steps[B]) ->
    (score_sum, placed, preempted, used_cpu', used_mem').
    """
    from nomad_tpu.ops.kernel import NEG_INF

    def loop(shared: KernelIn, used_cpu, used_mem,
             pre_cpu, pre_mem, pre_score,
             ask_cpu, ask_mem, n_steps):
        def one_eval(uc, um, pc, pm, ps, a_cpu, a_mem, ns):
            """K sequential placements with deduction; preemption is
            the per-step fallback (generic_sched.go:800 second pass)."""
            def step(st, i):
                uc, um, pc, pm = st
                free_cpu = shared.cap_cpu - uc
                free_mem = shared.cap_mem - um
                normal = (shared.base_mask
                          & (free_cpu >= a_cpu) & (free_mem >= a_mem))
                # binpack fit (funcs.go:259), normalized like the kernel
                fc = jnp.where(shared.cap_cpu > 0,
                               1.0 - (uc + a_cpu) / shared.cap_cpu, 0.0)
                fm = jnp.where(shared.cap_mem > 0,
                               1.0 - (um + a_mem) / shared.cap_mem, 0.0)
                fit = jnp.clip(
                    20.0 - (jnp.power(10.0, fc) + jnp.power(10.0, fm)),
                    0.0, 18.0) / 18.0
                active = i < ns
                normal_masked = jnp.where(normal & active, fit, NEG_INF)
                best_n = jnp.argmax(normal_masked)
                found_n = normal_masked[best_n] > NEG_INF / 2

                # preemption fallback plane (stack.py select_preempting)
                evictable = (pc > 0) | (pm > 0)
                pre_ok = (shared.base_mask & evictable & ~normal
                          & ((free_cpu + pc) >= a_cpu)
                          & ((free_mem + pm) >= a_mem))
                uce = uc - pc + a_cpu
                ume = um - pm + a_mem
                fce = jnp.where(shared.cap_cpu > 0,
                                1.0 - uce / shared.cap_cpu, 0.0)
                fme = jnp.where(shared.cap_mem > 0,
                                1.0 - ume / shared.cap_mem, 0.0)
                fite = jnp.clip(
                    20.0 - (jnp.power(10.0, fce) + jnp.power(10.0, fme)),
                    0.0, 18.0) / 18.0
                pre_masked = jnp.where(
                    pre_ok & active, (fite + ps) / 2.0, NEG_INF)
                best_p = jnp.argmax(pre_masked)
                found_p = pre_masked[best_p] > NEG_INF / 2

                idx = jnp.where(found_n, best_n, best_p)
                found = found_n | found_p
                preempted = found_p & ~found_n
                score = jnp.where(
                    found_n, normal_masked[best_n],
                    jnp.where(found_p, pre_masked[best_p], 0.0))

                one = jax.nn.one_hot(
                    idx, shared.cap_cpu.shape[0], dtype=jnp.float32
                ) * found.astype(jnp.float32)
                evict = one * preempted.astype(jnp.float32)
                uc2 = uc + one * a_cpu - evict * pc[idx]
                um2 = um + one * a_mem - evict * pm[idx]
                pc2 = pc * (1.0 - evict)
                pm2 = pm * (1.0 - evict)
                return (uc2, um2, pc2, pm2), (score * found, found,
                                              preempted)

            (uc2, um2, pc2, pm2), (scores, found, preempted) = \
                jax.lax.scan(step, (uc, um, pc, pm),
                             jnp.arange(k_steps))
            return (jnp.sum(scores), jnp.sum(found), jnp.sum(preempted),
                    uc2, um2, pc2, pm2)

        def one_batch(carry, asks):
            uc, um, pc, pm = carry
            a_cpu, a_mem = asks
            # batch members schedule against the SAME snapshot
            # (optimistic concurrency, like the lean loop)
            score, placed, preempted, uc2, um2, pc2, pm2 = jax.vmap(
                one_eval, in_axes=(None, None, None, None, None, 0, 0, 0)
            )(uc, um, pc, pm, pre_score, a_cpu, a_mem, n_steps)
            # commit = sum of PLACEMENT adds, but each node's evicted
            # capacity is credited ONCE (two members evicting the same
            # node free it once, not twice). A member's placement adds
            # are its used delta plus whatever it evicted.
            add_uc = jnp.sum(uc2 - uc[None, :] + (pc[None, :] - pc2),
                             axis=0)
            add_um = jnp.sum(um2 - um[None, :] + (pm[None, :] - pm2),
                             axis=0)
            pc3 = jnp.min(pc2, axis=0)
            pm3 = jnp.min(pm2, axis=0)
            stats = (jnp.sum(score), jnp.sum(placed), jnp.sum(preempted))
            return (uc + add_uc - (pc - pc3),
                    um + add_um - (pm - pm3), pc3, pm3), stats

        (uc, um, _pc, _pm), stats = _scan_with_reset(
            one_batch, (used_cpu, used_mem, pre_cpu, pre_mem),
            (ask_cpu, ask_mem), reset_every)
        scores, placed, preempted = stats
        return (jnp.sum(scores), jnp.sum(placed), jnp.sum(preempted),
                uc, um)

    # donate ONLY used_cpu/used_mem: they alias the uc/um outputs.
    # pre_cpu/pre_mem never leave the loop, so donating them has no
    # output to alias — XLA warns "Some donated buffers were not
    # usable" and the donation buys nothing (the warning is promoted
    # to an error in tests so this cannot regress). With reset_every
    # even uc/um are unusable: _scan_with_reset hands the scan COPIES
    # (``p + 0``) and the donated originals never reach an output
    # (the BENCH_r05 device/preemption-path warning) — donate nothing.
    return _jit_donating(loop, () if reset_every else (1, 2))


def commit_placements(used_cpu, used_mem, chosen, found, ask_cpu, ask_mem):
    """The plan applier's state update as on-device algebra
    (nomad/plan_apply.go:209): scatter every accepted placement's ask
    into the cluster utilization planes. Shared by the XLA and pallas
    step builders. ``chosen`` i32[B,K] node rows, ``found`` bool[B,K]."""
    rows = chosen.reshape(-1)                           # i32[B*K]
    ok = found.reshape(-1)
    w_cpu = (jnp.broadcast_to(ask_cpu[:, None], chosen.shape)
             .reshape(-1) * ok)
    w_mem = (jnp.broadcast_to(ask_mem[:, None], chosen.shape)
             .reshape(-1) * ok)
    safe = jnp.where(ok, rows, 0)
    used_cpu2 = used_cpu.at[safe].add(jnp.where(ok, w_cpu, 0.0))
    used_mem2 = used_mem.at[safe].add(jnp.where(ok, w_mem, 0.0))
    return used_cpu2, used_mem2
