"""Cross-eval kernel-launch coalescing: one device launch per wave.

The live half of the eval-batching design (SURVEY.md section 7 step 5).
The broker hands a worker B compatible evaluations (`dequeue_batch`);
the worker runs each eval's scheduler on its own thread against one
shared snapshot (the reference's concurrency axis, nomad/worker.go:386,
collapsed into one process). Every scheduler still thinks it owns the
device: when it reaches a placement launch, the request parks here
instead of dispatching. Once every still-running eval of the batch is
parked (or finished), the wave fires as ONE ``jax.vmap``'d kernel call
and each thread resumes with its slice of the output.

Why this is exact: ``KernelIn`` always carries every plane —
``KernelFeatures`` only selects which planes the *compiled program
reads* (ops/kernel.py). A wave compiles the union of its members'
feature sets; members that didn't ask for a feature provide neutral
planes (zero asks, -1 ids, inactive stanzas), which the kernel defines
to be no-ops. So batching changes arithmetic batching only, never
placement semantics.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from nomad_tpu.ops.kernel import (
    TOPK,
    KernelFeatures,
    KernelIn,
    KernelOut,
    canonical_features,
    fused_wave_launch,
    fused_wave_supported,
    pad_steps,
    place_taskgroups_joint_jit,
    unpack_fused_wave,
)
from nomad_tpu.telemetry.histogram import histograms, percentile
from nomad_tpu.telemetry.kernel_profile import profiler
from nomad_tpu.telemetry.trace import tracer
from nomad_tpu.tensors.device_state import default_device_state
from nomad_tpu.utils.faultpoints import fault
from nomad_tpu.utils.wavecohort import wave_cohorts
from nomad_tpu.utils.witness import witness_lock

#: B is bucketed to limit recompiles. Coarse on purpose: every
#: (wave bucket, step bucket, features) combination is a separate XLA
#: compile, and a cold TPU compile is tens of seconds — paying a few
#: inert filler members per wave is far cheaper than another variant.
#: 32 earns its slot: it is the default worker batch size, and the
#: joint kernel's step scan is O(wave x steps) — padding 32 to 64
#: doubled the live path's per-wave device time for nothing.
_WAVE_BUCKETS = (1, 4, 16, 32, 64, 256)

#: When set (configure_wave_mesh), DIRECT launch_wave calls run the
#: joint program with the node axis sharded over this mesh's devices —
#: per-step argmax/top-k become ICI collectives (SURVEY.md section
#: 2.10). None = single-device dispatch. Results are identical either
#: way. Live servers do NOT use this global: each threads its OWN
#: ``Server.wave_mesh`` through its workers' coalescers, so
#: co-resident servers (with different meshes, or one opted out)
#: cannot affect each other.
_WAVE_MESH = None
#: sentinel: "caller did not choose" — fall back to the global; a
#: coalescer always chooses (its server's mesh, possibly None=unsharded)
_USE_GLOBAL = object()
#: waves dispatched through the sharded path (asserted by tests;
#: the richer accounting lives in ``sharded_wave_stats`` below)
sharded_wave_launches = 0


class _ShardedWaveStats:
    """Sharded-dispatch accounting (exported as the
    ``nomad_tpu_wave_sharded_*`` Prometheus series by
    telemetry/exporter.py; reset with telemetry.reset()).

    ``launches`` counts waves that ran the joint program with the node
    axis sharded over a mesh; ``fallbacks`` counts waves that HAD a
    mesh but dispatched single-device anyway (a node axis the device
    count does not divide) — on a healthy mesh server this must sit at
    ZERO, and the steady-burst gate holds it there. ``mesh_devices``
    is the device count of the newest sharded launch (0 = never
    sharded)."""

    def __init__(self) -> None:
        self._lock = witness_lock("ShardedWaveStats._lock")
        self.launches = 0
        self.fallbacks = 0
        self.mesh_devices = 0

    def note_launch(self, devices: int) -> None:
        with self._lock:
            self.launches += 1
            self.mesh_devices = devices

    def note_fallback(self, devices: int) -> None:
        with self._lock:
            self.fallbacks += 1
            self.mesh_devices = devices

    def reset(self) -> None:
        with self._lock:
            self.launches = 0
            self.fallbacks = 0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "launches": self.launches,
                "fallbacks": self.fallbacks,
                "mesh_devices": self.mesh_devices,
            }


#: process-wide sharded-wave stats (coalescers are per-chunk and too
#: short-lived to carry their own history, like wave_stats)
sharded_wave_stats = _ShardedWaveStats()

#: Fused-wave dispatch knob (ISSUE 19). Default ON: waves whose
#: feature union fits the fused envelope
#: (ops/kernel.fused_wave_supported) run the one-dispatch mega-kernel;
#: the rest take the composite path, counted as fallbacks below.
_FUSED_WAVE = True


def configure_fused_wave(on: bool) -> None:
    """Enable/disable the fused wave mega-kernel process-wide (the
    bench's composite arm and the A/B cell flip this)."""
    global _FUSED_WAVE
    _FUSED_WAVE = bool(on)


def fused_wave_enabled() -> bool:
    return _FUSED_WAVE


class _FusedWaveStats:
    """Fused-dispatch accounting (exported as the
    ``nomad_tpu_wave_fused_*`` Prometheus series; reset with
    telemetry.reset()).

    ``launches`` counts waves that ran the fused mega-kernel;
    ``fallbacks`` counts waves that wanted fusion (knob on) but ran
    the composite anyway — an unsupported feature union
    (spreads/devices/cores/network), a node shard too narrow for the
    local top-k merge, or a fused dispatch error. Steady live traffic
    fits the envelope, so the steady-burst gate holds fallbacks at
    ZERO."""

    def __init__(self) -> None:
        self._lock = witness_lock("FusedWaveStats._lock")
        self.launches = 0
        self.fallbacks = 0

    def note_launch(self) -> None:
        with self._lock:
            self.launches += 1

    def note_fallback(self) -> None:
        with self._lock:
            self.fallbacks += 1

    def reset(self) -> None:
        with self._lock:
            self.launches = 0
            self.fallbacks = 0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "launches": self.launches,
                "fallbacks": self.fallbacks,
            }


#: process-wide fused-wave stats (same lifetime rationale as above)
fused_wave_stats = _FusedWaveStats()

#: JointOut fields the launcher fetches to host EAGERLY per wave (the
#: wave-critical d2h payload): the per-step placements the scheduler
#: walks immediately plus the per-member metric scalars. The top-k
#: score planes — the bulk of the old payload, [T, TOPK] x 2 — stay ON
#: DEVICE as lazy slices (``_WaveTopK``): they feed only AllocMetric
#: score_meta, whose materialization is deferred onto the plan window
#: (scheduler/stack.py), so their d2h overlaps the next wave's execute
#: instead of riding the wave-critical path.
_JOINT_FETCH_FIELDS = (
    "chosen", "scores", "found",
    "nodes_evaluated", "nodes_feasible",
    "exhausted_cpu", "exhausted_mem", "exhausted_disk",
    "exhausted_ports", "exhausted_devices", "exhausted_cores",
)


class _WaveTopK:
    """One wave's top-k planes, resident on device until first use.

    All members share the holder; the first score_meta materialization
    (inside the batching worker's plan window) fetches BOTH planes with
    one transfer each and caches the host copy for every other member.
    Bytes are metered at fetch time like any other d2h.
    """

    __slots__ = ("_idx", "_scores", "_host", "_lock", "_fetching",
                 "_done")

    def __init__(self, idx_dev, scores_dev) -> None:
        self._idx = idx_dev
        self._scores = scores_dev
        self._host = None
        self._lock = witness_lock("WaveTopK._lock")
        self._fetching = False
        self._done = threading.Event()

    def host(self):
        # claim-then-fetch: the lock only arbitrates WHO fetches; the
        # d2h transfer itself runs unlocked (graftcheck R2 — a device
        # fetch under a lock stalls every other member's deferred
        # score_meta drain behind the PCIe transfer instead of letting
        # them park on the event). Losers wait on the claim's event
        # and read the cached host copy. Each claim gets a FRESH
        # event (captured under the lock): a failed fetch's set() then
        # cannot leave a stale-set event that would busy-spin waiters
        # through the retry claim's whole transfer.
        while True:
            with self._lock:
                if self._host is not None:
                    return self._host
                if not self._fetching:
                    self._fetching = True
                    done = self._done = threading.Event()
                    break
                done = self._done
            done.wait()
        try:
            # deferred-drain seam (chaos plane): the shared top-k fetch
            # runs in the plan window; a failure here hits whichever
            # member claimed the fetch — losers retry the claim (the
            # while-loop above) so one injected error never wedges the
            # whole wave's score_meta drain
            fault("wave.d2h.drain")
            idx = np.asarray(self._idx)
            scores = np.asarray(self._scores)
            profiler.add_bytes("d2h", idx.nbytes + scores.nbytes)
            # counted in the dispatch series but EXCLUDED from the
            # steady dispatches_per_wave key: the drain runs in the
            # plan window, overlapping the next wave's execute — it
            # is not on the wave-critical path the key measures
            profiler.count_dispatch("topk_drain")
            self._host = (idx, scores)
            # release the device buffers
            self._idx = self._scores = None
        finally:
            with self._lock:
                self._fetching = False
            done.set()
        return self._host


class _TopKSlice:
    """A member's lazy [k, TOPK] view of the wave's top-k plane.

    Quacks enough like an array for the scheduler's deferred
    score_meta fill: ``np.asarray`` (via ``__array__``) and row
    indexing both resolve through the shared wave fetch.
    """

    __slots__ = ("_wave", "_field", "_start", "_stop")

    def __init__(self, wave: _WaveTopK, field: int, start: int,
                 stop: int) -> None:
        self._wave = wave
        self._field = field          # 0 = idx, 1 = scores
        self._start = start
        self._stop = stop

    def _resolve(self):
        return self._wave.host()[self._field][self._start:self._stop]

    def __array__(self, dtype=None, copy=None):
        a = self._resolve()
        return a if dtype is None else a.astype(dtype)

    def __getitem__(self, item):
        return self._resolve()[item]

    def __len__(self) -> int:
        return self._stop - self._start

#: node planes shipped once per wave (unbatched) when every member
#: shares them by identity: the cluster-static planes plus the wave
#: snapshot's gathered utilization (stack.py wave-shared build)
_SHAREABLE_FIELDS = (
    "cap_cpu", "cap_mem", "cap_disk", "free_cores", "shares_per_core",
    "avail_mbits", "free_dyn",
    "used_cpu", "used_mem", "used_disk", "used_cores", "used_mbits",
)

#: second sharing group: the WIDE ask planes (devices, spreads,
#: reserved-port conflicts, per-step penalty/preference pins) that
#: stay NEUTRAL for the common ask are frozen singletons
#: (ops/kernel.neutral_planes), so members share them by identity too.
#: They fork only when a member actually asks for devices/spreads/
#: rescheduling — rare in steady traffic, and they are the BULKIEST
#: per-member planes ([N, MAX_DEV_REQS], [S, N]).
#:
#: ``node_perm`` is deliberately NOT here: the shuffle permutation is
#: seeded per eval, so with shuffling on it is never identity-shared —
#: keeping it in this group forced EVERY live multi-member wave onto
#: the all-stacked layout, shipping B copies of dev_free/spread/count
#: planes that were in fact neutral singletons (the bulk of PR 2's
#: 30% h2d share). It ships always-stacked instead (one [B, N] i32
#: plane), which keeps the layout-variant count bounded.
_NEUTRAL_SHAREABLE_FIELDS = (
    "port_conflict", "dev_free", "dev_aff_score",
    "step_penalty", "step_preferred",
    "spread_active", "spread_even", "spread_weight",
    "spread_bucket", "spread_counts", "spread_desired",
)

#: third sharing group: the JOB-LOCAL [N] planes. A follow-up eval of
#: a job with live allocations forks job_tg_count/job_any_count (and a
#: rescheduled one the penalty plane) — common in steady traffic — and
#: used to drag the whole neutral group onto the stacked layout,
#: uploading B copies of the wide device/spread planes for a handful
#: of dirty members. Splitting the job planes into their own group
#: bounds that wave's extra upload to 4 x [B, N] instead of ~1MB.
#: Three all-or-nothing groups -> at most EIGHT layout variants per
#: (bucket, step, features) triple, all enumerable by the AOT warmup
#: lattice.
#:
#: ``base_mask`` joined the group with the feasibility compiler
#: (nomad_tpu/feasibility/): evals with no dynamic feasibility state
#: carry the mask-program cache's FROZEN array — members of equal job
#: specs (and, via content dedup, of any specs whose masks come out
#: equal) share it by identity, so the wave ships ONE base-mask plane
#: and the device broadcasts it to every member: the whole wave's base
#: masks from one dispatch. The frozen array rides the device-resident
#: frozen registry (frozen_ok lookup below), uploading once per
#: (node structure, constraint tree) ever.
_JOB_SHAREABLE_FIELDS = (
    "job_tg_count", "job_any_count", "penalty", "aff_score",
    "base_mask",
)


def wave_field_is_shared(field: str, shared: bool,
                         neutral_shared: bool,
                         job_shared: bool = True) -> bool:
    """Whether a KernelIn field ships UNBATCHED under the given wave
    layout flags. The single source of truth for the three sharing
    groups — the live launcher (``launch_wave``) and the AOT warmup's
    dummy-wave builder (ops/warmup.py) must agree EXACTLY, or warmup
    compiles programs the live path never hits."""
    return (shared and field in _SHAREABLE_FIELDS) or (
        neutral_shared and field in _NEUTRAL_SHAREABLE_FIELDS) or (
        job_shared and field in _JOB_SHAREABLE_FIELDS)


def configure_wave_mesh(mesh) -> None:
    """Route DIRECT launch_wave calls over ``mesh`` (None restores
    single-device dispatch). Live servers ignore this: they pass their
    own ``Server.wave_mesh`` through their coalescers."""
    global _WAVE_MESH
    _WAVE_MESH = mesh


def pad_wave(b: int) -> int:
    for w in _WAVE_BUCKETS:
        if b <= w:
            return w
    return ((b + 255) // 256) * 256


def union_features(features: List[KernelFeatures]) -> KernelFeatures:
    """Smallest feature set that serves every member (see module doc),
    canonicalized (ops/kernel.canonical_features) so near-identical
    waves land on one compiled variant instead of forking the jit
    cache per incidental feature combination."""
    return canonical_features(KernelFeatures(
        n_spreads=max(f.n_spreads for f in features),
        with_topk=any(f.with_topk for f in features),
        with_devices=any(f.with_devices for f in features),
        with_ports=any(f.with_ports for f in features),
        with_cores=any(f.with_cores for f in features),
        with_network=any(f.with_network for f in features),
        with_distinct=any(f.with_distinct for f in features),
        with_step_penalties=any(f.with_step_penalties for f in features),
        with_preferred=any(f.with_preferred for f in features),
        with_shuffle=any(f.with_shuffle for f in features),
    ))


def _pad_kin_steps(kin: KernelIn, k_max: int) -> KernelIn:
    """Pad the per-step planes to the wave's step count (neutral rows)."""
    from nomad_tpu.ops.kernel import neutral_step_planes

    k = int(kin.step_penalty.shape[0])
    if k == k_max:
        return kin
    n_pen, n_pref = neutral_step_planes(k)
    if kin.step_penalty is n_pen and kin.step_preferred is n_pref:
        # neutral singletons pad to the neutral singleton of the wave's
        # step count — identity (and so wave sharing) survives padding
        pen, pref = neutral_step_planes(k_max)
        return kin._replace(step_penalty=pen, step_preferred=pref)
    pen = np.full((k_max, kin.step_penalty.shape[1]), -1, np.int32)
    pen[:k] = np.asarray(kin.step_penalty)
    pref = np.full(k_max, -1, np.int32)
    pref[:k] = np.asarray(kin.step_preferred)
    return kin._replace(step_penalty=pen, step_preferred=pref)


class WaveStats:
    """Process-wide wave-shape observability (exported as Prometheus
    gauges by telemetry/exporter.py; reset with telemetry.reset()).

    ``fill_ratio`` = real members / padded wave slots — low fill means
    the coalescer fires before waves fill (deadline pressure) or the
    broker hands out ragged batches. ``park_latency`` percentiles are
    the rendezvous cost an eval thread pays waiting for its wave; the
    adaptive deadline exists to bound exactly this number."""

    def __init__(self) -> None:
        self._lock = witness_lock("WaveStats._lock")
        self.requests = 0
        self.launches = 0
        self.full_launches = 0
        self.deadline_launches = 0
        self.members_sum = 0
        self.slots_sum = 0
        self._park_s: deque = deque(maxlen=4096)

    def observe_wave(self, members: int, deadline_fired: bool) -> None:
        with self._lock:
            self.launches += 1
            self.members_sum += members
            self.slots_sum += pad_wave(members)
            if deadline_fired:
                self.deadline_launches += 1
            else:
                self.full_launches += 1

    def observe_park(self, seconds: float) -> None:
        with self._lock:
            self.requests += 1
            self._park_s.append(seconds)
        # the streaming histogram keeps the FULL distribution (the
        # deque above is a bounded recent window for the gauges)
        histograms.get("wave_park").record(seconds)

    def reset(self) -> None:
        with self._lock:
            self.requests = 0
            self.launches = 0
            self.full_launches = 0
            self.deadline_launches = 0
            self.members_sum = 0
            self.slots_sum = 0
            self._park_s.clear()

    def snapshot(self) -> dict:
        with self._lock:
            # shared nearest-rank helper (telemetry/histogram.py): the
            # old int(len*0.99) indexing returned the MAX of a
            # 100-sample window as "p99"
            p50 = percentile(self._park_s, 0.5)
            p99 = percentile(self._park_s, 0.99)
            return {
                "requests": self.requests,
                "launches": self.launches,
                "full_launches": self.full_launches,
                "deadline_launches": self.deadline_launches,
                "fill_ratio": (self.members_sum / self.slots_sum
                               if self.slots_sum else 0.0),
                "park_latency_p50_ms": p50 * 1e3,
                "park_latency_p99_ms": p99 * 1e3,
            }


#: process-wide wave stats (all coalescers feed it; they are per-chunk
#: and too short-lived to carry their own history)
wave_stats = WaveStats()


class _LatencyEWMA:
    """Exponentially-weighted wave latency: the adaptive coalescer's
    deadline is a fraction of what a launch actually costs, so parking
    never dominates the device time it tries to amortize."""

    def __init__(self, alpha: float = 0.2) -> None:
        self._lock = witness_lock("LatencyEWMA._lock")
        self._alpha = alpha
        self._value: Optional[float] = None

    def update(self, seconds: float) -> None:
        with self._lock:
            if self._value is None:
                self._value = seconds
            else:
                self._value += self._alpha * (seconds - self._value)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value


#: EWMA of launch_wave wall seconds (compile transients included on
#: purpose: while variants still compile, waiting longer for fuller
#: waves is the right call)
wave_latency_ewma = _LatencyEWMA()

#: EWMA of "this launch was deadline-fired" (0/1 per launch). The
#: adaptive window is a fraction of the (device) wave latency — but
#: the device-resident cluster state made launches several times
#: cheaper, and a window that keeps shrinking with launch cost drops
#: below the members' host-prep spread and FRAGMENTS waves: partial
#: fire -> more launches -> lower fill -> more per-launch overhead
#: than the parking it saved. When deadline fires dominate, this
#: signal widens the window back toward the cap, so the coalescer
#: self-corrects instead of feeding back.
wave_deadline_ewma = _LatencyEWMA(alpha=0.25)

#: launches currently executing, token -> perf_counter start. A
#: long-running in-flight launch (a cold XLA compile) disarms the
#: adaptive deadline process-wide: the EWMA only learns about a slow
#: variant AFTER it finishes, but parked members must stop firing
#: partial waves INTO the transient (each would cold-compile its own
#: wave bucket).
_INFLIGHT_LOCK = witness_lock("coalesce._INFLIGHT_LOCK")
_INFLIGHT_STARTS: dict = {}


def _fused_fetch(fout, t_pad: int, b_pad: int):
    """Turn a fused wave's outputs into the launcher's eager host
    dict + lazy top-k holder. ONE packed-buffer readback — and no
    "wave_fetch" dispatch count: profiler.call already blocked on the
    fused program's outputs, so the copy rides the dispatch's own
    synchronization instead of being another device interaction."""
    with tracer.span("kernel.d2h"):
        packed = np.asarray(fout.packed)
    profiler.add_bytes("d2h", packed.nbytes)
    host = unpack_fused_wave(packed, t_pad, b_pad)
    return host, _WaveTopK(fout.topk_idx, fout.topk_scores)


def _oldest_inflight_age_s() -> float:
    with _INFLIGHT_LOCK:
        if not _INFLIGHT_STARTS:
            return 0.0
        oldest = min(_INFLIGHT_STARTS.values())
    return time.perf_counter() - oldest


def launch_wave(kins: List[KernelIn], k_steps: List[int],
                features: List[KernelFeatures],
                mesh=_USE_GLOBAL) -> List[KernelOut]:
    """Fire B launch requests as ONE joint device call; split results.

    The wave runs the joint kernel (ops/kernel.place_taskgroups_joint):
    members' placement steps execute in arrival order over a shared
    capacity carry, so members see each other's placements — the
    serialized plan applier's semantics, on device.

    ``mesh``: shard the node axis over this mesh. A coalescer always
    passes its server's choice explicitly — including None for "this
    server opted out" — so co-resident servers never fight over the
    module global; only DIRECT calls (no mesh argument) fall back to
    ``configure_wave_mesh``'s global.
    """
    if mesh is _USE_GLOBAL:
        mesh = _WAVE_MESH
    # wave-launch seam (chaos plane): an injected failure lands on
    # EVERY member of the wave (the coalescer's _fire propagates it to
    # each parked request) — a crashed wave, mid-cohort; the armed
    # wavecohort window must expire and the broker must redeliver
    fault("wave.launch")
    with tracer.span("wave.assemble"):
        k_max = max(k_steps)
        feats = union_features(features)
        padded = [_pad_kin_steps(kin, k_max) for kin in kins]
        b_pad = pad_wave(len(padded))
        if b_pad > len(padded):
            # inert filler rows: first member with zero active steps
            filler = padded[0]._replace(n_steps=np.asarray(0, np.int32))
            padded = padded + [filler] * (b_pad - len(padded))
        # sharded dispatch needs the node axis to split evenly over the
        # mesh; pad_bucket's power-of-two floor (64) covers every
        # power-of-two slice, so a fallback here means an exotic device
        # count — counted, and gated to zero on the steady burst
        n_nodes = int(np.asarray(padded[0].cap_cpu).shape[-1])
        mesh_size = int(mesh.size) if mesh is not None else 0
        wave_sharded = mesh_size >= 2 and n_nodes % mesh_size == 0
        # stack on HOST (numpy): the jit call below uploads each stacked
        # leaf once; stacking device arrays would dispatch per leaf per
        # member — thousands of round trips on a remote-device
        # transport. The big node planes (cluster capacity + the wave
        # snapshot's utilization) are usually IDENTICAL across members;
        # when every one of _SHAREABLE_FIELDS is identity-shared, they
        # ship UNBATCHED (the joint kernel broadcasts on device) so wave
        # upload bytes stay flat in wave size instead of B-fold —
        # sharded waves included: a resident sharded twin costs ZERO
        # upload, exactly like the single-device path. Three
        # all-or-nothing groups -> at most eight layouts per
        # (bucket, features) pair, enumerable by warmup either way.
        def _group_shared(fields) -> bool:
            return all(
                all(getattr(k, f) is getattr(padded[0], f)
                    for k in padded[1:])
                for f in fields
            )

        shareable = _group_shared(_SHAREABLE_FIELDS)
        neutral_shareable = _group_shared(_NEUTRAL_SHAREABLE_FIELDS)
        job_shareable = _group_shared(_JOB_SHAREABLE_FIELDS)

        if wave_sharded:
            from nomad_tpu.parallel.sharded import shared_field_spec

        def _stack_field(f, xs):
            if wave_field_is_shared(f, shareable, neutral_shareable,
                                    job_shareable):
                # device-resident twin when one exists (the cluster
                # state advanced at snapshot time, frozen neutral
                # singletons uploaded once): jit's device_put then
                # moves ZERO bytes for this leaf. The lookup carries
                # the wave's placement — a sharded wave is only served
                # mesh-placed twins (tensors/device_state.py), so the
                # jit's in_shardings never reshard. The snapshot group
                # is registry-only (frozen_ok=False): a STALE
                # snapshot's read-only gathered planes must ship as
                # host numpy, not masquerade as singletons.
                dev = default_device_state.lookup(
                    xs[0], frozen_ok=f not in _SHAREABLE_FIELDS,
                    spec=(shared_field_spec(f) if wave_sharded
                          else None),
                    mesh=mesh if wave_sharded else None)
                if dev is not None:
                    return dev
                return np.asarray(xs[0])
            return np.stack([np.asarray(x) for x in xs])

        stacked = KernelIn(*[
            _stack_field(f, [getattr(k, f) for k in padded])
            for f in KernelIn._fields
        ])

        # step layout: member 0's steps, then member 1's, ... (the
        # applier's serialization order = plan arrival order). The step
        # axis is sized from the PADDED wave (b_pad * k_max) so the
        # compiled shape depends only on (wave bucket, step bucket,
        # features) — retry waves of any real size reuse it; inert
        # steps are microseconds of device time. Built vectorized:
        # the per-member python loop showed up at bench wave sizes.
        t_pad = pad_steps(b_pad * k_max)
        ks = np.asarray(k_steps, np.int64)
        starts = np.concatenate(([0], np.cumsum(ks)[:-1]))
        offsets = starts.tolist()
        total = int(ks.sum())
        step_member = np.full(t_pad, -1, np.int32)
        step_local = np.zeros(t_pad, np.int32)
        member_of_step = np.repeat(np.arange(len(ks)), ks)
        step_member[:total] = member_of_step
        step_local[:total] = (np.arange(total)
                              - np.repeat(starts, ks))

    # the jit-cache identity the bucketing scheme promises: a repeat of
    # this key must NOT recompile (the profiler counts violations)
    wave_key = (b_pad, t_pad, n_nodes, shareable, neutral_shareable,
                job_shareable, feats)
    # fused dispatch (ISSUE 19): one mega-kernel program instead of
    # program + eager multi-buffer fetch. Sharded fusion additionally
    # needs each node shard wide enough for the local TOPK merge.
    fused_ok = (_FUSED_WAVE and fused_wave_supported(feats)
                and (not wave_sharded
                     or n_nodes // mesh_size >= TOPK))
    host = None
    wave_topk = None
    t_launch = time.perf_counter()
    token = object()
    with _INFLIGHT_LOCK:
        _INFLIGHT_STARTS[token] = t_launch
    try:
        if wave_sharded:
            from nomad_tpu.parallel.sharded import (
                fused_sharded_entry,
                joint_sharded_entry,
            )

            global sharded_wave_launches
            sharded_wave_launches += 1
            sharded_wave_stats.note_launch(mesh_size)
            # host leaves pre-place with the jit's exact in_shardings
            # (the profiler's explicit upload would otherwise commit
            # them to one device and the call would pay a reshard);
            # step planes ship replicated, raw numpy on purpose
            if fused_ok:
                try:
                    fn, kin_shardings, repl = fused_sharded_entry(
                        mesh, shareable, neutral_shareable,
                        job_shareable)
                    fout = profiler.call(
                        "fused_wave_sharded", fn,
                        (stacked, step_member, step_local),
                        (t_pad, feats),
                        wave_key + (tuple(mesh.devices.flat),),
                        jit_fn=fn,
                        shardings=(kin_shardings, repl, repl),
                    )
                    host, wave_topk = _fused_fetch(fout, t_pad, b_pad)
                except Exception:       # noqa: BLE001 - counted, composite covers
                    host = wave_topk = None
            if host is None:
                fn, kin_shardings, repl = joint_sharded_entry(
                    mesh, shareable, neutral_shareable, job_shareable)
                out = profiler.call(
                    "joint_sharded", fn,
                    (stacked, step_member, step_local),
                    (t_pad, feats),
                    wave_key + (tuple(mesh.devices.flat),), jit_fn=fn,
                    shardings=(kin_shardings, repl, repl),
                )
        else:
            if mesh is not None:
                sharded_wave_stats.note_fallback(mesh_size)
            if fused_ok:
                try:
                    fout = fused_wave_launch(
                        stacked, step_member, step_local, t_pad,
                        feats, wave_key)
                    host, wave_topk = _fused_fetch(fout, t_pad, b_pad)
                except Exception:       # noqa: BLE001 - counted, composite covers
                    host = wave_topk = None
            if host is None:
                out = profiler.call(
                    "joint", place_taskgroups_joint_jit,
                    (stacked, jnp.asarray(step_member),
                     jnp.asarray(step_local)),
                    (t_pad, feats),
                    wave_key, jit_fn=place_taskgroups_joint_jit,
                )
        if host is not None:
            fused_wave_stats.note_launch()
        else:
            if _FUSED_WAVE:
                # wanted fusion, ran the composite (unsupported
                # feature union, narrow shard, or a fused error)
                fused_wave_stats.note_fallback()
            with tracer.span("kernel.d2h"):
                # fetch ONLY the planes members consume immediately:
                # the per-step placements and the per-member metric
                # scalars. The joint kernel's final capacity carry
                # (a_cpu/a_mem/a_disk — full node planes) stays on
                # device (the live path commits through plans, never
                # through it), and the top-k planes stay on device
                # too — handed back as lazy slices whose one shared
                # fetch runs in the plan window.
                host = {
                    f: np.asarray(getattr(out, f))
                    for f in _JOINT_FETCH_FIELDS
                }
            # the composite's wave-critical result drain is its own
            # device interaction on top of the program dispatch
            profiler.count_dispatch("wave_fetch")
            profiler.add_bytes(
                "d2h", sum(a.nbytes for a in host.values()))
            wave_topk = _WaveTopK(out.topk_idx, out.topk_scores)
    finally:
        with _INFLIGHT_LOCK:
            _INFLIGHT_STARTS.pop(token, None)
    wave_latency_ewma.update(time.perf_counter() - t_launch)
    results = []
    for i, k in enumerate(k_steps):
        o = offsets[i]
        results.append(KernelOut(
            chosen=host["chosen"][o:o + k],
            scores=host["scores"][o:o + k],
            found=host["found"][o:o + k],
            topk_idx=_TopKSlice(wave_topk, 0, o, o + k),
            topk_scores=_TopKSlice(wave_topk, 1, o, o + k),
            nodes_evaluated=host["nodes_evaluated"][i],
            nodes_feasible=host["nodes_feasible"][i],
            exhausted_cpu=host["exhausted_cpu"][i],
            exhausted_mem=host["exhausted_mem"][i],
            exhausted_disk=host["exhausted_disk"][i],
            exhausted_ports=host["exhausted_ports"][i],
            exhausted_devices=host["exhausted_devices"][i],
            exhausted_cores=host["exhausted_cores"][i],
        ))
    return results


class _Request:
    __slots__ = ("kin", "k_steps", "features", "out", "error", "event")

    def __init__(self, kin, k_steps, features):
        self.kin = kin
        self.k_steps = k_steps
        self.features = features
        self.out: Optional[KernelOut] = None
        self.error: Optional[BaseException] = None
        self.event = threading.Event()


class _PlanWindow:
    """Context manager a batching worker wraps around plan submission:
    the participant yields its rendezvous slot while it blocks on the
    serialized applier, so the NEXT wave fires without waiting for it
    (plan submission pipelines behind wave N instead of serializing
    wave N+1)."""

    __slots__ = ("_coalescer",)

    def __init__(self, coalescer: "LaunchCoalescer") -> None:
        self._coalescer = coalescer

    def __enter__(self) -> "_PlanWindow":
        self._coalescer.suspend()
        return self

    def __exit__(self, *exc) -> None:
        self._coalescer.resume()


class LaunchCoalescer:
    """Rendezvous point for one batch of concurrently-scheduled evals.

    Every participant must end with ``done()`` (use try/finally). A
    wave fires when every not-yet-done (and not suspended) participant
    is parked in ``launch`` — OR when a parked request's adaptive
    deadline expires, in which case whatever is pending fires as a
    partial wave and later arrivals form the next one. The deadline is
    a fraction of the EWMA wave latency clamped to
    ``[window_min_s, window_max_s]``: parking is only worth paying
    while it stays small against the device call it amortizes. The
    observer that completes the rendezvous (a parking launcher, a
    finishing participant, or the deadline owner itself) executes the
    device call — there is no dispatcher thread.
    """

    #: deadline = EWMA wave latency x this fraction (clamped)
    WINDOW_FRACTION = 0.5

    def __init__(self, participants: int, mesh=None,
                 window_min_s: float = 0.001,
                 window_max_s: float = 0.050,
                 adaptive: bool = True) -> None:
        self._cv = threading.Condition(
            witness_lock("LaunchCoalescer._lock"))
        self._active = participants
        # the owning server's device mesh (None = module default)
        self.mesh = mesh
        self._pending: List[_Request] = []
        self.window_min_s = window_min_s
        self.window_max_s = window_max_s
        self.adaptive = adaptive
        # stats (asserted by tests, reported by the worker)
        self.launches = 0
        self.requests = 0
        self.max_wave = 0
        self.deadline_launches = 0

    #: deadlines disarm while EWMA x fraction exceeds this multiple of
    #: window_max: the device is grossly slower than the cap (cold
    #: compiles in flight), and firing partial waves then SPRAYS more
    #: cold compiles across fresh wave buckets instead of amortizing
    #: one full-wave compile
    TRANSIENT_FACTOR = 4.0

    def _window_s(self) -> Optional[float]:
        """Deadline for a parked request, or None to park until the
        rendezvous completes (no latency sample yet, or the compile
        transient is still running — both cases where fragmenting
        waves costs far more than parking)."""
        ewma = wave_latency_ewma.value
        if ewma is None:
            return None
        target = ewma * self.WINDOW_FRACTION
        if target > self.window_max_s * self.TRANSIENT_FACTOR:
            return None
        # an in-flight launch already running far past the cap is a
        # cold compile the EWMA hasn't learned about yet — disarm
        # before firing more partial waves into it
        if _oldest_inflight_age_s() > \
                self.window_max_s * self.TRANSIENT_FACTOR:
            return None
        # fragmentation feedback: widen (up to 4x, still capped) while
        # recent launches keep firing by deadline instead of by full
        # rendezvous
        frag = wave_deadline_ewma.value or 0.0
        target *= 1.0 + 3.0 * frag
        return min(max(target, self.window_min_s), self.window_max_s)

    def launch(self, kin: KernelIn, k_steps: int,
               features: KernelFeatures) -> KernelOut:
        req = _Request(kin, k_steps, features)
        wave: Optional[List[_Request]] = None
        with self._cv:
            self.requests += 1
            self._pending.append(req)
            if len(self._pending) >= self._active:
                wave = self._pending
                self._pending = []
        if wave is not None:
            self._fire(wave)
        else:
            # parked: another member completes the rendezvous and runs
            # the device call, or this member's deadline expires and it
            # fires the partial wave itself. Park time OVERLAPS the
            # firing member's wave stages — the decomposition reports
            # it separately and must not sum it with them. The park
            # span and the park-latency stat cover ONLY the waiting:
            # a deadline owner's own launch work is attributed under
            # wave.launch, never double-reported as parking.
            t0 = time.perf_counter()
            with tracer.span("wave.park"):
                if self.adaptive:
                    fired = claimed = False
                    while not (fired or claimed):
                        window = self._window_s()
                        if window is None:
                            # disarmed (no latency sample yet, or a
                            # compile transient in flight): park, and
                            # poll at a coarse cadence so the deadline
                            # re-arms once the transient clears
                            fired = req.event.wait(0.05)
                            continue
                        fired = req.event.wait(window)
                        if fired:
                            break
                        if self._window_s() is None:
                            # a transient STARTED during the window
                            # (e.g. another wave hit a cold compile):
                            # do not fire a partial wave into it
                            continue
                        with self._cv:
                            if req in self._pending:
                                wave = self._pending
                                self._pending = []
                                self.deadline_launches += 1
                        claimed = True
                    if wave is None and not fired:
                        # claimed by another member mid-timeout: wait
                        # for its launch like any parked member
                        req.event.wait()
                else:
                    req.event.wait()
            wave_stats.observe_park(time.perf_counter() - t0)
            if wave is not None:
                self._fire(wave, deadline_fired=True)
        if req.error is not None:
            raise req.error
        return req.out

    def done(self) -> None:
        wave: Optional[List[_Request]] = None
        with self._cv:
            self._active -= 1
            if self._pending and len(self._pending) >= self._active:
                wave = self._pending
                self._pending = []
        if wave is not None:
            self._fire(wave)

    def suspend(self) -> None:
        """Temporarily yield this participant's rendezvous slot (it is
        about to block outside the scheduling hot path, e.g. on the
        plan applier). Pending requests stop waiting for it."""
        wave: Optional[List[_Request]] = None
        with self._cv:
            self._active -= 1
            if self._pending and len(self._pending) >= self._active:
                wave = self._pending
                self._pending = []
        if wave is not None:
            self._fire(wave)

    def resume(self) -> None:
        """Re-take the slot released by ``suspend``."""
        with self._cv:
            self._active += 1

    def plan_window(self) -> _PlanWindow:
        return _PlanWindow(self)

    def _fire(self, wave: List[_Request], deadline_fired: bool = False) -> None:
        # members that retried after a partial-commit snapshot refresh
        # may have crossed a node-axis pad bucket; a joint launch needs
        # one node axis, so split by shape (each group still coalesces)
        groups: dict = {}
        for r in wave:
            groups.setdefault(int(r.kin.cap_cpu.shape[0]), []).append(r)
        wave_deadline_ewma.update(1.0 if deadline_fired else 0.0)
        for grp in groups.values():
            self.launches += 1
            self.max_wave = max(self.max_wave, len(grp))
            wave_stats.observe_wave(len(grp), deadline_fired)
            try:
                with tracer.span("wave.launch"):
                    outs = launch_wave(
                        [r.kin for r in grp],
                        [r.k_steps for r in grp],
                        [r.features for r in grp],
                        mesh=self.mesh,
                    )
                for r, out in zip(grp, outs):
                    r.out = out
                # wave-boundary plan batching: the members are about
                # to resume and submit ~len(grp) plans — arm the plan
                # queue's drain window BEFORE releasing them, so the
                # whole wave commits as one raft entry
                # (utils/wavecohort + PlanQueue.dequeue_batch)
                wave_cohorts.note_wave(len(grp))
            except BaseException as e:              # noqa: BLE001
                for r in grp:
                    r.error = e
            for r in grp:
                r.event.set()


_CLUSTER_LRU_MAX = 8


class ClusterCache:
    """ClusterTensors memo shared by a batch's evals.

    When the store publishes usage planes, the process-wide
    incremental cache serves the build: unchanged ``structure_version``
    is an identity hit, a bumped one applies dirty-node deltas from
    the store's change log instead of the full O(nodes) Python rebuild
    every batch used to pay (tensors/schema.IncrementalClusterCache).
    Snapshot-identity keying is the fallback for states without usage
    planes (bare test harnesses)."""

    def __init__(self) -> None:
        self._lock = witness_lock("ClusterCache._lock")
        self._cache = {}

    def get(self, state):
        from nomad_tpu.tensors.schema import (
            ClusterTensors,
            default_incremental_cluster_cache,
        )

        u = getattr(state, "usage", None)
        if u is not None and u.uid:
            built = default_incremental_cluster_cache.get(state)
            # advance the device-resident wave planes HERE, on an eval
            # thread at snapshot time: the dirty-row h2d of the next
            # wave runs while the previous wave's execute holds the
            # device (the functional scatter double-buffers — in-
            # flight waves keep their own generation's arrays). The
            # wave launcher then finds every shared leaf resident and
            # uploads nothing for it.
            try:
                default_device_state.ensure(built, u)
            except Exception:                   # noqa: BLE001
                pass        # residency is an optimization, never a dep
            return built
        key = id(state)
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None and hit[0] is state:
                return hit[1]
        built = ClusterTensors.build(state.nodes())
        with self._lock:
            self._cache[key] = (state, built)
            while len(self._cache) > _CLUSTER_LRU_MAX:
                self._cache.pop(next(iter(self._cache)))
        return built


#: process-wide cache used by schedulers outside batch mode too
default_cluster_cache = ClusterCache()
