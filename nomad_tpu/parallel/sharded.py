"""Sharded, batched placement: the multi-chip scheduler hot path.

A batch of B independent (evaluation, task group) placement problems —
each a :class:`~nomad_tpu.ops.kernel.KernelIn` over the same padded
node axis — runs as ONE ``jit`` over a 2D device mesh:

- every array gains a leading batch dim, sharded over the ``evals``
  mesh axis (dp: the analog of reference worker parallelism,
  nomad/worker.go:386);
- node-axis planes shard over the ``nodes`` mesh axis (sp: the cluster
  table split across the slice over ICI).

Sharding is GSPMD-style: we annotate in/out shardings on the
*unmodified* single-problem kernel (vmapped), and XLA inserts the
collectives — the global ``argmax``/``top_k`` over the sharded node
axis compiles to an all-gather+reduce riding ICI, which is the tensor
formulation of the reference's MaxScore/Limit iterators
(scheduler/select.go) and of the leader's global plan ordering.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nomad_tpu.ops.kernel import (
    FULL_FEATURES,
    KernelFeatures,
    KernelIn,
    KernelOut,
    place_taskgroup,
)
from nomad_tpu.parallel.mesh import AXIS_EVALS, AXIS_NODES

_B = AXIS_EVALS
_N = AXIS_NODES

# PartitionSpec per KernelIn field for the BATCHED layout (leading B dim).
_IN_SPECS = dict(
    # [B, N] node planes
    cap_cpu=P(_B, _N), cap_mem=P(_B, _N), cap_disk=P(_B, _N),
    free_cores=P(_B, _N), shares_per_core=P(_B, _N), free_dyn=P(_B, _N),
    base_mask=P(_B, _N), used_cpu=P(_B, _N), used_mem=P(_B, _N),
    used_disk=P(_B, _N), used_cores=P(_B, _N), used_mbits=P(_B, _N),
    avail_mbits=P(_B, _N), port_conflict=P(_B, _N),
    dev_aff_score=P(_B, _N), job_tg_count=P(_B, _N), penalty=P(_B, _N),
    aff_score=P(_B, _N), job_any_count=P(_B, _N),
    # [B, N, D]
    dev_free=P(_B, _N, None),
    # [B] scalars
    has_dev_affinity=P(_B), distinct_hosts_job=P(_B), distinct_hosts_tg=P(_B),
    ask_cpu=P(_B), ask_mem=P(_B), ask_disk=P(_B), ask_cores=P(_B),
    ask_dyn_ports=P(_B), ask_has_reserved_ports=P(_B), ask_mbits=P(_B),
    desired_count=P(_B), algorithm_spread=P(_B), n_steps=P(_B),
    # tie-break permutation [B, N] (replicated over nodes: it indexes
    # the global node axis, so it cannot shard with it)
    node_perm=P(_B, None),
    # per-step planes [B, K, ...]
    step_penalty=P(_B, None, None), step_preferred=P(_B, None),
    # spreads
    spread_active=P(_B, None), spread_even=P(_B, None),
    spread_weight=P(_B, None),
    spread_bucket=P(_B, None, _N),
    spread_counts=P(_B, None, None), spread_desired=P(_B, None, None),
    # [B, D]
    ask_dev=P(_B, None),
)

assert set(_IN_SPECS) == set(KernelIn._fields)


def batched_in_shardings(mesh: Mesh) -> KernelIn:
    return KernelIn(**{f: NamedSharding(mesh, s) for f, s in _IN_SPECS.items()})


def batched_out_shardings(mesh: Mesh) -> KernelOut:
    # outputs are small (per-placement rows); shard only the batch axis
    return KernelOut(
        **{f: NamedSharding(mesh, P(_B)) for f in KernelOut._fields}
    )


def stack_kernel_ins(kins: Sequence[KernelIn]) -> KernelIn:
    """Stack B single-problem inputs into one batched KernelIn.

    All problems must share the same padded node axis (the bucketed
    static shapes from tensors/schema.pad_bucket guarantee few distinct
    buckets; the broker batches compatible evals together).
    """
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *kins)


def make_place_batch(
    mesh: Mesh, k_steps: int, features: KernelFeatures = FULL_FEATURES
):
    """Compile the batched, sharded placement step for ``mesh``.

    Returns ``fn(kin_batched) -> KernelOut`` (batched) — the framework's
    "training step": one launch schedules a whole batch of evaluations
    across the slice.
    """
    vmapped = jax.vmap(lambda kin: place_taskgroup(kin, k_steps, features))
    return jax.jit(
        vmapped,
        in_shardings=(batched_in_shardings(mesh),),
        out_shardings=batched_out_shardings(mesh),
    )


# ---------------------------------------------------------------------------
# Sharded JOINT waves: the live coalescer's multi-chip path.
#
# The joint wave kernel (ops/kernel.place_taskgroups_joint) is the live
# server's launch shape: a stacked member axis + one serialized step
# axis with a shared capacity carry. Sharding its NODE axis over the
# mesh runs the same program across the slice — each step's masked
# argmax/top-k lowers to a per-shard reduce + cross-shard all-reduce
# riding ICI (the reference's MaxScore iterator as a collective;
# SURVEY.md section 2.10) — so results are bit-identical to the
# single-device path by construction.
# ---------------------------------------------------------------------------

# PartitionSpec per stacked KernelIn field ([B, ...] member axis
# replicated, node axis sharded).
_JOINT_SPECS = dict(
    cap_cpu=P(None, _N), cap_mem=P(None, _N), cap_disk=P(None, _N),
    free_cores=P(None, _N), shares_per_core=P(None, _N),
    free_dyn=P(None, _N), base_mask=P(None, _N), used_cpu=P(None, _N),
    used_mem=P(None, _N), used_disk=P(None, _N), used_cores=P(None, _N),
    used_mbits=P(None, _N), avail_mbits=P(None, _N),
    port_conflict=P(None, _N), dev_aff_score=P(None, _N),
    job_tg_count=P(None, _N), penalty=P(None, _N), aff_score=P(None, _N),
    job_any_count=P(None, _N),
    dev_free=P(None, _N, None),
    has_dev_affinity=P(None), distinct_hosts_job=P(None),
    distinct_hosts_tg=P(None),
    ask_cpu=P(None), ask_mem=P(None), ask_disk=P(None), ask_cores=P(None),
    ask_dyn_ports=P(None), ask_has_reserved_ports=P(None),
    ask_mbits=P(None), desired_count=P(None), algorithm_spread=P(None),
    n_steps=P(None),
    node_perm=P(None, None),        # indexes the GLOBAL node axis
    step_penalty=P(None, None, None), step_preferred=P(None, None),
    spread_active=P(None, None), spread_even=P(None, None),
    spread_weight=P(None, None),
    spread_bucket=P(None, None, _N),
    spread_counts=P(None, None, None), spread_desired=P(None, None, None),
    ask_dev=P(None, None),
)

assert set(_JOINT_SPECS) == set(KernelIn._fields)


def shared_field_spec(field: str) -> P:
    """PartitionSpec of a WAVE-SHARED (unbatched) KernelIn leaf: the
    stacked layout's spec minus the leading member axis. Single source
    of truth for the sharded launcher, the device-resident state's
    frozen-singleton placement, and the AOT warmup — a drift here
    would make a resident plane's sharding miss the jit's
    ``in_shardings`` and silently reshard every wave."""
    return P(*tuple(_JOINT_SPECS[field])[1:])


def node_axis_sharding(mesh: Mesh) -> NamedSharding:
    """The [n_pad] node-plane sharding: rows split over the mesh's
    nodes axis (tensors/device_state.py places resident generations
    with this)."""
    return NamedSharding(mesh, P(_N))


def joint_in_shardings(mesh: Mesh, shared: bool = False,
                       neutral_shared: bool = False,
                       job_shared: bool = False):
    """(KernelIn-of-NamedSharding, replicated) for a wave layout: a
    field that ships UNBATCHED under the layout flags loses the member
    axis and keeps its node-axis split; stacked fields keep the full
    joint spec. The launcher pre-places host leaves with exactly these
    shardings so the jit's ``in_shardings`` never reshard."""
    from nomad_tpu.parallel.coalesce import wave_field_is_shared

    kin = KernelIn(**{
        f: NamedSharding(
            mesh,
            shared_field_spec(f)
            if wave_field_is_shared(f, shared, neutral_shared,
                                    job_shared)
            else s)
        for f, s in _JOINT_SPECS.items()
    })
    return kin, NamedSharding(mesh, P())


import weakref

# keyed by the live mesh OBJECT (weakly): a freed mesh's entry
# evicts itself, and an unrelated mesh allocated at the same address
# can never collide with a stale jit bound to dead devices. Each
# mesh maps sharing-layout flags -> the compiled wrapper (the sharing
# groups change leaf SHAPES, so every layout is its own in_shardings
# pytree; the (t_steps, features) variants are cached by jit itself).
_joint_sharded_cache: "weakref.WeakKeyDictionary" = \
    weakref.WeakKeyDictionary()


def joint_sharded_entry(mesh: Mesh, shared: bool = False,
                        neutral_shared: bool = False,
                        job_shared: bool = False):
    """(jit fn, KernelIn-of-NamedSharding, replicated) for the joint
    wave program with the node axis sharded over ``mesh``'s nodes axis
    under the given sharing layout. Cached per (mesh, layout) as ONE
    entry — the launcher needs the shardings on every wave (to
    pre-place host leaves), so rebuilding ~40 NamedShardings per
    launch would be repeated dispatch-path work; the (t_steps,
    features) variants are cached by jit itself (static args)."""
    from nomad_tpu.ops.kernel import place_taskgroups_joint

    layouts = _joint_sharded_cache.get(mesh)
    if layouts is None:
        layouts = _joint_sharded_cache[mesh] = {}
    key = (shared, neutral_shared, job_shared)
    hit = layouts.get(key)
    if hit is not None:
        return hit
    kin_shardings, repl = joint_in_shardings(
        mesh, shared, neutral_shared, job_shared)
    fn = jax.jit(
        place_taskgroups_joint,
        static_argnums=(3, 4),
        in_shardings=(kin_shardings, repl, repl),
        out_shardings=repl,      # outputs are small per-step rows
    )
    entry = (fn, kin_shardings, repl)
    layouts[key] = entry
    return entry


def make_joint_sharded(mesh: Mesh, shared: bool = False,
                       neutral_shared: bool = False,
                       job_shared: bool = False):
    """The compiled wrapper alone (see ``joint_sharded_entry``)."""
    return joint_sharded_entry(mesh, shared, neutral_shared,
                               job_shared)[0]


def wave_mesh(n_devices: int = 0, devices=None) -> Mesh:
    """A 1D nodes-axis mesh for live waves (the coalescer's multi-chip
    routing; evals parallelism comes from wave batching, so the whole
    slice goes to the node axis)."""
    import numpy as np

    devs = list(devices if devices is not None else jax.devices())
    if n_devices:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (_N,))


def unstack_kernel_outs(out: KernelOut) -> List[KernelOut]:
    """Split a batched KernelOut back into per-problem results."""
    b = out.chosen.shape[0]
    import numpy as np

    host = KernelOut(*[np.asarray(x) for x in out])
    return [KernelOut(*[f[i] for f in host]) for i in range(b)]
