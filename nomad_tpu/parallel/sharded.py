"""Sharded, batched placement: the multi-chip scheduler hot path.

A batch of B independent (evaluation, task group) placement problems —
each a :class:`~nomad_tpu.ops.kernel.KernelIn` over the same padded
node axis — runs as ONE ``jit`` over a 2D device mesh:

- every array gains a leading batch dim, sharded over the ``evals``
  mesh axis (dp: the analog of reference worker parallelism,
  nomad/worker.go:386);
- node-axis planes shard over the ``nodes`` mesh axis (sp: the cluster
  table split across the slice over ICI).

Sharding is GSPMD-style: we annotate in/out shardings on the
*unmodified* single-problem kernel (vmapped), and XLA inserts the
collectives — the global ``argmax``/``top_k`` over the sharded node
axis compiles to an all-gather+reduce riding ICI, which is the tensor
formulation of the reference's MaxScore/Limit iterators
(scheduler/select.go) and of the leader's global plan ordering.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nomad_tpu.ops.kernel import (
    FULL_FEATURES,
    KernelFeatures,
    KernelIn,
    KernelOut,
    place_taskgroup,
)
from nomad_tpu.parallel.mesh import AXIS_EVALS, AXIS_NODES

_B = AXIS_EVALS
_N = AXIS_NODES

# PartitionSpec per KernelIn field for the BATCHED layout (leading B dim).
_IN_SPECS = dict(
    # [B, N] node planes
    cap_cpu=P(_B, _N), cap_mem=P(_B, _N), cap_disk=P(_B, _N),
    free_cores=P(_B, _N), shares_per_core=P(_B, _N), free_dyn=P(_B, _N),
    base_mask=P(_B, _N), used_cpu=P(_B, _N), used_mem=P(_B, _N),
    used_disk=P(_B, _N), used_cores=P(_B, _N), used_mbits=P(_B, _N),
    avail_mbits=P(_B, _N), port_conflict=P(_B, _N),
    dev_aff_score=P(_B, _N), job_tg_count=P(_B, _N), penalty=P(_B, _N),
    aff_score=P(_B, _N), job_any_count=P(_B, _N),
    # [B, N, D]
    dev_free=P(_B, _N, None),
    # [B] scalars
    has_dev_affinity=P(_B), distinct_hosts_job=P(_B), distinct_hosts_tg=P(_B),
    ask_cpu=P(_B), ask_mem=P(_B), ask_disk=P(_B), ask_cores=P(_B),
    ask_dyn_ports=P(_B), ask_has_reserved_ports=P(_B), ask_mbits=P(_B),
    desired_count=P(_B), algorithm_spread=P(_B), n_steps=P(_B),
    # tie-break permutation [B, N] (replicated over nodes: it indexes
    # the global node axis, so it cannot shard with it)
    node_perm=P(_B, None),
    # per-step planes [B, K, ...]
    step_penalty=P(_B, None, None), step_preferred=P(_B, None),
    # spreads
    spread_active=P(_B, None), spread_even=P(_B, None),
    spread_weight=P(_B, None),
    spread_bucket=P(_B, None, _N),
    spread_counts=P(_B, None, None), spread_desired=P(_B, None, None),
    # [B, D]
    ask_dev=P(_B, None),
)

assert set(_IN_SPECS) == set(KernelIn._fields)


def batched_in_shardings(mesh: Mesh) -> KernelIn:
    return KernelIn(**{f: NamedSharding(mesh, s) for f, s in _IN_SPECS.items()})


def batched_out_shardings(mesh: Mesh) -> KernelOut:
    # outputs are small (per-placement rows); shard only the batch axis
    return KernelOut(
        **{f: NamedSharding(mesh, P(_B)) for f in KernelOut._fields}
    )


def stack_kernel_ins(kins: Sequence[KernelIn]) -> KernelIn:
    """Stack B single-problem inputs into one batched KernelIn.

    All problems must share the same padded node axis (the bucketed
    static shapes from tensors/schema.pad_bucket guarantee few distinct
    buckets; the broker batches compatible evals together).
    """
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *kins)


def make_place_batch(
    mesh: Mesh, k_steps: int, features: KernelFeatures = FULL_FEATURES
):
    """Compile the batched, sharded placement step for ``mesh``.

    Returns ``fn(kin_batched) -> KernelOut`` (batched) — the framework's
    "training step": one launch schedules a whole batch of evaluations
    across the slice.
    """
    vmapped = jax.vmap(lambda kin: place_taskgroup(kin, k_steps, features))
    return jax.jit(
        vmapped,
        in_shardings=(batched_in_shardings(mesh),),
        out_shardings=batched_out_shardings(mesh),
    )


# ---------------------------------------------------------------------------
# Sharded JOINT waves: the live coalescer's multi-chip path.
#
# The joint wave kernel (ops/kernel.place_taskgroups_joint) is the live
# server's launch shape: a stacked member axis + one serialized step
# axis with a shared capacity carry. Sharding its NODE axis over the
# mesh runs the same program across the slice — each step's masked
# argmax/top-k lowers to a per-shard reduce + cross-shard all-reduce
# riding ICI (the reference's MaxScore iterator as a collective;
# SURVEY.md section 2.10) — so results are bit-identical to the
# single-device path by construction.
# ---------------------------------------------------------------------------

# PartitionSpec per stacked KernelIn field ([B, ...] member axis
# replicated, node axis sharded).
_JOINT_SPECS = dict(
    cap_cpu=P(None, _N), cap_mem=P(None, _N), cap_disk=P(None, _N),
    free_cores=P(None, _N), shares_per_core=P(None, _N),
    free_dyn=P(None, _N), base_mask=P(None, _N), used_cpu=P(None, _N),
    used_mem=P(None, _N), used_disk=P(None, _N), used_cores=P(None, _N),
    used_mbits=P(None, _N), avail_mbits=P(None, _N),
    port_conflict=P(None, _N), dev_aff_score=P(None, _N),
    job_tg_count=P(None, _N), penalty=P(None, _N), aff_score=P(None, _N),
    job_any_count=P(None, _N),
    dev_free=P(None, _N, None),
    has_dev_affinity=P(None), distinct_hosts_job=P(None),
    distinct_hosts_tg=P(None),
    ask_cpu=P(None), ask_mem=P(None), ask_disk=P(None), ask_cores=P(None),
    ask_dyn_ports=P(None), ask_has_reserved_ports=P(None),
    ask_mbits=P(None), desired_count=P(None), algorithm_spread=P(None),
    n_steps=P(None),
    node_perm=P(None, None),        # indexes the GLOBAL node axis
    step_penalty=P(None, None, None), step_preferred=P(None, None),
    spread_active=P(None, None), spread_even=P(None, None),
    spread_weight=P(None, None),
    spread_bucket=P(None, None, _N),
    spread_counts=P(None, None, None), spread_desired=P(None, None, None),
    ask_dev=P(None, None),
)

assert set(_JOINT_SPECS) == set(KernelIn._fields)


def shared_field_spec(field: str) -> P:
    """PartitionSpec of a WAVE-SHARED (unbatched) KernelIn leaf: the
    stacked layout's spec minus the leading member axis. Single source
    of truth for the sharded launcher, the device-resident state's
    frozen-singleton placement, and the AOT warmup — a drift here
    would make a resident plane's sharding miss the jit's
    ``in_shardings`` and silently reshard every wave."""
    return P(*tuple(_JOINT_SPECS[field])[1:])


def node_axis_sharding(mesh: Mesh) -> NamedSharding:
    """The [n_pad] node-plane sharding: rows split over the mesh's
    nodes axis (tensors/device_state.py places resident generations
    with this)."""
    return NamedSharding(mesh, P(_N))


def joint_in_shardings(mesh: Mesh, shared: bool = False,
                       neutral_shared: bool = False,
                       job_shared: bool = False):
    """(KernelIn-of-NamedSharding, replicated) for a wave layout: a
    field that ships UNBATCHED under the layout flags loses the member
    axis and keeps its node-axis split; stacked fields keep the full
    joint spec. The launcher pre-places host leaves with exactly these
    shardings so the jit's ``in_shardings`` never reshard."""
    from nomad_tpu.parallel.coalesce import wave_field_is_shared

    kin = KernelIn(**{
        f: NamedSharding(
            mesh,
            shared_field_spec(f)
            if wave_field_is_shared(f, shared, neutral_shared,
                                    job_shared)
            else s)
        for f, s in _JOINT_SPECS.items()
    })
    return kin, NamedSharding(mesh, P())


import weakref

# keyed by the live mesh OBJECT (weakly): a freed mesh's entry
# evicts itself, and an unrelated mesh allocated at the same address
# can never collide with a stale jit bound to dead devices. Each
# mesh maps sharing-layout flags -> the compiled wrapper (the sharing
# groups change leaf SHAPES, so every layout is its own in_shardings
# pytree; the (t_steps, features) variants are cached by jit itself).
_joint_sharded_cache: "weakref.WeakKeyDictionary" = \
    weakref.WeakKeyDictionary()


def joint_sharded_entry(mesh: Mesh, shared: bool = False,
                        neutral_shared: bool = False,
                        job_shared: bool = False):
    """(jit fn, KernelIn-of-NamedSharding, replicated) for the joint
    wave program with the node axis sharded over ``mesh``'s nodes axis
    under the given sharing layout. Cached per (mesh, layout) as ONE
    entry — the launcher needs the shardings on every wave (to
    pre-place host leaves), so rebuilding ~40 NamedShardings per
    launch would be repeated dispatch-path work; the (t_steps,
    features) variants are cached by jit itself (static args)."""
    from nomad_tpu.ops.kernel import place_taskgroups_joint

    layouts = _joint_sharded_cache.get(mesh)
    if layouts is None:
        layouts = _joint_sharded_cache[mesh] = {}
    key = (shared, neutral_shared, job_shared)
    hit = layouts.get(key)
    if hit is not None:
        return hit
    kin_shardings, repl = joint_in_shardings(
        mesh, shared, neutral_shared, job_shared)
    fn = jax.jit(
        place_taskgroups_joint,
        static_argnums=(3, 4),
        in_shardings=(kin_shardings, repl, repl),
        out_shardings=repl,      # outputs are small per-step rows
    )
    entry = (fn, kin_shardings, repl)
    layouts[key] = entry
    return entry


def make_joint_sharded(mesh: Mesh, shared: bool = False,
                       neutral_shared: bool = False,
                       job_shared: bool = False):
    """The compiled wrapper alone (see ``joint_sharded_entry``)."""
    return joint_sharded_entry(mesh, shared, neutral_shared,
                               job_shared)[0]


def wave_mesh(n_devices: int = 0, devices=None) -> Mesh:
    """A 1D nodes-axis mesh for live waves (the coalescer's multi-chip
    routing; evals parallelism comes from wave batching, so the whole
    slice goes to the node axis)."""
    import numpy as np

    devs = list(devices if devices is not None else jax.devices())
    if n_devices:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (_N,))


def unstack_kernel_outs(out: KernelOut) -> List[KernelOut]:
    """Split a batched KernelOut back into per-problem results."""
    b = out.chosen.shape[0]
    import numpy as np

    host = KernelOut(*[np.asarray(x) for x in out])
    return [KernelOut(*[f[i] for f in host]) for i in range(b)]


# ---------------------------------------------------------------------------
# Fused sharded waves (ISSUE 19): the fused mega-kernel composed with
# the PR 14 mesh. GSPMD cannot partition through the fused program's
# pallas boundary, so the node-axis split is explicit ``shard_map``:
# each shard runs the SAME per-step math as the composite
# (ops/kernel._feasible/_score on its local node rows — shared code,
# not a reimplementation) and the per-step argmax / preferred-pin /
# top-k merge across shards is a handful of scalar-or-[TOPK]-wide
# collectives (pmax/pmin/all_gather) riding ICI. The carry planes stay
# local [N/D] the whole scan and the a_* outputs come back node-axis
# sharded — no full gather anywhere, same invariant the mesh cell
# measures for the composite.
#
# Tie-break parity: the composite picks ``argmax(masked)`` (lowest
# index among equal maxima) or, with shuffle on,
# ``perm[argmax(masked[perm])]`` (lowest PERMUTATION RANK among
# maxima). Both reduce to "minimize a per-node i32 rank among the
# global maxima" with rank = global index or inv(perm) — which is
# exactly the pmax-value / pmin-rank / pmin-index cascade below, so
# selection is bit-identical, not just score-identical.
# ---------------------------------------------------------------------------


def _fused_sharded_core(kin: KernelIn, step_member, step_local, *,
                        t_steps: int, features: KernelFeatures,
                        n_shards: int):
    """Per-shard body of the fused sharded wave (runs under
    shard_map; node-axis leaves arrive pre-sliced to [.., N/D])."""
    from nomad_tpu.ops.kernel import (
        KIN_UNBATCHED_RANKS,
        NEG_INF,
        TOPK,
        JointOut,
        _feasible,
        _score,
        pack_fused_wave,
    )

    f = features
    n_loc = kin.cap_cpu.shape[-1]
    n_glob = n_loc * n_shards
    b = kin.n_steps.shape[0]
    g0 = jax.lax.axis_index(_N) * n_loc
    giota = g0.astype(jnp.int32) + jnp.arange(n_loc, dtype=jnp.int32)
    big = jnp.int32(2**31 - 1)

    def _bat(x, rank):
        if jnp.ndim(x) == rank + 1:
            return x
        return jnp.broadcast_to(x, (b,) + jnp.shape(x))

    zf = jnp.zeros(n_loc, jnp.float32)
    zi = jnp.zeros(n_loc, jnp.int32)
    init = dict(
        a_cpu=zf, a_mem=zf, a_disk=zf,
        job_tg_count=_bat(kin.job_tg_count, 1),
    )
    if f.with_ports:
        init["a_dyn"] = zi
        init["port_conflict"] = _bat(kin.port_conflict, 1)
    if f.with_distinct:
        init["job_any_count"] = _bat(kin.job_any_count, 1)

    # tie-break rank rows, local slice: inv(perm) under shuffle
    # (node_perm is REPLICATED — it indexes the global axis — so the
    # inverse is computed in full and sliced to this shard's rows),
    # else the global index itself
    if f.with_shuffle:
        def _inv(p):
            return jnp.zeros_like(p).at[p].set(
                jnp.arange(n_glob, dtype=p.dtype))

        def _slc(p):
            return jax.lax.dynamic_slice(p, (g0,), (n_loc,))

        if jnp.ndim(kin.node_perm) == 2:
            rank_rows = jax.vmap(
                lambda p: _slc(_inv(p)))(kin.node_perm)   # [B, N/D]
        else:
            rank_rows = _slc(_inv(kin.node_perm))         # [N/D]

    def member_view(st, m):
        kin_m = KernelIn(*[
            x[m] if jnp.ndim(x) == r + 1 else x
            for x, r in zip(kin, KIN_UNBATCHED_RANKS)
        ])
        st_m = dict(
            used_cpu=kin_m.used_cpu + st["a_cpu"],
            used_mem=kin_m.used_mem + st["a_mem"],
            used_disk=kin_m.used_disk + st["a_disk"],
            job_tg_count=st["job_tg_count"][m],
        )
        if f.with_ports:
            st_m["free_dyn"] = kin_m.free_dyn - st["a_dyn"]
            st_m["port_conflict"] = st["port_conflict"][m]
        if f.with_distinct:
            st_m["job_any_count"] = st["job_any_count"][m]
        return kin_m, st_m

    def step(st, t):
        member = step_member[t]
        active_step = member >= 0
        m = jnp.clip(member, 0, b - 1)
        j = step_local[t]
        kin_m, st_m = member_view(st, m)

        feasible, ask_cpu_total, _ = _feasible(kin_m, st_m, f)
        penalty = kin_m.penalty
        if f.with_step_penalties:
            pen_ids = kin_m.step_penalty[j]      # GLOBAL node ids
            step_pen = jnp.any(giota[:, None] == pen_ids[None, :],
                               axis=1)
            penalty = penalty | step_pen
        final = _score(kin_m, st_m, ask_cpu_total, penalty, f, None)
        active = active_step & (j < kin_m.n_steps)
        masked = jnp.where(feasible & active, final, NEG_INF)

        if f.with_shuffle:
            rank = (rank_rows[m] if rank_rows.ndim == 2
                    else rank_rows)
        else:
            rank = giota
        vmax = jax.lax.pmax(jnp.max(masked), _N)
        is_max = masked == vmax
        rwin = jax.lax.pmin(
            jnp.min(jnp.where(is_max, rank, big)), _N)
        best = jax.lax.pmin(
            jnp.min(jnp.where(is_max & (rank == rwin), giota, big)),
            _N)
        if f.with_preferred:
            pref = kin_m.step_preferred[j]
            prefc = jnp.clip(pref, 0, n_glob - 1)
            feas_pref = jax.lax.pmax(
                jnp.max(((giota == prefc) & feasible)
                        .astype(jnp.int32)), _N) > 0
            pref_ok = (pref >= 0) & feas_pref & active
            idx = jnp.where(pref_ok, prefc, best)
        else:
            idx = best
        at_idx = giota == idx
        val = jax.lax.pmax(
            jnp.max(jnp.where(at_idx, masked, -jnp.inf)), _N)
        found = val > NEG_INF / 2

        if f.with_topk:
            # local top-k, then merge: each shard surfaces its TOPK
            # best in value-desc/index-asc order, and the flat
            # [D*TOPK] concatenation preserves global-index order
            # between shards for equal values — so a second top_k
            # reproduces the composite's global tie order exactly
            tv_loc, ti_loc = jax.lax.top_k(masked, TOPK)
            gi_loc = giota[ti_loc]
            tv_all = jax.lax.all_gather(tv_loc, _N)     # [D, TOPK]
            gi_all = jax.lax.all_gather(gi_loc, _N)
            topv, pos = jax.lax.top_k(tv_all.reshape(-1), TOPK)
            topi = gi_all.reshape(-1)[pos]
        else:
            topv = jnp.full(TOPK, NEG_INF)
            topi = jnp.zeros(TOPK, jnp.int32)

        upd = (found & active).astype(jnp.float32)
        updi = (found & active).astype(jnp.int32)
        one = at_idx.astype(jnp.float32) * upd
        onei = at_idx.astype(jnp.int32) * updi
        st2 = dict(
            a_cpu=st["a_cpu"] + one * ask_cpu_total,
            a_mem=st["a_mem"] + one * kin_m.ask_mem,
            a_disk=st["a_disk"] + one * kin_m.ask_disk,
            job_tg_count=st["job_tg_count"].at[m].add(onei),
        )
        if f.with_ports:
            st2["a_dyn"] = st["a_dyn"] + onei * kin_m.ask_dyn_ports
            st2["port_conflict"] = st["port_conflict"].at[m].set(
                st["port_conflict"][m]
                | ((one > 0) & kin_m.ask_has_reserved_ports)
            )
        if f.with_distinct:
            st2["job_any_count"] = st["job_any_count"].at[m].add(onei)
        out = (
            jnp.where(found, idx, -1).astype(jnp.int32),
            jnp.where(found, val, 0.0),
            found & active,
            topi.astype(jnp.int32),
            topv,
        )
        return st2, out

    st_final, (chosen, scores, found, topk_idx, topk_scores) = \
        jax.lax.scan(step, init, jnp.arange(t_steps))

    # per-member metrics: local partial sums + one exact i32 psum
    def member_metrics(kin_m: KernelIn):
        st0 = dict(
            used_cpu=kin_m.used_cpu, used_mem=kin_m.used_mem,
            used_disk=kin_m.used_disk, job_tg_count=kin_m.job_tg_count,
            used_cores=kin_m.used_cores, used_mbits=kin_m.used_mbits,
            free_dyn=kin_m.free_dyn, port_conflict=kin_m.port_conflict,
            dev_free=kin_m.dev_free, job_any_count=kin_m.job_any_count,
            spread_counts=kin_m.spread_counts,
        )
        feas0, _, dims0 = _feasible(kin_m, st0, f)
        base_i = kin_m.base_mask
        ex = lambda fit: jnp.sum(base_i & ~fit).astype(jnp.int32)  # noqa: E731
        return (
            jnp.sum(base_i).astype(jnp.int32),
            jnp.sum(feas0).astype(jnp.int32),
            ex(dims0["fit_cpu"]), ex(dims0["fit_mem"]),
            ex(dims0["fit_disk"]), ex(dims0["fit_ports"]),
            ex(dims0["fit_dev"]), ex(dims0["fit_cores"]),
        )

    in_axes = KernelIn(*[
        0 if jnp.ndim(x) == r + 1 else None
        for x, r in zip(kin, KIN_UNBATCHED_RANKS)
    ])
    locs = jax.vmap(member_metrics, in_axes=(in_axes,))(kin)
    mets = [jax.lax.psum(x, _N) for x in locs]

    out = JointOut(
        chosen=chosen, scores=scores, found=found,
        topk_idx=topk_idx, topk_scores=topk_scores,
        nodes_evaluated=mets[0], nodes_feasible=mets[1],
        exhausted_cpu=mets[2], exhausted_mem=mets[3],
        exhausted_disk=mets[4], exhausted_ports=mets[5],
        exhausted_devices=mets[6], exhausted_cores=mets[7],
        a_cpu=st_final["a_cpu"], a_mem=st_final["a_mem"],
        a_disk=st_final["a_disk"],
    )
    packed = pack_fused_wave(out, t_steps, int(b))
    return (packed, topk_idx, topk_scores,
            st_final["a_cpu"], st_final["a_mem"], st_final["a_disk"])


#: fused sharded entries, cached per live mesh object like
#: _joint_sharded_cache (same WeakKeyDictionary rationale)
_fused_sharded_cache: "weakref.WeakKeyDictionary" = \
    weakref.WeakKeyDictionary()


def fused_sharded_entry(mesh: Mesh, shared: bool = False,
                        neutral_shared: bool = False,
                        job_shared: bool = False):
    """(jit fn, KernelIn-of-NamedSharding, replicated) for the FUSED
    wave program with the node axis split over ``mesh`` via
    shard_map. Same sharding discipline as joint_sharded_entry — the
    in_specs ARE shared_field_spec's layout, so resident mesh-placed
    twins flow in without resharding."""
    import functools

    from jax.experimental.shard_map import shard_map

    from nomad_tpu.ops.kernel import FusedWaveOut

    layouts = _fused_sharded_cache.get(mesh)
    if layouts is None:
        layouts = _fused_sharded_cache[mesh] = {}
    key = (shared, neutral_shared, job_shared)
    hit = layouts.get(key)
    if hit is not None:
        return hit
    kin_shardings, repl = joint_in_shardings(
        mesh, shared, neutral_shared, job_shared)
    in_specs = (KernelIn(*[s.spec for s in kin_shardings]), P(), P())
    out_specs = (P(), P(), P(), P(_N), P(_N), P(_N))
    n_shards = int(mesh.shape[_N])

    def run(kin, step_member, step_local, t_steps, features):
        body = functools.partial(
            _fused_sharded_core, t_steps=t_steps, features=features,
            n_shards=n_shards)
        res = shard_map(body, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=False)(
            kin, step_member, step_local)
        return FusedWaveOut(*res)

    fn = jax.jit(run, static_argnums=(3, 4),
                 in_shardings=(kin_shardings, repl, repl))
    entry = (fn, kin_shardings, repl)
    layouts[key] = entry
    return entry
