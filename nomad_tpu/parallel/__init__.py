"""Multi-chip execution: device meshes and sharded placement kernels.

The reference scales horizontally (SURVEY.md section 2.11): N servers x
M workers process evaluations concurrently (the data-parallel axis) and
node-set scaling is handled by class caching + candidate limiting (the
"long context" axis). The TPU build maps both onto a 2D device mesh:

- ``evals`` axis: independent evaluations batch together and shard
  across devices (the worker-parallelism analog, dp).
- ``nodes`` axis: the cluster's node planes shard across devices over
  ICI (the sequence-parallel analog, sp); global node selection is an
  XLA collective (all-gather + argmax under GSPMD).
"""

from nomad_tpu.parallel.mesh import AXIS_EVALS, AXIS_NODES, make_mesh
from nomad_tpu.parallel.sharded import (
    batched_in_shardings,
    batched_out_shardings,
    make_place_batch,
    stack_kernel_ins,
)

__all__ = [
    "AXIS_EVALS",
    "AXIS_NODES",
    "make_mesh",
    "make_place_batch",
    "stack_kernel_ins",
    "batched_in_shardings",
    "batched_out_shardings",
]
