"""Sidecar proxy: the envoy stand-in for the Connect service mesh.

Reference behavior: client/allocrunner/taskrunner/envoy_bootstrap_hook.go
generates an Envoy bootstrap and runs Envoy as the sidecar; this build
runs this program instead (one process per sidecar role, launched by
the connect hook inside the allocation's network namespace):

- ``inbound``: the sidecar's public (mesh) listener. Accepts mesh
  connections, REQUIRES the service's mesh identity token as a
  preamble line (the SI-token analog of Envoy's mTLS + intentions;
  consul.go DeriveSITokens), then relays to the local service bound on
  loopback inside the namespace. A connection without the token is
  dropped before a single upstream byte flows.
- ``upstream``: a local listener on 127.0.0.1:<local_bind_port> inside
  the namespace (services.go ConsulUpstream). Relays to the
  destination sidecar's mesh address, sending the token preamble.

Run with ``python -S`` (no site imports) and a single JSON argv:
  {"mode": "inbound"|"upstream", "listen": ["ip", port],
   "target": ["ip", port], "token": "..."}
"""

import json
import socket
import sys
import threading

PREAMBLE_MAX = 128


def _pump(src: socket.socket, dst: socket.socket) -> None:
    try:
        while True:
            data = src.recv(65536)
            if not data:
                break
            dst.sendall(data)
    except OSError:
        pass
    finally:
        for s in (src, dst):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


def _relay(conn: socket.socket, target, preamble: bytes = b"") -> None:
    try:
        upstream = socket.create_connection(tuple(target), timeout=10)
    except OSError:
        conn.close()
        return
    try:
        if preamble:
            upstream.sendall(preamble)
        t = threading.Thread(target=_pump, args=(conn, upstream), daemon=True)
        t.start()
        _pump(upstream, conn)
        t.join(timeout=2)
    finally:
        for s in (conn, upstream):
            try:
                s.close()
            except OSError:
                pass


def _read_line(conn: socket.socket, limit: int = PREAMBLE_MAX) -> bytes:
    buf = b""
    while b"\n" not in buf and len(buf) < limit:
        try:
            chunk = conn.recv(1)
        except OSError:
            return b""
        if not chunk:
            break
        buf += chunk
    return buf.split(b"\n", 1)[0]


def _serve_inbound(cfg) -> None:
    token = cfg["token"].encode()
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(tuple(cfg["listen"]))
    srv.listen(32)
    while True:
        conn, _ = srv.accept()

        def handle(conn=conn):
            conn.settimeout(10)
            line = _read_line(conn)
            if line != b"SI " + token:
                # unauthenticated mesh connection: refuse before any
                # bytes reach the service (the intentions-deny analog)
                conn.close()
                return
            conn.settimeout(None)
            _relay(conn, cfg["target"])

        threading.Thread(target=handle, daemon=True).start()


def _serve_upstream(cfg) -> None:
    preamble = ("SI " + cfg["token"] + "\n").encode()
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(tuple(cfg["listen"]))
    srv.listen(32)
    while True:
        conn, _ = srv.accept()
        threading.Thread(
            target=_relay, args=(conn, cfg["target"], preamble),
            daemon=True).start()


def main() -> None:
    cfg = json.loads(sys.argv[1])
    if cfg["mode"] == "inbound":
        _serve_inbound(cfg)
    else:
        _serve_upstream(cfg)


if __name__ == "__main__":
    main()
