"""Artifact fetching into task directories.

Reference behavior: client/allocrunner/taskrunner/artifact_hook.go +
getter/getter.go (go-getter). Supported sources:

- http(s)://...           urllib download
- git::<url> or *.git     ``git clone`` (depth 1; ``ref`` option)
- file paths / file://    copy (file or tree)

Options (the go-getter subset the reference jobs actually use):
- checksum: "<algo>:<hex>" or "<hex>" (md5/sha1/sha256/sha512),
  verified before the artifact is exposed to the task
- archive: "false" disables auto-unpacking; otherwise .zip/.tar.gz/
  .tgz/.tar.bz2/.tar are extracted into the destination (go-getter's
  default unarchiving)

Destinations resolve inside the task directory and are containment-
checked (escapingfs semantics, like the template hook): a jobspec
cannot write outside its sandbox.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import tarfile
import urllib.parse
import urllib.request
import zipfile
from typing import Dict, Optional


class ArtifactError(Exception):
    """Download/verify failure -> task setup failure (restartable per
    the restart policy, artifact_hook.go wraps as recoverable)."""

    recoverable = True


_ALGOS = {"md5", "sha1", "sha256", "sha512"}
_HEX_LEN_TO_ALGO = {32: "md5", 40: "sha1", 64: "sha256", 128: "sha512"}


def _safe_join(root: str, *parts: str) -> str:
    """Containment-checked join (escapingfs; CVE-2022-24683 class)."""
    path = os.path.realpath(os.path.join(root, *parts))
    rootr = os.path.realpath(root)
    if path != rootr and not path.startswith(rootr + os.sep):
        raise ArtifactError(f"artifact destination escapes task dir: {parts}")
    return path


def _verify_checksum(path: str, spec: str) -> None:
    spec = spec.strip()
    if ":" in spec:
        algo, want = spec.split(":", 1)
        algo = algo.lower()
    else:
        want = spec
        algo = _HEX_LEN_TO_ALGO.get(len(spec), "")
    if algo not in _ALGOS:
        raise ArtifactError(f"unsupported checksum spec: {spec!r}")
    h = hashlib.new(algo)
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    got = h.hexdigest()
    if got.lower() != want.lower():
        raise ArtifactError(
            f"checksum mismatch: want {algo}:{want}, got {algo}:{got}"
        )


def _unpack(path: str, dest_dir: str) -> bool:
    """Extract recognized archives; True when extraction happened."""
    lower = path.lower()
    try:
        if lower.endswith(".zip"):
            with zipfile.ZipFile(path) as z:
                for name in z.namelist():
                    _safe_join(dest_dir, name)     # zip-slip guard
                z.extractall(dest_dir)
            return True
        if lower.endswith((".tar.gz", ".tgz", ".tar.bz2", ".tbz2", ".tar")):
            with tarfile.open(path) as t:
                members = t.getmembers()
                for m in members:
                    _safe_join(dest_dir, m.name)   # tar-slip guard
                try:
                    t.extractall(dest_dir, filter="data")
                except TypeError:
                    # pre-3.12 tarfile has no filter: the name guard
                    # above cannot catch symlink-member escapes
                    # ("lnk" -> "/" then "lnk/evil"), so reject links
                    # and special files outright
                    for m in members:
                        if not (m.isreg() or m.isdir()):
                            raise ArtifactError(
                                f"archive member {m.name!r} is not a "
                                "regular file/dir (links need "
                                "Python >= 3.12)")
                    t.extractall(dest_dir)
            return True
    except (OSError, zipfile.BadZipFile, tarfile.TarError) as e:
        raise ArtifactError(f"extracting {os.path.basename(path)}: {e}")
    return False


def fetch_artifact(artifact: Dict, task_dir: str,
                   timeout: float = 300.0) -> str:
    """Download one artifact stanza into the task dir; returns the
    destination path. Raises ArtifactError on any failure."""
    source = str(artifact.get("source", "")).strip()
    if not source:
        raise ArtifactError("artifact has no source")
    destination = str(artifact.get("destination", "local/")).strip() or "local/"
    options = artifact.get("options") or {}
    checksum = options.get("checksum", "")
    unarchive = str(options.get("archive", "true")).lower() not in (
        "false", "0")

    dest_dir = _safe_join(task_dir, destination)
    os.makedirs(dest_dir, exist_ok=True)

    # --- git ---
    is_git = source.startswith("git::") or source.endswith(".git")
    if is_git:
        if checksum:
            # silently skipping a declared checksum would be worse
            # than failing; pin git artifacts by ref instead
            raise ArtifactError(
                "checksum verification is not supported for git "
                "sources; pin a ref instead")
        url = source[5:] if source.startswith("git::") else source
        ref = options.get("ref", "")
        cmd = ["git", "clone", "--depth", "1"]
        if ref:
            cmd += ["--branch", ref]
        cmd += [url, dest_dir]
        try:
            if os.listdir(dest_dir):
                # idempotent under prestart retries: a completed clone
                # is kept; anything else in the way is an error
                if os.path.isdir(os.path.join(dest_dir, ".git")):
                    return dest_dir
                raise ArtifactError(
                    f"git destination {destination!r} is not empty")
            proc = subprocess.run(cmd, capture_output=True, timeout=timeout)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise ArtifactError(f"git clone {url}: {e}")
        if proc.returncode != 0:
            raise ArtifactError(
                f"git clone {url}: {proc.stderr.decode(errors='replace')[:300]}"
            )
        return dest_dir

    parsed = urllib.parse.urlparse(source)
    name = os.path.basename(parsed.path) or "artifact"
    fetched = _safe_join(dest_dir, name)

    if parsed.scheme in ("http", "https"):
        try:
            req = urllib.request.Request(source)
            with urllib.request.urlopen(req, timeout=timeout) as resp, \
                    open(fetched, "wb") as out:
                shutil.copyfileobj(resp, out)
        except OSError as e:
            raise ArtifactError(f"GET {source}: {e}")
    elif parsed.scheme in ("", "file"):
        src_path = parsed.path if parsed.scheme == "file" else source
        if not os.path.exists(src_path):
            raise ArtifactError(f"artifact source not found: {src_path}")
        if os.path.isdir(src_path):
            if checksum:
                # a declared checksum cannot be verified against a
                # directory tree; hard-error like the git-source path
                # rather than silently skipping verification
                raise ArtifactError(
                    "checksum verification is not supported for "
                    f"directory sources: {src_path}"
                )
            shutil.copytree(src_path, dest_dir, dirs_exist_ok=True)
            return dest_dir
        shutil.copy2(src_path, fetched)
    else:
        raise ArtifactError(f"unsupported artifact scheme: {parsed.scheme}")

    if checksum:
        try:
            _verify_checksum(fetched, checksum)
        except ArtifactError:
            # never leave an unverified artifact in the task dir
            try:
                os.unlink(fetched)
            except OSError:
                pass
            raise

    if unarchive and _unpack(fetched, dest_dir):
        os.unlink(fetched)
    return dest_dir
