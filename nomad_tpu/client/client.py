"""The Client: node agent main loop.

Reference behavior: client/client.go (3,174 LoC) -- fingerprint the
host into a Node, register with servers and heartbeat
(registerAndHeartbeat :1609), watch assigned allocations with a
blocking query (watchAllocations :2063), diff into add/update/remove
(runAllocs :2293), run allocRunners, batch alloc status updates back to
the server, persist state for restart recovery (restoreState
:1109-1180), and GC terminal allocs.

The RPC boundary is the ``ClientRPC`` protocol: ``InProcessRPC`` talks
to a Server object directly (the test topology); the HTTP transport
plugs in at the same seam.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import uuid
from typing import Dict, List, Optional, Protocol

from nomad_tpu.client.alloc_runner import AllocRunner
from nomad_tpu.client.fingerprint import fingerprint_node
from nomad_tpu.client.state_db import MemStateDB, StateDB
from nomad_tpu.structs import consts
from nomad_tpu.telemetry.trace import tracer
from nomad_tpu.utils.metrics import global_registry
from nomad_tpu.structs.alloc import Allocation

LOG = logging.getLogger(__name__)


class ClientRPC(Protocol):
    def register_node(self, node) -> Dict: ...
    def update_status(self, node_id: str, status: str) -> Dict: ...
    def get_client_allocs(self, node_id: str, min_index: int, timeout: float) -> Dict: ...
    def update_allocs(self, allocs: List[Allocation]) -> int: ...
    def csi_claim(self, namespace: str, volume_id: str, claim): ...


class InProcessRPC:
    """Direct-call transport to a Server (test topology; the reference
    equivalent is the client and server sharing an agent process)."""

    def __init__(self, server) -> None:
        self.server = server

    def register_node(self, node) -> Dict:
        return self.server.node_register(node)

    def update_status(self, node_id: str, status: str) -> Dict:
        return self.server.node_update_status(node_id, status)

    def get_client_allocs(self, node_id: str, min_index: int, timeout: float) -> Dict:
        return self.server.get_client_allocs(node_id, min_index, timeout)

    def update_allocs(self, allocs: List[Allocation]) -> int:
        return self.server.update_allocs_from_client(allocs)

    def csi_claim(self, namespace: str, volume_id: str, claim):
        """CSIVolume.Claim RPC (allocrunner/csi_hook.go)."""
        self.server.csi_volume_claim(namespace, volume_id, claim)
        return self.server.state.csi_volume_by_id(namespace, volume_id)

    def derive_vault_tokens(self, alloc_id: str,
                            task_names: List[str]) -> Dict[str, str]:
        """Node.DeriveVaultToken RPC (taskrunner vault_hook)."""
        return self.server.derive_vault_tokens(alloc_id, task_names)

    def consul_kv_get(self, key: str):
        """Consul KV read for template rendering."""
        return self.server.consul.kv_get(key)

    def consul_kv_index(self) -> int:
        return self.server.consul.kv_index()

    def consul_kv_list(self, prefix: str):
        return self.server.consul.kv_list(prefix)

    def services_index(self) -> int:
        """Service-registration table index (templates ranging over
        ``service`` re-render when instances come and go)."""
        return self.server.state.table_index(["services"])

    def vault_read_secret(self, path: str, token: str = ""):
        """Policy-checked against the task's derived token."""
        return self.server.vault.provider.read_secret(path, token=token)

    def vault_secrets_index(self) -> int:
        return self.server.vault.provider.secrets_index()

    def vault_token_valid(self, token: str) -> bool:
        return self.server.vault.provider.token_valid(token)

    def register_services(self, regs) -> int:
        """ServiceRegistration.Upsert RPC (client serviceregistration
        wrapper -> NomadServiceProvider)."""
        return self.server.service_register(regs)

    def mesh_identity_token(self, namespace: str, service: str,
                            alloc_id: str = "") -> str:
        """Connect mesh credential (consul.go DeriveSITokens analog).
        ``alloc_id`` scopes derivation to the alloc's declared
        services/upstreams server-side."""
        return self.server.mesh_identity_token(namespace, service,
                                               alloc_id=alloc_id)

    def services_by_name(self, namespace: str, name: str):
        """ServiceRegistration.GetService (connect upstream discovery)."""
        return self.server.services_by_name(namespace, name)

    def deregister_services_by_alloc(self, alloc_ids) -> int:
        return self.server.service_deregister_by_alloc(alloc_ids)

    def deregister_services(self, reg_ids) -> int:
        index = 0
        for rid in reg_ids:
            try:
                index = self.server.service_deregister(rid)
            except ValueError:
                pass   # already gone (idempotent dereg)
        return index


class SecretsClient:
    """Client-side facade over the server's Vault/Consul surface: the
    data sources taskrunner vault/template hooks pull from
    (vault_hook.go tokens; template.go Consul KV + Vault KV reads)."""

    def __init__(self, rpc, node=None) -> None:
        self.rpc = rpc
        self.node = node

    def derive_vault_tokens(self, alloc_id: str,
                            task_names: List[str]) -> Dict[str, str]:
        return self.rpc.derive_vault_tokens(alloc_id, task_names)

    def kv_get(self, key: str):
        return self.rpc.consul_kv_get(key)

    def kv_ls(self, prefix: str):
        return self.rpc.consul_kv_list(prefix)

    def services(self, namespace: str, name: str):
        """Live service instances for template ``service`` blocks."""
        return self.rpc.services_by_name(namespace, name)

    def read_secret(self, path: str, token: str = ""):
        return self.rpc.vault_read_secret(path, token)

    def live_data_index(self) -> int:
        """Combined monotonic index over every live template source
        (Consul KV + Vault secrets + service registrations); watchers
        poll this."""
        return (self.rpc.consul_kv_index()
                + self.rpc.vault_secrets_index()
                + self.rpc.services_index())

    def vault_token_valid(self, token: str) -> bool:
        return self.rpc.vault_token_valid(token)

    def node_attrs(self) -> Dict[str, str]:
        return dict(self.node.attributes) if self.node is not None else {}


class ClientConfig:
    def __init__(
        self,
        data_dir: str = "/tmp/nomad-tpu-client",
        datacenter: str = "dc1",
        node_class: str = "",
        meta: Optional[Dict[str, str]] = None,
        persistent_state: bool = False,
        update_batch_interval: float = 0.2,
        max_terminal_allocs: int = 50,
        plugin_dir: str = "",
        options: Optional[Dict[str, str]] = None,
    ) -> None:
        self.data_dir = data_dir
        self.datacenter = datacenter
        self.node_class = node_class
        self.meta = meta or {}
        self.persistent_state = persistent_state
        self.update_batch_interval = update_batch_interval
        self.max_terminal_allocs = max_terminal_allocs
        self.plugin_dir = plugin_dir
        # client { options { "docker.volumes.enabled" = "true" } }
        # (agent config.go client options map, consumed by drivers)
        self.options = options or {}


class Client:
    def __init__(
        self,
        rpc: ClientRPC,
        config: Optional[ClientConfig] = None,
        drivers: Optional[Dict] = None,
        device_plugins: Optional[List] = None,
        node_id: Optional[str] = None,
        csi_clients: Optional[Dict] = None,
    ) -> None:
        self.rpc = rpc
        self.config = config or ClientConfig()
        if drivers is None:
            from nomad_tpu.drivers import builtin_drivers
            drivers = builtin_drivers(self.config.options)
        # external plugin subprocesses from plugin_dir merge over the
        # built-ins (helper/pluginutils/catalog + loader semantics)
        self.external_drivers: Dict[str, object] = {}
        if self.config.plugin_dir:
            from nomad_tpu.plugins.external import load_plugin_dir
            self.external_drivers = load_plugin_dir(self.config.plugin_dir)
            drivers = dict(drivers, **self.external_drivers)
        self.drivers = drivers
        self.device_plugins = device_plugins or []
        self.csi_clients = csi_clients or {}

        os.makedirs(self.config.data_dir, exist_ok=True)
        if self.config.persistent_state:
            self.state_db: StateDB = StateDB(
                os.path.join(self.config.data_dir, "client_state.db")
            )
        else:
            self.state_db = MemStateDB()

        # stable node ID across restarts (client.go nodeID persistence)
        self.node_id = node_id or self.state_db.get_meta("node_id") or str(uuid.uuid4())
        self.state_db.put_meta("node_id", self.node_id)

        self.node = fingerprint_node(
            self.node_id,
            datacenter=self.config.datacenter,
            node_class=self.config.node_class,
            drivers=self.drivers,
            device_plugins=self.device_plugins,
            meta=self.config.meta,
        )
        # advertise CSI node plugins this agent runs (the reference
        # fingerprints these from plugin allocs via dynamicplugins; the
        # build registers them at agent config time)
        for pid, client in self.csi_clients.items():
            info = {"healthy": True}
            try:
                detail = client.plugin_get_info()
                info["provider"] = detail.get("name", "")
                info["version"] = detail.get("version", "")
            except Exception:                   # noqa: BLE001
                info["healthy"] = False
            self.node.csi_node_plugins[pid] = info
        from nomad_tpu.client.csimanager import CSIManager

        self.csi_manager = CSIManager(
            rpc, self.csi_clients, self.node_id, self.config.data_dir
        ) if hasattr(rpc, "csi_claim") else None
        # bridge-mode alloc networking (networking_bridge_linux.go);
        # probed once, None on hosts without netns/veth privileges
        from nomad_tpu.client.network_manager import (
            BridgeNetworkManager, bridge_supported,
        )

        self.network_manager = BridgeNetworkManager() \
            if bridge_supported() else None
        from nomad_tpu.client.servicereg import ServiceRegWrapper

        self.service_reg = ServiceRegWrapper(rpc, self.node) \
            if hasattr(rpc, "register_services") else None
        # Connect hook manager (envoy_bootstrap_hook analog); needs the
        # mesh-token + discovery RPC verbs
        from nomad_tpu.client.connect import ConnectManager

        self.connect_mgr = ConnectManager(rpc) \
            if hasattr(rpc, "mesh_identity_token") else None
        self.secrets = SecretsClient(rpc, self.node) \
            if hasattr(rpc, "derive_vault_tokens") else None
        self.allocs: Dict[str, AllocRunner] = {}
        self._alloc_lock = threading.Lock()
        self._alloc_indexes: Dict[str, int] = {}    # alloc_id -> modify_index
        self._pending_updates: Dict[str, Allocation] = {}
        self._update_lock = threading.Lock()
        self.heartbeat_ttl = 10.0
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []

    # --- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._restore_state()
        self._register()
        for name, target in (
            ("heartbeat", self._run_heartbeat),
            ("watch-allocs", self._run_watch_allocations),
            ("update-allocs", self._run_update_batcher),
        ):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"client-{name}-{self.node_id[:8]}")
            self._threads.append(t)
            t.start()

    def shutdown(self) -> None:
        self._shutdown.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
        self._flush_updates()
        for drv in self.external_drivers.values():
            drv.shutdown()
        self.state_db.close()

    def stop_allocs(self) -> None:
        """Stop all running allocs (used by tests/drain shutdown)."""
        with self._alloc_lock:
            runners = list(self.allocs.values())
        for ar in runners:
            ar.stop("client shutting down")

    def _prev_runner(self, alloc_id: str):
        """allocwatcher lookup: the previous alloc's local runner."""
        with self._alloc_lock:
            return self.allocs.get(alloc_id)

    # --- registration + heartbeat (client.go:1609) ----------------------

    def _register(self) -> None:
        self.node.status = consts.NODE_STATUS_INIT
        resp = self.rpc.register_node(self.node)
        self.heartbeat_ttl = resp.get("heartbeat_ttl", 10.0) or 10.0
        # first heartbeat flips the node ready (client.go watchNodeUpdates)
        self.rpc.update_status(self.node_id, consts.NODE_STATUS_READY)

    def _run_heartbeat(self) -> None:
        self.last_heartbeat_ok = time.time()
        while not self._shutdown.is_set():
            # heartbeat at a fraction of the TTL (client.go heartbeats
            # at intervals inside the server-granted TTL)
            wait = max(self.heartbeat_ttl * 0.4, 0.05)
            if self._shutdown.wait(wait):
                return
            if getattr(self, "partition_heartbeats", False):
                # test hook: a network partition — tasks keep running,
                # heartbeats stop reaching the servers (the
                # disconnected-clients e2e scenarios flip this)
                self._heartbeat_stop_check()
                continue
            try:
                away = time.time() - self.last_heartbeat_ok
                # heartbeat round-trip telemetry (client.go emits
                # client.heartbeat latency the same way): a server whose
                # applier or GIL is saturated shows up HERE first, as
                # heartbeat latency creeping toward the TTL
                t_hb = time.perf_counter()
                with tracer.span("client.heartbeat", trace_id=self.node_id):
                    resp = self.rpc.update_status(
                        self.node_id, consts.NODE_STATUS_READY
                    )
                global_registry.add_sample(
                    "nomad.client.heartbeat",
                    (time.perf_counter() - t_hb) * 1000.0,
                )
                self.heartbeat_ttl = resp.get("heartbeat_ttl", self.heartbeat_ttl) or self.heartbeat_ttl
                self.last_heartbeat_ok = time.time()
                if away > max(self.heartbeat_ttl, 1.0):
                    # reconnect after a real gap: the servers may have
                    # marked our allocs 'unknown' — re-push every live
                    # runner's actual status (client.go marks allocs
                    # dirty on reconnect so the server's view heals)
                    self._resync_alloc_states()
            except Exception as e:              # noqa: BLE001
                LOG.warning("client %s: heartbeat failed: %s", self.node_id[:8], e)
                self._heartbeat_stop_check()
                # the server may have lost our node (restart, GC):
                # re-register instead of retrying forever
                # (client.go retryRegisterNode on "node not found")
                try:
                    self._register()
                    self.last_heartbeat_ok = time.time()
                except Exception as re_err:     # noqa: BLE001
                    LOG.warning(
                        "client %s: re-register failed: %s",
                        self.node_id[:8], re_err,
                    )

    def _heartbeat_stop_check(self) -> None:
        """heartbeatstop.go: while disconnected from servers, stop any
        alloc whose group sets stop_after_client_disconnect once the
        disconnect outlives that duration (the client self-stops so
        the replacement the server schedules can't double-run)."""
        away = time.time() - self.last_heartbeat_ok
        with self._alloc_lock:
            runners = list(self.allocs.values())
        for runner in runners:
            tg = runner.alloc.job.lookup_task_group(runner.alloc.task_group) \
                if runner.alloc.job is not None else None
            stop_after = getattr(tg, "stop_after_client_disconnect_s", None) \
                if tg is not None else None
            if stop_after is None or away < stop_after:
                continue
            if runner.is_done():
                continue
            LOG.warning(
                "client %s: heartbeat lost %.0fs > stop_after_client_"
                "disconnect; stopping alloc %s",
                self.node_id[:8], away, runner.alloc.id[:8])
            runner.stop("heartbeat with servers lost")

    # --- allocation watching (client.go:2063, :2293) --------------------

    def _run_watch_allocations(self) -> None:
        index = 0
        while not self._shutdown.is_set():
            try:
                resp = self.rpc.get_client_allocs(
                    self.node_id, min_index=index, timeout=1.0
                )
            except Exception as e:              # noqa: BLE001
                LOG.warning("client %s: alloc watch failed: %s", self.node_id[:8], e)
                if self._shutdown.wait(1.0):
                    return
                continue
            index = max(index, resp.get("index", index))
            self._run_allocs(resp.get("allocs", []))

    def _run_allocs(self, server_allocs: List[Allocation]) -> None:
        """runAllocs: diff server view against local runners."""
        with self._alloc_lock:
            existing = dict(self.allocs)
        server_by_id = {a.id: a for a in server_allocs}

        for alloc in server_allocs:
            runner = existing.get(alloc.id)
            if runner is None:
                if alloc.server_terminal_status() or alloc.client_terminal_status():
                    continue
                self._add_alloc(alloc)
            elif alloc.modify_index > self._alloc_indexes.get(alloc.id, 0):
                self._alloc_indexes[alloc.id] = alloc.modify_index
                if alloc.job is None:
                    alloc.job = runner.alloc.job
                runner.update(alloc)

        # GC runners the server no longer knows (garbage collected)
        for alloc_id, runner in existing.items():
            if alloc_id not in server_by_id:
                runner.destroy()
                with self._alloc_lock:
                    self.allocs.pop(alloc_id, None)

        self._gc_terminal()

    def _add_alloc(self, alloc: Allocation) -> None:
        runner = AllocRunner(
            alloc=alloc,
            drivers=self.drivers,
            data_dir=self.config.data_dir,
            on_alloc_update=self._queue_update,
            state_db=self.state_db,
            csi_manager=self.csi_manager,
            service_reg=self.service_reg,
            secrets=self.secrets,
            prev_lookup=self._prev_runner,
            device_plugins=self.device_plugins,
            connect_mgr=self.connect_mgr,
            network_manager=self.network_manager,
        )
        with self._alloc_lock:
            self.allocs[alloc.id] = runner
            self._alloc_indexes[alloc.id] = alloc.modify_index
        self.state_db.put_allocation(alloc)
        threading.Thread(
            target=runner.run, daemon=True, name=f"allocrun-{alloc.id[:8]}"
        ).start()

    def _gc_terminal(self) -> None:
        """client/gc.go: bound the number of terminal alloc runners."""
        with self._alloc_lock:
            terminal = [
                (aid, ar) for aid, ar in self.allocs.items()
                if ar.is_done() and ar.alloc.terminal_status()
            ]
            excess = len(terminal) - self.config.max_terminal_allocs
            victims = terminal[:max(excess, 0)]
            for aid, _ar in victims:
                self.allocs.pop(aid, None)
        # destroy outside the lock: it blocks on task teardown
        for _aid, ar in victims:
            ar.destroy()

    # --- status updates (client.go allocSync batching) ------------------

    def _resync_alloc_states(self) -> None:
        """Queue a status update for every live runner — used after a
        reconnect, when the servers' view (possibly 'unknown'/'lost')
        must converge back to the client's ground truth."""
        import copy as _copy

        from nomad_tpu.structs.alloc import TaskEvent

        now_ns = time.time_ns()
        with self._alloc_lock:
            runners = list(self.allocs.values())
        for runner in runners:
            try:
                updated = runner.alloc.copy_skip_job()
                with runner._lock:
                    updated.task_states = _copy.deepcopy(
                        dict(runner.task_states))
                # the reconnect stamp the reconciler compares against
                # the server's 'Disconnected' mark (structs.go
                # Allocation.Reconnected)
                for ts in updated.task_states.values():
                    ts.events.append(TaskEvent(
                        type="Reconnected", time_ns=now_ns,
                        message="client reconnected"))
                updated.client_status = runner.client_status()
                self._queue_update(updated)
            except Exception:                   # noqa: BLE001
                pass

    def _queue_update(self, alloc: Allocation) -> None:
        with self._update_lock:
            prior = self._pending_updates.get(alloc.id)
            if (prior is not None and alloc.deployment_status is None
                    and prior.deployment_status is not None):
                # don't let a task-state update clobber an unflushed
                # deployment-health report
                alloc.deployment_status = prior.deployment_status
            self._pending_updates[alloc.id] = alloc

    def _run_update_batcher(self) -> None:
        while not self._shutdown.is_set():
            if self._shutdown.wait(self.config.update_batch_interval):
                break
            self._flush_updates()

    def _flush_updates(self) -> None:
        with self._update_lock:
            updates, self._pending_updates = self._pending_updates, {}
        if not updates:
            return
        try:
            self.rpc.update_allocs(list(updates.values()))
        except Exception as e:                  # noqa: BLE001
            LOG.warning("client %s: alloc update failed: %s", self.node_id[:8], e)
            with self._update_lock:
                for a in updates.values():
                    self._pending_updates.setdefault(a.id, a)

    # --- restore (client.go:1109 restoreState) --------------------------

    def _restore_state(self) -> None:
        for alloc in self.state_db.get_allocations():
            if alloc.server_terminal_status():
                continue
            runner = AllocRunner(
                alloc=alloc,
                drivers=self.drivers,
                data_dir=self.config.data_dir,
                on_alloc_update=self._queue_update,
                state_db=self.state_db,
                csi_manager=self.csi_manager,
                service_reg=self.service_reg,
                secrets=self.secrets,
                prev_lookup=self._prev_runner,
                device_plugins=self.device_plugins,
                network_manager=self.network_manager,
            )
            with self._alloc_lock:
                self.allocs[alloc.id] = runner
                self._alloc_indexes[alloc.id] = alloc.modify_index
            runner.restore()

    # --- introspection --------------------------------------------------

    def num_allocs(self) -> int:
        with self._alloc_lock:
            return len(self.allocs)

    def alloc_runner(self, alloc_id: str) -> Optional[AllocRunner]:
        with self._alloc_lock:
            return self.allocs.get(alloc_id)

    def stats(self) -> Dict:
        with self._alloc_lock:
            return {
                "node_id": self.node_id,
                "allocs": len(self.allocs),
                "running": sum(
                    1 for ar in self.allocs.values() if not ar.is_done()
                ),
            }
