"""Per-task log collection with rotation (the logmon analog).

Reference behavior: client/logmon/logmon.go runs a SEPARATE PROCESS
per task stream that reads the task's stdout/stderr through a FIFO and
writes size-rotated files ``<task>.<stream>.N`` (client/lib/fifo +
logmon/logging/rotator.go), honoring the task's LogConfig (max_files /
max_file_size_mb). The process boundary is the point: task logs keep
flowing across agent restarts, and a restarted agent REATTACHES to the
live collector instead of starting a second one (go-plugin reattach).

Here ``LogMon`` is the supervisor handle: ``start()`` spawns
``python -m nomad_tpu.client.logmon <base> <max_files> <max_mb>`` as a
detached session, or adopts an already-running collector via its
pidfile. The collector child owns the FIFO and the rotation chain; on
SIGTERM it drains the FIFO tail and exits. If spawning fails the
collector runs as an in-agent thread (degraded: logs die with the
agent, logged as a warning).

fs 'logs' reads concatenate the rotated chain in index order.
"""

from __future__ import annotations

import errno
import glob
import logging
import os
import re
import select
import signal
import subprocess
import sys
import threading
import time
from typing import List, Optional, Tuple

LOG = logging.getLogger(__name__)


class _Collector:
    """The FIFO -> rotated-files loop (runs in the collector process,
    or in-agent as the degraded fallback)."""

    def __init__(self, base_path: str, max_files: int,
                 max_file_size_mb: int) -> None:
        self.base_path = base_path
        self.fifo_path = base_path + ".fifo"
        self.max_files = max(1, max_files)
        self.max_bytes = max(1, max_file_size_mb) * 1024 * 1024
        self._stop = threading.Event()
        self._fd: Optional[int] = None
        self._idx = 0
        self._out = None
        self._written = 0

    def open(self) -> None:
        os.makedirs(os.path.dirname(self.base_path), exist_ok=True)
        try:
            os.mkfifo(self.fifo_path)
        except FileExistsError:
            pass
        # O_RDWR keeps the read end open across writer restarts (task
        # restarts reopen the FIFO) and makes this open non-blocking
        self._fd = os.open(self.fifo_path, os.O_RDWR | os.O_NONBLOCK)
        # resume at the highest existing index (restart must not
        # interleave new output into already-rotated files)
        existing = rotated_files(self.base_path)
        if existing:
            self._idx = int(existing[-1].rsplit(".", 1)[1])
        self._open_current()
        if self._written >= self.max_bytes:
            self._rotate()

    def _open_current(self) -> None:
        path = f"{self.base_path}.{self._idx}"
        self._out = open(path, "ab")
        self._written = self._out.tell()

    def _rotate(self) -> None:
        self._out.close()
        self._idx += 1
        self._open_current()
        # prune beyond max_files (rotator.go purgeOldFiles)
        doomed = self._idx - self.max_files
        if doomed >= 0:
            try:
                os.unlink(f"{self.base_path}.{doomed}")
            except OSError:
                pass

    #: run-loop iterations between liveness checks (~10s at the 0.2s
    #: select timeout)
    _CHECK_EVERY = 50

    def run(self, should_exit=None) -> None:
        ticks = 0
        while not self._stop.is_set():
            ticks += 1
            if ticks % self._CHECK_EVERY == 0:
                # the alloc's log dir being deleted means the alloc was
                # garbage-collected (or a test's tmp tree was removed):
                # nothing will ever reattach — exit instead of leaking
                # a poller forever (this exact leak class degraded a
                # whole round's benchmarks once)
                if not os.path.isdir(os.path.dirname(self.base_path)):
                    break
                if should_exit is not None and should_exit():
                    break
            r, _, _ = select.select([self._fd], [], [], 0.2)
            if not r:
                continue
            try:
                chunk = os.read(self._fd, 65536)
            except OSError as e:
                if e.errno == errno.EAGAIN:
                    continue
                break
            if not chunk:
                continue
            self._out.write(chunk)
            self._out.flush()
            self._written += len(chunk)
            if self._written >= self.max_bytes:
                self._rotate()
        self.drain_and_close()

    def request_stop(self) -> None:
        self._stop.set()

    def drain_and_close(self) -> None:
        if self._fd is not None:
            # drain what the writer flushed before it exited — a
            # fast-exiting task's tail output is still in the FIFO
            # buffer when the collector stops
            while True:
                try:
                    chunk = os.read(self._fd, 65536)
                except OSError:
                    break
                if not chunk:
                    break
                self._out.write(chunk)
            os.close(self._fd)
            self._fd = None
        if self._out is not None:
            self._out.close()
            self._out = None
        try:
            os.unlink(self.fifo_path)
        except OSError:
            pass


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _is_collector(pid: int) -> bool:
    """A pidfile pid is only trustworthy if the process actually IS a
    logmon collector — crashes leave stale files, and pids recycle."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return b"logmon" in f.read()
    except OSError:
        return False


class LogMon:
    """Supervisor handle for one task stream's collector process.

    ``base_path`` is the unsuffixed target (".../web.stdout"); output
    files are ``base_path.N``. The write side is ``fifo_path`` — hand
    it to the driver as the task's stdout/stderr path.
    """

    def __init__(self, base_path: str, max_files: int = 10,
                 max_file_size_mb: int = 10) -> None:
        self.base_path = base_path
        self.fifo_path = base_path + ".fifo"
        self.pid_path = base_path + ".logmon.pid"
        self.max_files = max_files
        self.max_file_size_mb = max_file_size_mb
        self._pid: Optional[int] = None
        self._proc: Optional[subprocess.Popen] = None
        self._inproc: Optional[_Collector] = None
        self._inproc_thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        os.makedirs(os.path.dirname(self.base_path), exist_ok=True)
        # reattach: a collector from a previous agent life is still
        # running (the whole point of the process boundary)
        existing = self._read_pidfile()
        if existing is not None and _pid_alive(existing) \
                and _is_collector(existing):
            self._pid = existing
            return
        # stale leftovers from an uncleanly-died collector would make
        # the spawn-wait loop adopt the wrong pid
        for leftover in (self.pid_path,):
            try:
                os.unlink(leftover)
            except OSError:
                pass
        try:
            # run THIS FILE as a script with -S: the collector is
            # stdlib-only, and skipping site/package init avoids the
            # environment's heavyweight interpreter startup per stream
            proc = subprocess.Popen(
                [sys.executable, "-S", os.path.abspath(__file__),
                 self.base_path, str(self.max_files),
                 str(self.max_file_size_mb)],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                start_new_session=True,
                cwd="/",
            )
        except OSError as e:
            LOG.warning("logmon %s: spawn failed (%s); collecting "
                        "in-process (logs will not survive agent "
                        "restart)", self.base_path, e)
            self._start_inproc()
            return
        # wait for the collector to own the FIFO + pidfile
        deadline = time.time() + 10
        while time.time() < deadline:
            pid = self._read_pidfile()
            if pid is not None and os.path.exists(self.fifo_path):
                self._pid = pid
                self._proc = proc      # our child: reap it on stop
                return
            if proc.poll() is not None:
                break
            time.sleep(0.01)
        LOG.warning("logmon %s: collector did not come up; collecting "
                    "in-process", self.base_path)
        self._start_inproc()

    def _start_inproc(self) -> None:
        self._inproc = _Collector(self.base_path, self.max_files,
                                  self.max_file_size_mb)
        self._inproc.open()
        self._inproc_thread = threading.Thread(
            target=self._inproc.run, daemon=True,
            name=f"logmon-{os.path.basename(self.base_path)}",
        )
        self._inproc_thread.start()

    def _read_pidfile(self) -> Optional[int]:
        try:
            with open(self.pid_path) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def stop(self) -> None:
        """Terminate the collector (task is done). NOT called on agent
        shutdown with a live task — the collector must outlive us."""
        if self._inproc is not None:
            self._inproc.request_stop()
            if self._inproc_thread is not None:
                self._inproc_thread.join(timeout=2)
            self._inproc = None
            self._inproc_thread = None
            return
        if self._pid is not None:
            try:
                os.kill(self._pid, signal.SIGTERM)
            except OSError:
                pass
            if self._proc is not None:
                # our own child: reap it, or it lingers as a zombie
                try:
                    self._proc.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    self._proc.kill()
                    try:
                        self._proc.wait(timeout=2)
                    except subprocess.TimeoutExpired:
                        pass
                self._proc = None
            else:
                # adopted collector (previous agent life): init reaps it
                deadline = time.time() + 3
                while time.time() < deadline and _pid_alive(self._pid):
                    time.sleep(0.02)
                if _pid_alive(self._pid):
                    try:
                        os.kill(self._pid, signal.SIGKILL)
                    except OSError:
                        pass
            self._pid = None
        try:
            os.unlink(self.pid_path)
        except OSError:
            pass


def read_rotated(base_path: str, offset: int = 0, limit: int = 0) -> bytes:
    """Concatenated read across the rotation chain ``base.N`` in index
    order (fs_endpoint.go Logs stitches frames the same way)."""
    out = []
    remaining = limit if limit > 0 else None
    skip = offset
    for path in rotated_files(base_path):
        try:
            size = os.path.getsize(path)
        except OSError:
            continue
        if skip >= size:
            skip -= size
            continue
        with open(path, "rb") as f:
            if skip:
                f.seek(skip)
                skip = 0
            data = f.read(remaining if remaining is not None else -1)
        out.append(data)
        if remaining is not None:
            remaining -= len(data)
            if remaining <= 0:
                break
    return b"".join(out)


def rotated_files(base_path: str) -> List[str]:
    found: List[Tuple[int, str]] = []
    for path in glob.glob(base_path + ".*"):
        m = re.fullmatch(re.escape(base_path) + r"\.(\d+)", path)
        if m:
            found.append((int(m.group(1)), path))
    return [p for _i, p in sorted(found)]


def _collector_main(argv: List[str]) -> int:
    """``python -m nomad_tpu.client.logmon <base> <max_files> <max_mb>``
    — the collector process entry (logmon.go main loop)."""
    if len(argv) != 3:
        print("usage: logmon <base_path> <max_files> <max_file_size_mb>",
              file=sys.stderr)
        return 2
    base, max_files, max_mb = argv[0], int(argv[1]), int(argv[2])
    collector = _Collector(base, max_files, max_mb)
    collector.open()
    pid_path = base + ".logmon.pid"
    with open(pid_path, "w") as f:
        f.write(str(os.getpid()))
    signal.signal(signal.SIGTERM, lambda *_: collector.request_stop())
    signal.signal(signal.SIGHUP, signal.SIG_IGN)   # agent exit is not ours
    # Reattach semantics want the collector to OUTLIVE the agent; test
    # harnesses want the opposite (a suite spawning hundreds of agents
    # must not leak hundreds of pollers). With the env toggle set, the
    # collector also exits once its spawning agent is gone.
    should_exit = None
    if os.environ.get("NOMAD_TPU_LOGMON_ORPHAN_EXIT") == "1":
        # orphaning is detected as REPARENTING (ppid changed away from
        # the spawning agent), not as "parent is pid 1" — the agent
        # itself may legitimately BE pid 1 (container entrypoint), in
        # which case this signal never fires and the alloc-dir check
        # remains the only exit path
        parent = os.getppid()
        should_exit = (lambda: os.getppid() != parent
                       or not _pid_alive(parent))
    collector.run(should_exit)
    try:
        os.unlink(pid_path)
    except OSError:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(_collector_main(sys.argv[1:]))
