"""Per-task log collection with rotation (the logmon analog).

Reference behavior: client/logmon/logmon.go runs a separate process
per task that reads the task's stdout/stderr through FIFOs and writes
size-rotated files ``<task>.<stream>.N`` (rotator in
client/lib/fifo + logmon/logging/rotator.go), honoring the task's
LogConfig (max_files / max_file_size_mb). Here logmon is a thread in
the client agent reading the same kind of FIFO: the driver (or the
native executor, which open(2)s the path it is given) writes into the
FIFO; the reader rotates on size and prunes old indexes. fs 'logs'
reads concatenate the rotated chain in index order.
"""

from __future__ import annotations

import errno
import glob
import logging
import os
import re
import select
import threading
from typing import List, Optional, Tuple

LOG = logging.getLogger(__name__)


class LogMon:
    """One rotating collector for one task stream.

    ``base_path`` is the unsuffixed target (".../web.stdout"); output
    files are ``base_path.N``. The write side is ``fifo_path`` —
    hand it to the driver as the task's stdout/stderr path.
    """

    def __init__(self, base_path: str, max_files: int = 10,
                 max_file_size_mb: int = 10) -> None:
        self.base_path = base_path
        self.fifo_path = base_path + ".fifo"
        self.max_files = max(1, max_files)
        self.max_bytes = max(1, max_file_size_mb) * 1024 * 1024
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fd: Optional[int] = None
        self._idx = 0
        self._out = None
        self._written = 0

    def start(self) -> None:
        os.makedirs(os.path.dirname(self.base_path), exist_ok=True)
        try:
            os.mkfifo(self.fifo_path)
        except FileExistsError:
            pass
        # O_RDWR keeps the read end open across writer restarts (task
        # restarts reopen the FIFO) and makes this open non-blocking
        self._fd = os.open(self.fifo_path, os.O_RDWR | os.O_NONBLOCK)
        # resume at the highest existing index (agent restart must not
        # interleave new output into already-rotated files)
        existing = rotated_files(self.base_path)
        if existing:
            self._idx = int(existing[-1].rsplit(".", 1)[1])
        self._open_current()
        if self._written >= self.max_bytes:
            self._rotate()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"logmon-{os.path.basename(self.base_path)}",
        )
        self._thread.start()

    def _open_current(self) -> None:
        path = f"{self.base_path}.{self._idx}"
        self._out = open(path, "ab")
        self._written = self._out.tell()

    def _rotate(self) -> None:
        self._out.close()
        self._idx += 1
        self._open_current()
        # prune beyond max_files (rotator.go purgeOldFiles)
        doomed = self._idx - self.max_files
        if doomed >= 0:
            try:
                os.unlink(f"{self.base_path}.{doomed}")
            except OSError:
                pass

    def _run(self) -> None:
        while not self._stop.is_set():
            r, _, _ = select.select([self._fd], [], [], 0.2)
            if not r:
                continue
            try:
                chunk = os.read(self._fd, 65536)
            except OSError as e:
                if e.errno == errno.EAGAIN:
                    continue
                break
            if not chunk:
                continue
            self._out.write(chunk)
            self._out.flush()
            self._written += len(chunk)
            if self._written >= self.max_bytes:
                self._rotate()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        if self._fd is not None:
            # drain what the writer flushed before it exited — a
            # fast-exiting task's tail output is still in the FIFO
            # buffer when the runner stops the collector
            while True:
                try:
                    chunk = os.read(self._fd, 65536)
                except OSError:
                    break
                if not chunk:
                    break
                self._out.write(chunk)
            os.close(self._fd)
            self._fd = None
        if self._out is not None:
            self._out.close()
            self._out = None
        try:
            os.unlink(self.fifo_path)
        except OSError:
            pass


def read_rotated(base_path: str, offset: int = 0, limit: int = 0) -> bytes:
    """Concatenated read across the rotation chain ``base.N`` in index
    order (fs_endpoint.go Logs stitches frames the same way)."""
    out = []
    remaining = limit if limit > 0 else None
    skip = offset
    for path in rotated_files(base_path):
        try:
            size = os.path.getsize(path)
        except OSError:
            continue
        if skip >= size:
            skip -= size
            continue
        with open(path, "rb") as f:
            if skip:
                f.seek(skip)
                skip = 0
            data = f.read(remaining if remaining is not None else -1)
        out.append(data)
        if remaining is not None:
            remaining -= len(data)
            if remaining <= 0:
                break
    return b"".join(out)


def rotated_files(base_path: str) -> List[str]:
    found: List[Tuple[int, str]] = []
    for path in glob.glob(base_path + ".*"):
        m = re.fullmatch(re.escape(base_path) + r"\.(\d+)", path)
        if m:
            found.append((int(m.group(1)), path))
    return [p for _i, p in sorted(found)]
