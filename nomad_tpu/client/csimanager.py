"""Client-side CSI volume mount lifecycle.

Reference behavior: client/pluginmanager/csimanager/ -- the
``volumeManager`` stages and publishes CSI volumes for claiming
allocations (volume.go MountVolume: NodeStageVolume once per volume,
NodePublishVolume per alloc into the alloc dir) and unpublishes on
release (UnmountVolume). Claims are made against the server first
(allocrunner/csi_hook.go Claim RPC), which controller-publishes when
the plugin requires it.

The usage counter mirrors csimanager's ref-counted staging: the last
alloc to unmount a volume on the node also unstages it.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional, Tuple

from nomad_tpu.structs import csi as csi_structs

LOG = logging.getLogger(__name__)


class CSIMountInfo:
    def __init__(self, source: str, target_path: str,
                 plugin_id: str = "", external_id: str = "") -> None:
        self.source = source
        self.target_path = target_path
        self.plugin_id = plugin_id
        self.external_id = external_id


class CSIManager:
    def __init__(self, rpc, csi_clients: Dict[str, object],
                 node_id: str, data_dir: str) -> None:
        self.rpc = rpc                       # ClientRPC: csi_claim verb
        self.csi_clients = csi_clients       # plugin_id -> CSIClient
        self.node_id = node_id
        self.data_dir = data_dir
        self._lock = threading.Lock()
        # volume id -> set of alloc ids using its staged mount
        self._usage: Dict[str, set] = {}

    def _staging_path(self, vol) -> str:
        return os.path.join(self.data_dir, "csi", "staging", vol.id)

    def _target_path(self, vol, alloc_id: str) -> str:
        return os.path.join(self.data_dir, "csi", "per-alloc", alloc_id, vol.id)

    def mount_volume(self, alloc, vol_req) -> CSIMountInfo:
        """csi_hook.go Prerun: claim against the server, then stage +
        publish through the node plugin."""
        mode = csi_structs.CLAIM_READ if vol_req.read_only \
            else csi_structs.CLAIM_WRITE
        # the claim records the exact paths this node will publish at,
        # so the server-side unpublish workflow can replay them
        claim = csi_structs.CSIVolumeClaim(
            alloc_id=alloc.id, node_id=self.node_id, mode=mode,
            access_mode=vol_req.access_mode,
            attachment_mode=vol_req.attachment_mode,
        )
        claim.staging_path = os.path.join(
            self.data_dir, "csi", "staging", vol_req.source
        )
        claim.target_path = os.path.join(
            self.data_dir, "csi", "per-alloc", alloc.id, vol_req.source
        )
        vol = self.rpc.csi_claim(alloc.namespace, vol_req.source, claim)
        client = self.csi_clients.get(vol.plugin_id)
        staging = claim.staging_path
        target = claim.target_path
        capability = {
            "access_mode": vol_req.access_mode or (
                vol.requested_capabilities[0].access_mode
                if vol.requested_capabilities else ""
            ),
            "attachment_mode": vol_req.attachment_mode or (
                vol.requested_capabilities[0].attachment_mode
                if vol.requested_capabilities else ""
            ),
        }
        with self._lock:
            first = not self._usage.get(vol.id)
        if client is not None:
            if first:
                client.node_stage_volume(
                    vol.external_id, staging, capability, vol.context
                )
            client.node_publish_volume(
                vol.external_id, staging, target, vol_req.read_only, capability
            )
        else:
            os.makedirs(target, exist_ok=True)
        # count the alloc as a user only once staged+published, so a
        # failed stage doesn't leave a phantom user that makes the next
        # alloc skip staging
        with self._lock:
            self._usage.setdefault(vol.id, set()).add(alloc.id)
        return CSIMountInfo(source=vol_req.source, target_path=target,
                            plugin_id=vol.plugin_id,
                            external_id=vol.external_id)

    def unmount_volume(self, alloc_id: str, mount: CSIMountInfo) -> None:
        """csi_hook.go Postrun: unpublish this alloc's mount; unstage if
        it was the last user on the node."""
        client = self.csi_clients.get(mount.plugin_id)
        with self._lock:
            users = self._usage.get(mount.source, set())
            users.discard(alloc_id)
            last = not users
        if client is not None:
            client.node_unpublish_volume(mount.external_id,
                                         mount.target_path)
            if last:
                client.node_unstage_volume(
                    mount.external_id,
                    os.path.join(self.data_dir, "csi", "staging",
                                 mount.source),
                )
