"""Host fingerprinting: building the Node from the machine.

Reference behavior: client/fingerprint/ (~30 fingerprinters feeding
Node.Attributes/NodeResources via fingerprint_manager.go). Implemented
fingerprinters: arch, cpu, memory, storage, host, nomad version,
network, plus driver fingerprints (via the driver registry) and device
fingerprints (via device plugins -- the TPU fingerprinter surfaces
chips as schedulable NodeDeviceResources).
"""

from __future__ import annotations

import os
import platform
import shutil
import socket
from typing import Callable, Dict, List, Optional

from nomad_tpu import structs
from nomad_tpu.structs import consts


def fingerprint_arch(attrs: Dict, res: structs.NodeResources) -> None:
    attrs["cpu.arch"] = platform.machine()
    attrs["arch"] = platform.machine()


def fingerprint_cpu(attrs: Dict, res: structs.NodeResources) -> None:
    cores = os.cpu_count() or 1
    attrs["cpu.numcores"] = str(cores)
    # without frequency probing assume 1 GHz/core compute units
    # (fingerprint/cpu.go uses MHz x cores for cpu shares)
    mhz = 1000
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = int(float(line.split(":")[1]))
                    break
    except (FileNotFoundError, ValueError, IndexError):
        pass
    attrs["cpu.frequency"] = str(mhz)
    total = mhz * cores
    attrs["cpu.totalcompute"] = str(total)
    res.cpu = structs.NodeCpuResources(
        cpu_shares=total,
        total_core_count=cores,
        reservable_cpu_cores=list(range(cores)),
    )


def fingerprint_memory(attrs: Dict, res: structs.NodeResources) -> None:
    mem_mb = 1024
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    mem_mb = int(line.split()[1]) // 1024
                    break
    except (FileNotFoundError, ValueError, IndexError):
        pass
    attrs["memory.totalbytes"] = str(mem_mb * 1024 * 1024)
    res.memory = structs.NodeMemoryResources(memory_mb=mem_mb)


def fingerprint_storage(attrs: Dict, res: structs.NodeResources, data_dir: str = "/tmp") -> None:
    try:
        usage = shutil.disk_usage(data_dir)
        disk_mb = usage.free // (1024 * 1024)
    except OSError:
        disk_mb = 1024
    attrs["unique.storage.volume"] = data_dir
    attrs["unique.storage.bytesfree"] = str(disk_mb * 1024 * 1024)
    res.disk = structs.NodeDiskResources(disk_mb=int(disk_mb))


def fingerprint_host(attrs: Dict, res: structs.NodeResources) -> None:
    attrs["kernel.name"] = platform.system().lower()
    attrs["kernel.version"] = platform.release()
    attrs["os.name"] = platform.system().lower()
    attrs["os.version"] = platform.version()
    attrs["unique.hostname"] = socket.gethostname()


def fingerprint_nomad(attrs: Dict, res: structs.NodeResources) -> None:
    from nomad_tpu import __version__
    attrs["nomad.version"] = __version__
    attrs["nomad.revision"] = "tpu"


def fingerprint_network(attrs: Dict, res: structs.NodeResources) -> None:
    hostname = socket.gethostname()
    try:
        ip = socket.gethostbyname(hostname)
    except OSError:
        ip = "127.0.0.1"
    attrs["unique.network.ip-address"] = ip
    res.networks = [
        structs.NetworkResource(
            device="eth0", cidr=f"{ip}/32", ip=ip, mbits=1000
        )
    ]


DEFAULT_FINGERPRINTERS: List[Callable] = [
    fingerprint_arch,
    fingerprint_cpu,
    fingerprint_memory,
    fingerprint_storage,
    fingerprint_host,
    fingerprint_nomad,
    fingerprint_network,
]


def fingerprint_node(
    node_id: str,
    datacenter: str = "dc1",
    node_class: str = "",
    drivers: Optional[Dict] = None,
    device_plugins: Optional[List] = None,
    meta: Optional[Dict[str, str]] = None,
) -> structs.Node:
    """Run all fingerprinters into a fresh Node
    (fingerprint_manager.go run + client.go setupNode)."""
    attrs: Dict[str, str] = {}
    res = structs.NodeResources()
    for fp in DEFAULT_FINGERPRINTERS:
        try:
            fp(attrs, res)
        except Exception:                       # noqa: BLE001
            continue
    driver_infos = {}
    for name, drv in (drivers or {}).items():
        try:
            fp = drv.fingerprint()
        except Exception:                       # noqa: BLE001
            continue
        attrs.update(fp.attributes)
        driver_infos[name] = structs.DriverInfo(
            detected=fp.health != "undetected",
            healthy=fp.health == "healthy",
            health_description=fp.health_description,
        )
    for plugin in device_plugins or []:
        try:
            res.devices.extend(plugin.fingerprint())
        except Exception:                       # noqa: BLE001
            continue
    node = structs.Node(
        id=node_id,
        name=socket.gethostname(),
        datacenter=datacenter,
        node_class=node_class,
        attributes=attrs,
        node_resources=res,
        reserved_resources=structs.NodeReservedResources(),
        drivers=driver_infos,
        meta=dict(meta or {}),
        status=consts.NODE_STATUS_INIT,
    )
    node.compute_class()
    return node
