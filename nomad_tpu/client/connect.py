"""Connect hook: sidecar + upstream proxies for service-mesh groups.

Reference behavior: client/allocrunner/taskrunner/envoy_bootstrap_hook.go
+ connect_native_hook.go + the group-service hook's sidecar
registration. For every group service with a ``connect.sidecar_service``
stanza this hook:

1. derives the service's mesh identity token from the server
   (consul.go DeriveSITokens analog — the SecretsClient RPC);
2. launches the INBOUND sidecar proxy (client/connect_proxy.py, the
   envoy stand-in) inside the allocation's network namespace: mesh
   port (the scheduler-assigned ``connect-proxy-<svc>`` dynamic port)
   -> 127.0.0.1:<local service port>, token-gated;
3. launches one UPSTREAM proxy per declared upstream: a loopback
   listener on ``local_bind_port`` inside the namespace that relays to
   the destination's sidecar (resolved from the native service
   registry, re-resolved until it appears) with the token preamble;
4. synthesizes the ``<name>-sidecar-proxy`` service registration so
   other allocations discover the mesh entry point (the Consul sidecar
   service Nomad registers for Connect).

Connect-native services skip the proxies: the hook only derives the
token and exposes it as ``NOMAD_SI_TOKEN_<SVC>`` task env
(connect_native_hook.go workload-identity delivery).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Tuple

from nomad_tpu.structs.job import Service

LOG = logging.getLogger(__name__)

PROXY_PROGRAM = os.path.join(os.path.dirname(__file__), "connect_proxy.py")


class _Proxy:
    __slots__ = ("proc", "desc")

    def __init__(self, proc: subprocess.Popen, desc: str) -> None:
        self.proc = proc
        self.desc = desc


class AllocConnect:
    """Per-allocation mesh state (the hook's runtime handle)."""

    def __init__(self, alloc_id: str) -> None:
        self.alloc_id = alloc_id
        self.proxies: List[_Proxy] = []
        self.sidecar_services: List[Service] = []
        self.env: Dict[str, str] = {}
        self._stop = threading.Event()
        # serializes proxy-list mutation vs destroy so a late resolver
        # thread can never spawn into an already-reaped state
        self._lock = threading.Lock()

    def add_proxy(self, proc: subprocess.Popen,
                  desc: str) -> Optional[_Proxy]:
        """Track a spawned proxy; None (caller must kill it) when
        the alloc was already destroyed."""
        with self._lock:
            if self._stop.is_set():
                return None
            p = _Proxy(proc, desc)
            self.proxies.append(p)
            return p

    def destroy(self) -> None:
        with self._lock:
            self._stop.set()
            proxies = list(self.proxies)
        for p in proxies:
            try:
                p.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        for p in proxies:
            try:
                p.proc.wait(timeout=2)
            except (OSError, subprocess.TimeoutExpired):
                try:
                    p.proc.kill()
                except OSError:
                    pass


class ConnectManager:
    """Launches and tracks sidecar/upstream proxies per allocation."""

    def __init__(self, rpc) -> None:
        self.rpc = rpc

    # -- hook entry ------------------------------------------------------

    def setup(self, alloc, tg, alloc_network) -> Optional[AllocConnect]:
        """Start mesh plumbing for the group's connect services.
        Returns None when the group has none."""
        connect_services = [
            s for s in (tg.services or [])
            if s.has_sidecar() or s.is_connect_native()
        ]
        if not connect_services:
            return None
        state = AllocConnect(alloc.id)
        try:
            self._setup_services(state, alloc, tg, alloc_network,
                                 connect_services)
        except Exception:
            # a partial setup must not leak already-spawned proxies
            state.destroy()
            raise
        return state

    def _setup_services(self, state, alloc, tg, alloc_network,
                        connect_services) -> None:
        for svc in connect_services:
            token = self._mesh_token(alloc, svc)
            env_key = ("NOMAD_SI_TOKEN_"
                       + svc.name.upper().replace("-", "_"))
            state.env[env_key] = token
            if not svc.has_sidecar():
                continue                      # connect-native: token only
            if alloc_network is None:
                raise RuntimeError(
                    f"connect sidecar for {svc.name} requires bridge "
                    "networking on this client")
            self._start_sidecar(state, alloc, svc, alloc_network, token)
            for up in svc.upstreams():
                self._start_upstream(state, alloc, svc, up, alloc_network)
            sidecar = Service(
                name=f"{svc.name}-sidecar-proxy",
                port_label=svc.mesh_port_label(),
                tags=["connect-proxy"] + list(svc.tags),
            )
            state.sidecar_services.append(sidecar)

    # -- internals -------------------------------------------------------

    def _mesh_token(self, alloc, svc: Service) -> str:
        try:
            return self.rpc.mesh_identity_token(alloc.namespace, svc.name,
                                                alloc_id=alloc.id)
        except Exception as e:                  # noqa: BLE001
            raise RuntimeError(
                f"mesh identity token for {svc.name}: {e}") from e

    def _mesh_ports(self, alloc, svc: Service) -> Tuple[int, int]:
        """(host mesh port, in-namespace mesh port) from the alloc's
        scheduler-assigned ports."""
        res = alloc.allocated_resources
        label = svc.mesh_port_label()
        ports = []
        if res is not None and res.shared is not None:
            ports.extend(res.shared.ports)
            for net in res.shared.networks:
                ports.extend(list(net.dynamic_ports)
                             + list(net.reserved_ports))
        for p in ports:
            if p.label == label:
                return p.value, (p.to or p.value)
        raise RuntimeError(
            f"no scheduler-assigned mesh port '{label}' on alloc "
            f"{alloc.id} (connect admission should have injected it)")

    def _local_service_port(self, alloc, svc: Service) -> int:
        proxy = svc.sidecar_proxy()
        port = int(proxy.get("local_service_port") or 0)
        if port:
            return port
        # fall back to the service's own port label's container port
        res = alloc.allocated_resources
        if res is not None and res.shared is not None and svc.port_label:
            for net in res.shared.networks:
                for p in list(net.dynamic_ports) + list(net.reserved_ports):
                    if p.label == svc.port_label:
                        return p.to or p.value
            for p in res.shared.ports:
                if p.label == svc.port_label:
                    return p.to or p.value
        raise RuntimeError(
            f"connect service {svc.name}: no local_service_port and no "
            f"resolvable port label '{svc.port_label}'")

    def _spawn(self, state: AllocConnect, netns: str, cfg: Dict,
               desc: str) -> Optional[_Proxy]:
        argv = ["ip", "netns", "exec", netns, sys.executable, "-S",
                PROXY_PROGRAM, json.dumps(cfg)]
        proc = subprocess.Popen(
            argv, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
        tracked = state.add_proxy(proc, desc)
        if tracked is None:
            # destroy() won between spawn decision and tracking: the
            # alloc is gone, reap the orphan immediately
            try:
                proc.kill()
            except OSError:
                pass
            return None
        LOG.info("connect %s: %s (pid %d)", state.alloc_id[:8], desc,
                 proc.pid)
        return tracked

    def _start_sidecar(self, state, alloc, svc, net, token: str) -> None:
        _host_port, ns_port = self._mesh_ports(alloc, svc)
        local = self._local_service_port(alloc, svc)
        cfg = {
            "mode": "inbound",
            "listen": ["0.0.0.0", ns_port],
            "target": ["127.0.0.1", local],
            "token": token,
        }
        self._spawn(state, net.ns_name, cfg,
                    f"sidecar {svc.name} :{ns_port} -> 127.0.0.1:{local}")

    def _start_upstream(self, state, alloc, svc, upstream: Dict,
                        net) -> None:
        dest = str(upstream.get("destination_name", ""))
        bind = int(upstream.get("local_bind_port") or 0)
        if not dest or not bind:
            raise RuntimeError(
                f"connect upstream on {svc.name}: destination_name and "
                "local_bind_port are required")
        # the preamble presents the DESTINATION service's identity —
        # its inbound gate verifies against the same derived credential
        # (the intentions-allow analog)
        token = self.rpc.mesh_identity_token(alloc.namespace, dest,
                                             alloc_id=alloc.id)

        def resolve(delay: float):
            try:
                regs = self.rpc.services_by_name(
                    alloc.namespace, f"{dest}-sidecar-proxy")
            except Exception as e:              # noqa: BLE001
                LOG.warning("connect upstream %s: resolve: %s", dest, e)
                return None
            if not regs:
                return None
            addr = str(regs[0]["Address"])
            # host-local destinations: inside the namespace, 127.0.0.1
            # is the netns loopback — the node's listeners (port
            # relays) live at the bridge gateway address
            if addr in ("127.0.0.1", "localhost", "0.0.0.0") \
                    and net.gateway:
                addr = net.gateway
            return (addr, int(regs[0]["Port"]))

        def watch() -> None:
            import time as _time

            current = None      # (addr, port) the live proxy targets
            proxy = None
            delay = 0.2
            while not state._stop.is_set():
                target = resolve(delay)
                if target is not None and target != current:
                    # destination appeared or MOVED (rescheduled alloc
                    # gets a new node/mesh port): point the upstream at
                    # the new sidecar — envoy's cluster discovery keeps
                    # endpoints current the same way
                    if proxy is not None:
                        try:
                            proxy.proc.terminate()
                        except OSError:
                            pass
                    cfg = {
                        "mode": "upstream",
                        "listen": ["127.0.0.1", bind],
                        "target": list(target),
                        "token": token,
                    }
                    proxy = self._spawn(
                        state, net.ns_name, cfg,
                        f"upstream {dest} 127.0.0.1:{bind} -> "
                        f"{target[0]}:{target[1]}")
                    if proxy is None:
                        return          # alloc destroyed mid-spawn
                    current = target
                    delay = 5.0         # steady-state watch cadence
                elif current is None:
                    _time.sleep(delay)
                    delay = min(delay * 1.5, 3.0)
                    continue
                state._stop.wait(delay)

        # the destination may not be registered yet (its alloc is still
        # starting) and may move later; watch in the background
        threading.Thread(target=watch, daemon=True,
                         name=f"connect-resolve-{dest}").start()
