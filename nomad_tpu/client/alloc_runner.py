"""AllocRunner: per-allocation lifecycle.

Reference behavior: client/allocrunner/alloc_runner.go -- owns the
alloc dir, runs the hook chain (here: allocdir setup), builds one
TaskRunner per task in the group, aggregates task states into the
alloc's client status (alloc_runner.go clientAlloc/getClientStatus),
and reports updates to the client for batched upload to servers.
"""

from __future__ import annotations

import copy
import logging
import os
import shutil
import threading
import time
from typing import Callable, Dict, List, Optional

from nomad_tpu.client.task_runner import STATE_DEAD, STATE_PENDING, STATE_RUNNING, TaskRunner
from nomad_tpu.structs import consts
from nomad_tpu.structs.alloc import Allocation, TaskState

LOG = logging.getLogger(__name__)


class AllocRunner:
    def __init__(
        self,
        alloc: Allocation,
        drivers: Dict[str, object],
        data_dir: str,
        on_alloc_update: Callable[[Allocation], None],
        state_db=None,
        csi_manager=None,
        service_reg=None,
        secrets=None,
        prev_lookup=None,
        device_plugins=None,
        network_manager=None,
        connect_mgr=None,
    ) -> None:
        self.alloc = alloc
        self.drivers = drivers
        self.data_dir = data_dir
        self.on_alloc_update = on_alloc_update
        self.state_db = state_db
        self.csi_manager = csi_manager
        self.service_reg = service_reg
        self.secrets = secrets
        # resolves a previous alloc id to its local runner
        # (allocwatcher; None for client-less/test topologies)
        self.prev_lookup = prev_lookup
        # device plugins for Reserve (devicemanager; device.proto)
        self.device_plugins = device_plugins or []
        # bridge networking (network_hook.go); None when unsupported
        self.network_manager = network_manager
        self.alloc_network = None
        # (driver, NetworkIsolationSpec) when the group's driver built
        # the namespace itself (DriverNetworkManager)
        self.driver_network = None
        # Connect hook (envoy_bootstrap_hook analog); None without the
        # mesh RPC verbs
        self.connect_mgr = connect_mgr
        self.alloc_connect = None
        # tasks whose services are currently registered
        self._registered_tasks: set = set()
        # volume name -> CSIMountInfo (csi_hook.go populates these for
        # task volume_mounts)
        self.csi_mounts: Dict[str, object] = {}
        self.alloc_dir = os.path.join(data_dir, "allocs", alloc.id)
        self.task_runners: Dict[str, TaskRunner] = {}
        self._lock = threading.Lock()
        self._destroyed = False
        self._stop_requested = False
        # True once run()/restore() is past task-runner creation (or
        # has decided it never will be); _await_previous keys on it so
        # a same-batch predecessor isn't mistaken for "done" while its
        # task_runners dict is still empty
        self._tasks_started = False
        self._waiter: Optional[threading.Thread] = None
        self.task_states: Dict[str, TaskState] = {}

    # --- lifecycle ------------------------------------------------------

    def run(self) -> None:
        """alloc_runner.go Run: prerun hooks then task runners."""
        tg = self.alloc.job.lookup_task_group(self.alloc.task_group) \
            if self.alloc.job is not None else None
        if tg is None:
            LOG.warning("alloc %s: unknown task group %s",
                        self.alloc.id, self.alloc.task_group)
            self._tasks_started = True
            return
        os.makedirs(self.alloc_dir, exist_ok=True)
        # upstream-alloc prerun hook (allocwatcher/alloc_watcher.go):
        # wait out the previous allocation, then migrate its ephemeral
        # disk when the group asks for it
        self._await_previous(tg)
        if self._destroyed or self._stop_requested:
            # stopped/GC'd while waiting: never start tasks for a dead
            # alloc (the wait returns early on both flags)
            self._tasks_started = True
            return
        # CSI prerun hook (allocrunner/csi_hook.go): claim + mount each
        # requested volume before any task starts; a claim failure fails
        # the whole alloc
        if self.csi_manager is not None:
            for name, req in tg.volumes.items():
                if req.type != "csi":
                    continue
                try:
                    self.csi_mounts[name] = \
                        self.csi_manager.mount_volume(self.alloc, req)
                except Exception as e:          # noqa: BLE001
                    LOG.warning("alloc %s: csi mount %s: %s",
                                self.alloc.id, name, e)
                    self._fail_alloc(tg)
                    return
        # bridge-network prerun hook (network_hook.go): a bridge-mode
        # group gets its own netns + veth before any task starts; the
        # scheduler's host ports relay to the alloc's namespace IP
        netns_name = ""
        net_env: Dict[str, str] = {}
        wants_bridge = any(
            getattr(n, "mode", "host") == "bridge" for n in tg.networks
        )
        mappings = self._port_mappings() if wants_bridge else []
        # driver-managed group network (drivers/driver.go:92
        # DriverNetworkManager): when the group's (single) driver MUST
        # own the namespace — docker's pause container — the client
        # delegates instead of building its own netns. Connect sidecar
        # groups stay on the client netns: the mesh proxies enter the
        # namespace via `ip netns exec`, which a driver-owned sandbox
        # does not expose (documented deviation).
        net_driver = self._group_network_driver(tg)
        if net_driver is not None and not any(
                svc.has_sidecar() for svc in tg.services):
            try:
                spec = net_driver.create_network(self.alloc.id, mappings)
            except Exception as e:              # noqa: BLE001
                LOG.warning("alloc %s: driver network setup failed: %s",
                            self.alloc.id, e)
                self._fail_alloc(tg)
                return
            if spec is not None:
                self.driver_network = (net_driver, spec)
                netns_name = spec.netns
                if spec.ip:
                    net_env["NOMAD_ALLOC_IP"] = spec.ip
            # spec None = the driver declined: the client path below
            # owns bridge networking after all
        if wants_bridge and self.driver_network is None \
                and self.network_manager is not None:
            try:
                self.alloc_network = self.network_manager.create(
                    self.alloc.id, mappings)
                netns_name = self.alloc_network.ns_name
                net_env["NOMAD_ALLOC_IP"] = self.alloc_network.ip
            except Exception as e:              # noqa: BLE001
                LOG.warning("alloc %s: bridge network setup failed: %s",
                            self.alloc.id, e)
                self._fail_alloc(tg)
                return
        elif wants_bridge and self.driver_network is None:
            LOG.warning("alloc %s: bridge networking requested but "
                        "unsupported on this client; tasks run in the "
                        "host network", self.alloc.id)
        # connect hook (envoy_bootstrap_hook/connect_native_hook): mesh
        # sidecar + upstream proxies before any task starts, so a
        # task's first upstream dial finds its local listener
        if self.connect_mgr is not None:
            try:
                self.alloc_connect = self.connect_mgr.setup(
                    self.alloc, tg, self.alloc_network)
                if self.alloc_connect is not None:
                    net_env.update(self.alloc_connect.env)
            except Exception as e:              # noqa: BLE001
                LOG.warning("alloc %s: connect setup failed: %s",
                            self.alloc.id, e)
                self._fail_alloc(tg)
                return
        # mount paths surface to tasks as env (the reference bind-mounts
        # them into the task via VolumeMounts; env is this build's
        # equivalent until drivers gain mount plumbing)
        volume_env = {
            f"NOMAD_ALLOC_VOLUME_{name.upper().replace('-', '_')}":
                m.target_path
            for name, m in self.csi_mounts.items()
        }
        for task in tg.tasks:
            driver = self.drivers.get(task.driver)
            if driver is None:
                ts = TaskState(state=STATE_DEAD, failed=True)
                self._on_task_state(task.name, ts)
                LOG.warning("alloc %s: no driver %s", self.alloc.id, task.driver)
                continue
            task_env = dict(volume_env)
            task_env.update(net_env)
            try:
                task_env.update(self._reserve_devices(task.name))
            except Exception as e:              # noqa: BLE001
                LOG.warning("alloc %s: device reserve for %s failed: %s",
                            self.alloc.id, task.name, e)
                self._on_task_state(
                    task.name, TaskState(state=STATE_DEAD, failed=True))
                continue
            tr = TaskRunner(
                alloc=self.alloc,
                task=task,
                driver=driver,
                alloc_dir=self.alloc_dir,
                on_state_change=self._on_task_state,
                state_db=self.state_db,
                restart_policy=tg.restart_policy,
                extra_env=task_env,
                secrets=self.secrets,
                netns=netns_name,
                network_isolation=(self.driver_network[1]
                                   if self.driver_network else None),
            )
            self.task_runners[task.name] = tr
            tr.start()
        self._tasks_started = True
        self._watch_done()

    def _fail_alloc(self, tg) -> None:
        """A prerun hook failed: every task is dead-failed and the
        runner reads as started (so is_done/GC proceed)."""
        for task in tg.tasks:
            self._on_task_state(
                task.name, TaskState(state=STATE_DEAD, failed=True))
        self._tasks_started = True

    def _port_mappings(self) -> List:
        """[(host_port, container_port)] from the scheduler's
        assignment; group ports appear both in shared.ports and inside
        shared.networks."""
        by_host: Dict[int, int] = {}
        res = self.alloc.allocated_resources
        if res is not None:
            for p in res.shared.ports:
                by_host[p.value] = p.to or p.value
            for net in res.shared.networks:
                for p in (list(net.reserved_ports)
                          + list(net.dynamic_ports)):
                    by_host.setdefault(p.value, p.to or p.value)
        return sorted(by_host.items())

    def _group_network_driver(self, tg):
        """The single driver that must own this bridge group's network
        (DriverNetworkManager + MustInitiateNetwork), or None."""
        if not any(getattr(n, "mode", "host") == "bridge"
                   for n in tg.networks):
            return None
        names = {task.driver for task in tg.tasks}
        if len(names) != 1:
            return None
        cand = self.drivers.get(next(iter(names)))
        if cand is not None and cand.capabilities().must_create_network:
            return cand
        return None

    def restore(self) -> None:
        """Rebuild task runners after agent restart, reattaching to live
        tasks (alloc_runner restore path; client.go:1109)."""
        tg = self.alloc.job.lookup_task_group(self.alloc.task_group) \
            if self.alloc.job is not None else None
        if tg is None:
            self._tasks_started = True
            return
        os.makedirs(self.alloc_dir, exist_ok=True)
        # re-adopt a live driver-created network (the pause container
        # outlived the agent with its tasks): destroy() must tear it
        # down and restarted tasks must rejoin it, not the host net.
        # A transiently unreachable engine (boot ordering) is retried —
        # adopting None by mistake would silently split the group's
        # network AND leak the sandbox
        net_driver = self._group_network_driver(tg)
        net_env: Dict[str, str] = {}
        if net_driver is not None:
            spec = None
            for attempt in range(3):
                try:
                    spec = net_driver.recover_network(
                        self.alloc.id, self._port_mappings())
                    break
                except Exception as e:          # noqa: BLE001
                    LOG.warning("alloc %s: network recover attempt %d: %s",
                                self.alloc.id, attempt + 1, e)
                    time.sleep(1.0 + attempt)
            if spec is not None:
                self.driver_network = (net_driver, spec)
                if spec.ip:
                    net_env["NOMAD_ALLOC_IP"] = spec.ip
        for task in tg.tasks:
            driver = self.drivers.get(task.driver)
            if driver is None:
                continue
            try:
                device_env = self._reserve_devices(task.name)
            except Exception as e:              # noqa: BLE001
                LOG.warning("alloc %s: device reserve on restore: %s",
                            self.alloc.id, e)
                device_env = {}
            tr = TaskRunner(
                alloc=self.alloc,
                task=task,
                driver=driver,
                alloc_dir=self.alloc_dir,
                on_state_change=self._on_task_state,
                state_db=self.state_db,
                restart_policy=tg.restart_policy,
                extra_env=dict(device_env, **net_env),
                secrets=self.secrets,
                network_isolation=(self.driver_network[1]
                                   if self.driver_network else None),
            )
            local_state, handle = (None, None)
            if self.state_db is not None:
                local_state, handle = self.state_db.get_task_state(
                    self.alloc.id, task.name
                )
            recovered = tr.restore(local_state, handle)
            self.task_runners[task.name] = tr
            if recovered:
                # reattached to a live task: re-assert its service
                # registrations (deterministic ids make this an
                # idempotent upsert) so the dead-task path knows to
                # deregister later
                if self.service_reg is not None:
                    with self._lock:
                        first = not self._registered_tasks
                        self._registered_tasks.add(task.name)
                    if first:
                        self.service_reg.register(self.alloc, tg.services)
                    self.service_reg.register(self.alloc, task.services,
                                              task.name)
            elif local_state is None or local_state.state != STATE_DEAD:
                # task wasn't running anymore: start fresh
                tr.start()
        self._tasks_started = True
        self._watch_done()

    def _watch_done(self) -> None:
        self._waiter = threading.Thread(
            target=self._wait_all, daemon=True,
            name=f"alloc-{self.alloc.id[:8]}",
        )
        self._waiter.start()
        if self.alloc.deployment_id:
            threading.Thread(
                target=self._watch_health, daemon=True,
                name=f"health-{self.alloc.id[:8]}",
            ).start()

    def _watch_health(self) -> None:
        """Deployment health watcher (allocrunner allocHealthWatcher /
        health_hook.go): healthy once every task has been running
        continuously for min_healthy_time; unhealthy on task failure or
        the healthy deadline."""
        from nomad_tpu.structs.alloc import AllocDeploymentStatus
        from nomad_tpu.structs.job import UpdateStrategy

        tg = self.alloc.job.lookup_task_group(self.alloc.task_group) \
            if self.alloc.job is not None else None
        update = (tg.update if tg is not None and tg.update is not None
                  else UpdateStrategy())
        deadline = time.time() + update.healthy_deadline_s
        healthy_since = None
        while time.time() < deadline and not self._destroyed:
            with self._lock:
                states = dict(self.task_states)
            if states and any(s.state == STATE_DEAD and s.failed
                              for s in states.values()):
                self._report_health(False)
                return
            tasks = (len(tg.tasks) if tg is not None else 0) or 1
            all_running = (
                len(states) >= tasks
                and all(s.state == STATE_RUNNING for s in states.values())
            )
            if all_running:
                healthy_since = healthy_since or time.time()
                if time.time() - healthy_since >= update.min_healthy_time_s:
                    self._report_health(True)
                    return
            else:
                healthy_since = None
            time.sleep(0.05)
        if not self._destroyed:
            self._report_health(False)

    def _report_health(self, healthy: bool) -> None:
        from nomad_tpu.structs.alloc import AllocDeploymentStatus

        updated = self.alloc.copy_skip_job()
        with self._lock:
            updated.task_states = dict(self.task_states)
        updated.client_status = self.client_status()
        updated.deployment_status = AllocDeploymentStatus(
            healthy=healthy, timestamp_ns=time.time_ns(),
        )
        self.on_alloc_update(updated)

    def _wait_all(self) -> None:
        for tr in list(self.task_runners.values()):
            tr.wait()

    # --- state aggregation (alloc_runner.go getClientStatus) ------------

    def _on_task_state(self, task_name: str, state: TaskState) -> None:
        # deep-copy at the boundary: the TaskRunner keeps mutating its
        # state object, and everything downstream (client batch, server
        # store, raft snapshot pickling) must own immutable rows
        state = copy.deepcopy(state)
        with self._lock:
            self.task_states[task_name] = state
            status, desc = self._client_status_locked()
        self._sync_services(task_name, state)
        updated = self.alloc.copy_skip_job()
        updated.client_status = status
        updated.client_description = desc
        updated.task_states = dict(self.task_states)
        self.on_alloc_update(updated)

    def _sync_services(self, task_name: str, state: TaskState) -> None:
        """Register a task's (and the group's) services when it starts
        running; pull everything when the alloc goes terminal
        (client/serviceregistration workload lifecycle)."""
        if self.service_reg is None:
            return
        tg = self.alloc.job.lookup_task_group(self.alloc.task_group) \
            if self.alloc.job is not None else None
        if tg is None:
            return
        if not tg.services and not any(t.services for t in tg.tasks):
            return
        if state.state == STATE_RUNNING:
            with self._lock:
                first = not self._registered_tasks
                fresh = task_name not in self._registered_tasks
                self._registered_tasks.add(task_name)
            if first:
                group_services = list(tg.services)
                if self.alloc_connect is not None:
                    # the sidecar's own registration is the mesh entry
                    # point other allocs' upstreams discover (the
                    # Consul sidecar service Nomad registers)
                    group_services += self.alloc_connect.sidecar_services
                self.service_reg.register(self.alloc, group_services)
            if fresh:
                task = tg.lookup_task(task_name)
                if task is not None:
                    self.service_reg.register(self.alloc, task.services,
                                              task_name)
        elif state.state == STATE_DEAD:
            with self._lock:
                terminal = all(s.state == STATE_DEAD
                               for s in self.task_states.values())
                was_registered = task_name in self._registered_tasks
                self._registered_tasks.discard(task_name)
            if terminal:
                # covers group services and any strays (also correct
                # after agent restart, where _registered_tasks was
                # rebuilt only from recovered tasks)
                self.service_reg.deregister_alloc(self.alloc.id)
            elif was_registered:
                # a dead task among live siblings pulls only its own
                # instances
                task = tg.lookup_task(task_name)
                if task is not None:
                    self.service_reg.deregister_task(
                        self.alloc, task.services, task_name
                    )

    def _client_status_locked(self) -> (str, str):
        states = list(self.task_states.values())
        if not states:
            return consts.ALLOC_CLIENT_PENDING, "no tasks have started"
        if any(s.state == STATE_RUNNING for s in states):
            return consts.ALLOC_CLIENT_RUNNING, "tasks are running"
        if all(s.state == STATE_DEAD for s in states):
            if any(s.failed for s in states):
                return consts.ALLOC_CLIENT_FAILED, "failed tasks"
            return consts.ALLOC_CLIENT_COMPLETE, "all tasks have completed"
        if any(s.state == STATE_DEAD and s.failed for s in states):
            return consts.ALLOC_CLIENT_FAILED, "failed tasks"
        return consts.ALLOC_CLIENT_PENDING, "no tasks have started"

    def client_status(self) -> str:
        with self._lock:
            return self._client_status_locked()[0]

    # --- filesystem + stats API (client fs_endpoint.go /
    #     alloc_endpoint.go surfaces) ------------------------------------

    def _safe_path(self, rel: str) -> str:
        """Confine API paths to the alloc dir (helper/escapingfs); task
        secrets dirs are never readable over the fs API
        (fs_endpoint.go denies SecretsDir)."""
        rel = rel.lstrip("/")
        full = os.path.realpath(os.path.join(self.alloc_dir, rel))
        root = os.path.realpath(self.alloc_dir)
        if not (full == root or full.startswith(root + os.sep)):
            raise PermissionError(f"path escapes allocation directory: {rel}")
        parts = os.path.relpath(full, root).split(os.sep)
        if "secrets" in parts:
            raise PermissionError("secrets directories are not accessible")
        return full

    def _reserve_devices(self, task_name: str):
        """devicemanager Reserve (device.proto Reserve -> container
        env/mounts): for each device the scheduler assigned to this
        task, ask the owning plugin how to expose it — e.g. the TPU
        plugin returns TPU_VISIBLE_DEVICES. Raises when a reservation
        fails: starting the task anyway would let the workload see
        devices reserved by other allocs (device_hook prestart fails
        the task in the reference)."""
        env = {}
        ar = self.alloc.allocated_resources
        if ar is None or not self.device_plugins:
            return env
        task_res = ar.tasks.get(task_name)
        if task_res is None or not task_res.devices:
            return env
        # enumerate each plugin once (fingerprint can be expensive:
        # the TPU plugin talks to the runtime)
        plugin_groups = []
        for plugin in self.device_plugins:
            try:
                plugin_groups.append((plugin, plugin.fingerprint()))
            except Exception:                   # noqa: BLE001
                continue
        for dev in task_res.devices:
            owner = next(
                (p for p, groups in plugin_groups
                 if any(g.vendor == dev.vendor and g.type == dev.type
                        and (not dev.name or g.name == dev.name)
                        for g in groups)),
                None,
            )
            if owner is None:
                raise RuntimeError(
                    f"no device plugin owns {dev.id_string()}")
            resp = owner.reserve(dev.device_ids)
            env.update(resp.container_res)
        return env

    def _await_previous(self, tg) -> None:
        """allocwatcher prevAllocWaiter: a replacement alloc
        (blue/green update, reschedule on the same node) must not start
        until its predecessor's tasks have stopped; sticky/migrate
        ephemeral disks then move the old alloc data dir over."""
        prev_id = self.alloc.previous_allocation
        if not prev_id or self.prev_lookup is None:
            return
        prev = self.prev_lookup(prev_id)
        if prev is None:
            return   # remote predecessor or already GC'd locally
        while not (prev._tasks_started and prev.is_done()) \
                and not self._destroyed and not self._stop_requested:
            time.sleep(0.05)
        if self._destroyed or self._stop_requested:
            return
        disk = getattr(tg, "ephemeral_disk", None)
        if disk is None or not (disk.sticky or disk.migrate):
            return
        src = os.path.join(prev.alloc_dir, "alloc")
        dst = os.path.join(self.alloc_dir, "alloc")
        if not os.path.isdir(src):
            return
        try:
            shutil.copytree(src, dst, dirs_exist_ok=True)
            LOG.info("alloc %s: migrated ephemeral disk from %s",
                     self.alloc.id[:8], prev_id[:8])
        except OSError as e:
            LOG.warning("alloc %s: disk migration failed: %s",
                        self.alloc.id[:8], e)

    def task_logs_bytes(self, task: str, logtype: str = "stdout",
                        offset: int = 0, limit: int = 0) -> bytes:
        """Raw read across the logmon rotation chain
        <task>.<type>.N in index order."""
        from nomad_tpu.client.logmon import read_rotated

        base = self._safe_path(
            os.path.join("alloc", "logs", f"{task}.{logtype}")
        )
        return read_rotated(base, offset=offset, limit=limit)

    def task_logs(self, task: str, logtype: str = "stdout",
                  offset: int = 0, limit: int = 0) -> str:
        """fs_endpoint.go Logs (non-follow read)."""
        return self.task_logs_bytes(
            task, logtype, offset=offset, limit=limit
        ).decode(errors="replace")

    def list_dir(self, rel: str = "/") -> List[Dict]:
        """fs_endpoint.go List."""
        path = self._safe_path(rel)
        if not os.path.isdir(path):
            raise FileNotFoundError(rel)
        out = []
        for name in sorted(os.listdir(path)):
            st = os.stat(os.path.join(path, name))
            out.append({
                "Name": name,
                "IsDir": os.path.isdir(os.path.join(path, name)),
                "Size": st.st_size,
                "ModTime": st.st_mtime,
            })
        return out

    def stat_file(self, rel: str) -> Dict:
        """fs_endpoint.go Stat."""
        path = self._safe_path(rel)
        if not os.path.exists(path):
            raise FileNotFoundError(rel)
        st = os.stat(path)
        return {
            "Name": os.path.basename(path) or "/",
            "IsDir": os.path.isdir(path),
            "Size": st.st_size,
            "ModTime": st.st_mtime,
        }

    def cat_file(self, rel: str, offset: int = 0, limit: int = 0) -> bytes:
        """fs_endpoint.go Cat/ReadAt."""
        path = self._safe_path(rel)
        if os.path.isdir(path):
            raise IsADirectoryError(rel)
        with open(path, "rb") as f:
            if offset:
                f.seek(offset)
            return f.read(limit or -1)

    def stats(self) -> Dict:
        """Per-task resource usage (AllocStats / TaskStats)."""
        tasks = {}
        for name, tr in self.task_runners.items():
            try:
                tasks[name] = tr.driver.task_stats(tr.task_id)
            except Exception:                   # noqa: BLE001
                tasks[name] = {}
        return {"Tasks": tasks}

    def restart_tasks(self, task_name: str = "") -> None:
        """alloc_endpoint.go Restart: bounce task(s) in place."""
        if task_name and task_name not in self.task_runners:
            raise KeyError(f"unknown task {task_name}")
        for name, tr in self.task_runners.items():
            if task_name and name != task_name:
                continue
            tr.restart("restart requested by user")

    def signal_tasks(self, signal: str, task_name: str = "") -> None:
        """alloc_endpoint.go Signal."""
        if task_name and task_name not in self.task_runners:
            raise KeyError(f"unknown task {task_name}")
        for name, tr in self.task_runners.items():
            if task_name and name != task_name:
                continue
            try:
                tr.driver.signal_task(tr.task_id, signal)
            except Exception as e:              # noqa: BLE001
                LOG.warning("signal %s to %s: %s", signal, name, e)

    def exec_in_task(self, task_name: str, cmd: List[str],
                     timeout: float = 30.0) -> Dict:
        """alloc_endpoint.go Exec (non-interactive one-shot)."""
        tr = self.task_runners.get(task_name)
        if tr is None:
            raise KeyError(f"unknown task {task_name}")
        return tr.driver.exec_task(tr.task_id, cmd, timeout=timeout)

    def exec_stream_in_task(self, task_name: str, cmd: List[str],
                            tty: bool = False):
        """Interactive exec (alloc exec; driver.proto:79
        ExecTaskStreaming). Returns the driver's ExecStream."""
        tr = self.task_runners.get(task_name)
        if tr is None:
            raise KeyError(f"unknown task {task_name}")
        fn = getattr(tr.driver, "exec_task_streaming", None)
        if fn is None:
            raise NotImplementedError(
                f"driver {tr.task.driver} does not support interactive exec"
            )
        return fn(tr.task_id, cmd, tty=tty)

    # --- updates / teardown ---------------------------------------------

    def update(self, alloc: Allocation) -> None:
        """Server pushed a new alloc version (alloc_runner.go Update)."""
        self.alloc = alloc
        if alloc.server_terminal_status():
            self.stop("alloc stopped by server")

    def stop(self, reason: str = "") -> None:
        self._stop_requested = True
        for tr in self.task_runners.values():
            tr.kill(reason)

    def destroy(self) -> None:
        self.stop("alloc destroyed")
        for tr in self.task_runners.values():
            tr.wait(timeout=5)
            try:
                tr.driver.destroy_task(tr.task_id, force=True)
            except Exception:                   # noqa: BLE001
                pass
        # connect postrun: sidecar/upstream proxies die with the alloc
        if self.alloc_connect is not None:
            try:
                self.alloc_connect.destroy()
            except Exception:                   # noqa: BLE001
                pass
            self.alloc_connect = None
        # bridge-network postrun (network_hook.go Postrun)
        if self.network_manager is not None and self.alloc_network is not None:
            try:
                self.network_manager.destroy(self.alloc.id)
            except Exception:                   # noqa: BLE001
                pass
            self.alloc_network = None
        if self.driver_network is not None:
            drv, spec = self.driver_network
            try:
                drv.destroy_network(self.alloc.id, spec)
            except Exception:                   # noqa: BLE001
                pass
            self.driver_network = None
        else:
            # safety net: even when recover/setup never adopted a spec
            # (engine down during restore), a sandbox may exist for
            # this alloc — best-effort teardown by name so it cannot
            # leak past the alloc's life
            tg = self.alloc.job.lookup_task_group(self.alloc.task_group) \
                if self.alloc.job is not None else None
            drv = self._group_network_driver(tg) if tg is not None else None
            if drv is not None:
                try:
                    drv.destroy_network(self.alloc.id, None)
                except Exception:               # noqa: BLE001
                    pass
        # CSI postrun: unpublish this alloc's mounts (csi_hook.go
        # Postrun); the server-side watcher releases the claim itself
        if self.csi_manager is not None:
            for mount in self.csi_mounts.values():
                try:
                    self.csi_manager.unmount_volume(self.alloc.id, mount)
                except Exception as e:          # noqa: BLE001
                    LOG.warning("alloc %s: csi unmount: %s", self.alloc.id, e)
            self.csi_mounts.clear()
        self._destroyed = True
        if self.state_db is not None:
            self.state_db.delete_allocation(self.alloc.id)
        shutil.rmtree(self.alloc_dir, ignore_errors=True)

    def is_done(self) -> bool:
        return all(tr.is_done() for tr in self.task_runners.values())
