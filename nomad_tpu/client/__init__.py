"""Client (node agent) runtime.

Reference behavior: client/ (SURVEY.md section 2.4) -- the node agent:
fingerprints the host into a Node, registers and heartbeats against
servers, watches for assigned allocations with blocking queries, runs
them through allocRunner/TaskRunner hook chains backed by driver
plugins, persists runner state locally for restart recovery, and
reattaches to live tasks after an agent restart.
"""

from nomad_tpu.client.client import Client, ClientConfig, InProcessRPC

__all__ = ["Client", "ClientConfig", "InProcessRPC"]
