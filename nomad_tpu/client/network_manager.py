"""Bridge-mode allocation networking: per-alloc netns + veth + ports.

Reference behavior: client/allocrunner/networking_bridge_linux.go +
network_hook.go — every bridge-mode allocation gets its own network
namespace joined to a shared client bridge through a veth pair, so two
allocations on one node can bind the SAME container port without
conflict, and the scheduler's host-port assignments (NetworkIndex)
map onto each alloc's namespace IP.

Deviations from the reference, both documented:
- the reference wires port maps with iptables DNAT via CNI; this
  environment has no netfilter NAT, so host-port -> alloc-port
  mappings run through the NATIVE splice(2) relay (native/relay.cc):
  one detached epoll process per allocation moving bytes in kernel
  space, surviving agent restarts the way DNAT rules do (pid persisted
  under /tmp/nomad-tpu-relays for teardown). A per-connection Python
  relay remains as the fallback when the binary cannot build.
- DNS/config files are inherited from the host (no per-ns resolv.conf)

Capability-gated: ``bridge_supported()`` probes netns/veth privileges
once; clients without them skip the hook (the reference equally
requires CNI plugins + root).
"""

from __future__ import annotations

import functools
import logging
import socket
import subprocess
import threading
from typing import Dict, List, Optional, Tuple

LOG = logging.getLogger(__name__)

DEFAULT_BRIDGE = "nomadtpu0"
DEFAULT_SUBNET_PREFIX = "172.26.64"     # /20 like the reference default
GATEWAY_HOST = 1


def _run(argv: List[str], timeout: float = 15.0) -> subprocess.CompletedProcess:
    return subprocess.run(argv, capture_output=True, timeout=timeout)


@functools.lru_cache(maxsize=1)
def bridge_supported() -> bool:
    """Can this host create netns + veth? (probe once)"""
    ns = "nomadtpu-probe"
    try:
        if _run(["ip", "netns", "add", ns]).returncode != 0:
            return False
        ok = _run(["ip", "link", "add", "nomadtpu-pr0", "type", "veth",
                   "peer", "name", "nomadtpu-pr1"]).returncode == 0
        _run(["ip", "link", "del", "nomadtpu-pr0"])
        return ok
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        try:
            _run(["ip", "netns", "del", ns])
        except (OSError, subprocess.TimeoutExpired):
            pass


class _PortForward:
    """Userspace host-port -> (alloc_ip, port) TCP relay (the DNAT
    deviation). One listener thread; a pump thread pair per conn."""

    def __init__(self, host_port: int, target_ip: str, target_port: int) -> None:
        self.host_port = host_port
        self.target = (target_ip, target_port)
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", self.host_port))
        self._listener.listen(16)
        self._listener.settimeout(0.5)
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"portmap-{self.host_port}",
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(
                target=self._relay, args=(conn,), daemon=True,
            ).start()

    def _relay(self, conn: socket.socket) -> None:
        try:
            upstream = socket.create_connection(self.target, timeout=10)
        except OSError:
            conn.close()
            return

        def pump(src, dst):
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        t = threading.Thread(target=pump, args=(conn, upstream), daemon=True)
        t.start()
        pump(upstream, conn)
        t.join(timeout=2)
        for s in (conn, upstream):
            try:
                s.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass


class _UdpForward:
    """Userspace UDP host-port -> (alloc_ip, port) relay (the CNI
    portmap udp rule analog; fallback when the native relay cannot
    build). NAT-style sessions: a datagram from a new client address
    opens a connected socket to the target so replies route back."""

    IDLE_SECS = 120.0

    def __init__(self, host_port: int, target_ip: str, target_port: int) -> None:
        self.host_port = host_port
        self.target = (target_ip, target_port)
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        # client addr -> [session socket, last_active, client addr];
        # _by_sock mirrors it keyed by the session socket so replies
        # avoid an O(sessions) scan per datagram
        self._sessions: Dict[tuple, list] = {}
        self._by_sock: Dict[socket.socket, list] = {}

    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", self.host_port))
        self._sock.settimeout(0.5)
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"udpmap-{self.host_port}")
        self._thread.start()

    def _loop(self) -> None:
        import select
        import time as _time

        while not self._stop.is_set():
            socks = [self._sock] + [e[0] for e in self._sessions.values()]
            try:
                ready, _, _ = select.select(socks, [], [], 0.5)
            except OSError:
                break
            now = _time.monotonic()
            for s in ready:
                if s is self._sock:
                    try:
                        data, addr = self._sock.recvfrom(65536)
                    except OSError:
                        continue
                    entry = self._sessions.get(addr)
                    if entry is None:
                        sess = socket.socket(socket.AF_INET,
                                             socket.SOCK_DGRAM)
                        sess.connect(self.target)
                        sess.setblocking(False)
                        entry = [sess, now, addr]
                        self._sessions[addr] = entry
                        self._by_sock[sess] = entry
                    entry[1] = now
                    try:
                        entry[0].send(data)
                    except OSError:
                        pass
                else:
                    entry = self._by_sock.get(s)
                    if entry is None:
                        continue
                    try:
                        data = s.recv(65536)
                    except OSError:
                        continue
                    entry[1] = now
                    try:
                        self._sock.sendto(data, entry[2])
                    except OSError:
                        pass
            for addr in [a for a, e in self._sessions.items()
                         if now - e[1] > self.IDLE_SECS]:
                entry = self._sessions.pop(addr)
                self._by_sock.pop(entry[0], None)
                try:
                    entry[0].close()
                except OSError:
                    pass

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # snapshot: the loop thread may mutate the dict until it
        # notices the stop flag
        for entry in list(self._sessions.values()):
            try:
                entry[0].close()
            except OSError:
                pass


RELAY_STATE_DIR = "/tmp/nomad-tpu-relays"


class _NativeRelay:
    """Detached native/relay.cc process carrying every port map of one
    allocation (the DNAT analog: kernel-space splice, survives agent
    restarts; the pid is persisted for teardown)."""

    def __init__(self, alloc_id: str, pid: int, status_path: str) -> None:
        self.alloc_id = alloc_id
        self.pid = pid
        self.status_path = status_path

    def stop(self) -> None:
        import os
        import signal as _signal

        try:
            os.kill(self.pid, _signal.SIGTERM)
        except OSError:
            pass
        try:
            os.unlink(self.status_path)
        except OSError:
            pass

    @classmethod
    def spawn(cls, alloc_id: str,
              mappings: List[Tuple[int, int]], target_ip: str,
              timeout: float = 5.0) -> "_NativeRelay":
        import os
        import time

        from nomad_tpu.drivers.rawexec import executor_path

        # the relay builds with the executor (same Makefile)
        if executor_path() is None:
            raise RuntimeError("native toolchain unavailable")
        binary = os.path.join(
            os.path.dirname(executor_path()), "relay")
        if not os.path.exists(binary):
            raise RuntimeError("native relay binary missing")
        os.makedirs(RELAY_STATE_DIR, exist_ok=True)
        status = os.path.join(RELAY_STATE_DIR, f"{alloc_id}.status")
        try:
            os.unlink(status)
        except OSError:
            pass
        specs = [f"{host}:{target_ip}:{cont}"
                 for host, cont in mappings]
        proc = subprocess.Popen(
            [binary, status] + specs,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
        def _abort(msg: str) -> RuntimeError:
            # Kill the spawn before raising: a half-started detached
            # relay would otherwise hold the alloc's host ports so the
            # Python fallback (and any future alloc) could never bind
            # them, and normal destroy() never sees this process.
            try:
                proc.terminate()
            except OSError:
                pass
            try:
                os.unlink(status)
            except OSError:
                pass
            return RuntimeError(msg)

        deadline = time.time() + timeout
        pid = 0
        while time.time() < deadline:
            try:
                with open(status) as f:
                    content = f.read()
            except FileNotFoundError:
                content = ""
            for line in content.splitlines():
                if line.startswith("pid "):
                    pid = int(line.split()[1])
                if line.startswith("error "):
                    raise _abort(f"relay: {line[6:]}")
                if line.startswith("ready "):
                    return cls(alloc_id, pid, status)
            if proc.poll() is not None:
                raise _abort(
                    f"relay exited rc={proc.returncode} before ready")
            time.sleep(0.01)
        raise _abort("relay did not report ready")

    @staticmethod
    def kill_persisted(alloc_id: str) -> None:
        """Teardown after an agent restart: the live process is found
        through the persisted status file, not agent memory."""
        import os
        import signal as _signal

        status = os.path.join(RELAY_STATE_DIR, f"{alloc_id}.status")
        try:
            with open(status) as f:
                for line in f:
                    if line.startswith("pid "):
                        try:
                            os.kill(int(line.split()[1]), _signal.SIGTERM)
                        except OSError:
                            pass
            os.unlink(status)
        except OSError:
            pass


class AllocNetwork:
    """One allocation's namespace + relays (network_hook state)."""

    def __init__(self, alloc_id: str, ns_name: str, ip: str,
                 veth_host: str, forwards: List[_PortForward],
                 gateway: str = "", native_relay=None,
                 port_mappings: Optional[List[Tuple[int, int]]] = None
                 ) -> None:
        self.alloc_id = alloc_id
        self.ns_name = ns_name
        self.ip = ip
        self.veth_host = veth_host
        self.forwards = forwards
        self.native_relay = native_relay
        # kept for the watchdog's respawn (iptables rules can't crash;
        # a relay process can)
        self.port_mappings = list(port_mappings or [])
        # the bridge address: how processes INSIDE the namespace reach
        # host-bound listeners (port relays, other allocs' host ports)
        self.gateway = gateway


class BridgeNetworkManager:
    """Client-wide bridge + per-alloc namespace lifecycle
    (networking_bridge_linux.go bridgeNetworkConfigurator)."""

    #: seconds between relay liveness checks (the "heartbeat" a dead
    #: relay is respawned within)
    WATCHDOG_INTERVAL = 3.0

    def __init__(self, bridge: str = DEFAULT_BRIDGE,
                 subnet_prefix: str = DEFAULT_SUBNET_PREFIX) -> None:
        self.bridge = bridge
        self.subnet_prefix = subnet_prefix
        self._lock = threading.Lock()
        self._used_hosts: set = set()
        self._allocs: Dict[str, AllocNetwork] = {}
        self._bridge_ready = False
        self._watchdog: Optional[threading.Thread] = None
        self._watchdog_stop = threading.Event()

    # -- relay supervision ----------------------------------------------

    def _ensure_watchdog(self) -> None:
        """Supervise native relays: iptables DNAT rules (the reference
        analog) cannot crash, but a relay process can — port maps would
        silently go dead. A dead relay is respawned from the alloc's
        recorded mappings within WATCHDOG_INTERVAL.

        Each watchdog generation carries its OWN stop event: a stopped
        thread keeps its (set) event and exits on its next check, while
        the replacement starts with a fresh event — the stop flag can
        never be cleared out from under a dying loop, so two live loops
        cannot coexist past the ownership check in _watchdog_loop."""
        with self._lock:
            prev = self._watchdog
            if (prev is not None and prev.is_alive()
                    and not self._watchdog_stop.is_set()):
                return
            self._watchdog_stop = stop = threading.Event()
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, args=(stop,), daemon=True,
                name="relay-watchdog")
            self._watchdog.start()

    def stop_watchdog(self) -> None:
        with self._lock:
            self._watchdog_stop.set()

    @staticmethod
    def _relay_alive(pid: int) -> bool:
        # kill(pid, 0) succeeds on zombies (a relay killed while the
        # agent lives is our unreaped child); /proc tells the truth
        try:
            with open(f"/proc/{pid}/stat") as f:
                return f.read().split(")")[1].split()[0] != "Z"
        except OSError:
            return False

    def _watchdog_loop(self, stop: threading.Event) -> None:
        me = threading.current_thread()
        while not stop.wait(self.WATCHDOG_INTERVAL):
            with self._lock:
                # replaced generations stand down: only the CURRENT
                # watchdog holds respawn duty, so a straggling old loop
                # can never double-spawn a relay alongside the new one
                if self._watchdog is not me:
                    return
                nets = [n for n in self._allocs.values()
                        if n.native_relay is not None]
            for net in nets:
                if self._relay_alive(net.native_relay.pid):
                    continue
                with self._lock:
                    # teardown may have raced the check
                    if self._allocs.get(net.alloc_id) is not net:
                        continue
                LOG.warning("alloc %s: native relay pid %d died; "
                            "respawning", net.alloc_id[:8],
                            net.native_relay.pid)
                try:
                    fresh = _NativeRelay.spawn(
                        net.alloc_id, net.port_mappings, net.ip)
                except Exception as e:          # noqa: BLE001
                    LOG.warning("alloc %s: relay respawn failed: %s",
                                net.alloc_id[:8], e)
                    continue
                with self._lock:
                    if (self._allocs.get(net.alloc_id) is net
                            and self._watchdog is me):
                        net.native_relay = fresh
                        fresh = None
                if fresh is not None:
                    # destroy() completed (or this generation was
                    # replaced) while we were spawning: the fresh relay
                    # would leak and hold the host ports forever
                    fresh.stop()

    # -- bridge ----------------------------------------------------------

    def _ensure_bridge(self) -> None:
        if self._bridge_ready:
            return
        if _run(["ip", "link", "show", self.bridge]).returncode != 0:
            out = _run(["ip", "link", "add", "name", self.bridge,
                        "type", "bridge"])
            if out.returncode != 0:
                raise RuntimeError(
                    f"bridge create: {out.stderr.decode(errors='replace')}")
            _run(["ip", "addr", "add",
                  f"{self.subnet_prefix}.{GATEWAY_HOST}/20",
                  "dev", self.bridge])
        _run(["ip", "link", "set", self.bridge, "up"])
        self._adopt_existing()
        self._bridge_ready = True

    def _adopt_existing(self) -> None:
        """Mark IPs held by pre-existing nomad netns as used.

        Namespaces outlive the agent process by design (tasks keep
        running across restarts for reattach, like the reference's
        executor); a fresh in-memory allocator would hand their IPs to
        new allocations and the shared bridge would route new traffic
        into the old namespace. The reference gets this from CNI's
        host-local IPAM lease files; here the running namespaces ARE
        the lease state."""
        out = _run(["ip", "netns", "list"])
        if out.returncode != 0:
            return
        for line in out.stdout.decode(errors="replace").splitlines():
            name = line.split()[0] if line.split() else ""
            if not name.startswith("nomad-"):
                continue
            addrs = _run(["ip", "netns", "exec", name,
                          "ip", "-4", "-o", "addr", "show"])
            for al in addrs.stdout.decode(errors="replace").splitlines():
                if "inet " not in al:
                    continue
                ip = al.split("inet ", 1)[1].split("/", 1)[0]
                if ip.startswith(self.subnet_prefix + "."):
                    try:
                        with self._lock:
                            self._used_hosts.add(int(ip.rsplit(".", 1)[1]))
                    except ValueError:
                        pass

    def _alloc_ip(self) -> str:
        # hosts .2..254 in the third+fourth octet space; _adopt_existing
        # seeds the set with IPs still held by namespaces from previous
        # agent processes
        with self._lock:
            for host in range(2, 255):
                if host not in self._used_hosts:
                    self._used_hosts.add(host)
                    return f"{self.subnet_prefix}.{host}"
        raise RuntimeError("bridge subnet exhausted")

    # -- alloc lifecycle -------------------------------------------------

    def create(self, alloc_id: str,
               port_mappings: List[Tuple[int, int]]) -> AllocNetwork:
        """netns + veth + relays. ``port_mappings`` is
        [(host_port, container_port)] from the scheduler's assignment
        (AllocatedSharedResources.ports)."""
        self._ensure_bridge()
        short = alloc_id.replace("-", "")[:10]
        ns = f"nomad-{short}"
        veth_h, veth_c = f"nv{short[:8]}h", f"nv{short[:8]}c"
        ip = self._alloc_ip()

        steps = [
            ["ip", "netns", "add", ns],
            ["ip", "link", "add", veth_h, "type", "veth",
             "peer", "name", veth_c],
            ["ip", "link", "set", veth_c, "netns", ns],
            ["ip", "link", "set", veth_h, "master", self.bridge],
            ["ip", "link", "set", veth_h, "up"],
            ["ip", "netns", "exec", ns, "ip", "addr", "add",
             f"{ip}/20", "dev", veth_c],
            ["ip", "netns", "exec", ns, "ip", "link", "set", veth_c, "up"],
            ["ip", "netns", "exec", ns, "ip", "link", "set", "lo", "up"],
            ["ip", "netns", "exec", ns, "ip", "route", "add", "default",
             "via", f"{self.subnet_prefix}.{GATEWAY_HOST}"],
        ]
        forwards: List[_PortForward] = []
        native_relay = None
        try:
            for argv in steps:
                out = _run(argv)
                if out.returncode != 0:
                    raise RuntimeError(
                        f"{' '.join(argv)}: "
                        f"{out.stderr.decode(errors='replace').strip()}")
            if port_mappings:
                try:
                    native_relay = _NativeRelay.spawn(
                        alloc_id, port_mappings, ip)
                except Exception as e:          # noqa: BLE001
                    LOG.warning("native relay unavailable (%s); using "
                                "in-process port relays", e)
                    for host_port, container_port in port_mappings:
                        # both protocols per mapping (CNI portmap
                        # programs tcp AND udp DNAT rules)
                        fwd = _PortForward(host_port, ip, container_port)
                        fwd.start()
                        forwards.append(fwd)
                        ufwd = _UdpForward(host_port, ip, container_port)
                        ufwd.start()
                        forwards.append(ufwd)
        except Exception:
            self._teardown(ns, veth_h, ip, forwards, native_relay)
            raise
        net = AllocNetwork(alloc_id, ns, ip, veth_h, forwards,
                           gateway=f"{self.subnet_prefix}.{GATEWAY_HOST}",
                           native_relay=native_relay,
                           port_mappings=port_mappings)
        with self._lock:
            self._allocs[alloc_id] = net
        if native_relay is not None:
            self._ensure_watchdog()
        return net

    def destroy(self, alloc_id: str) -> None:
        with self._lock:
            net = self._allocs.pop(alloc_id, None)
            # stop the watchdog with the last relay-bearing network:
            # without this the daemon thread polls every 3s for the
            # life of the process after all alloc networks are gone
            if not any(n.native_relay is not None
                       for n in self._allocs.values()):
                self._watchdog_stop.set()
        if net is None:
            # an alloc from a previous agent process may still have a
            # live detached relay; the persisted pid file finds it
            _NativeRelay.kill_persisted(alloc_id)
            return
        self._teardown(net.ns_name, net.veth_host, net.ip, net.forwards,
                       net.native_relay)

    def _teardown(self, ns: str, veth_h: str, ip: str,
                  forwards: List[_PortForward], native_relay=None) -> None:
        for fwd in forwards:
            fwd.stop()
        if native_relay is not None:
            native_relay.stop()
        _run(["ip", "netns", "del", ns])
        _run(["ip", "link", "del", veth_h])
        try:
            host = int(ip.rsplit(".", 1)[1])
            with self._lock:
                self._used_hosts.discard(host)
        except (ValueError, IndexError):
            pass

    def network_of(self, alloc_id: str) -> Optional[AllocNetwork]:
        with self._lock:
            return self._allocs.get(alloc_id)
