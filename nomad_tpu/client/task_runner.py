"""TaskRunner: the per-task state machine.

Reference behavior: client/allocrunner/taskrunner/task_runner.go:498
Run loop -- restore -> prestart hooks -> driver start -> wait -> restart
policy -> exit; hooks (task_runner_hooks.go:61-130) here are the
built-in subset: validate, task dir, logs, dispatch env. Restart policy
semantics follow taskrunner/restarts/restarts.go: up to ``attempts``
restarts inside ``interval``; beyond that ``mode=fail`` kills the task,
``mode=delay`` waits out the interval and continues.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from nomad_tpu.plugins.drivers import DriverPlugin, TaskConfig, TaskHandle
from nomad_tpu.structs import consts
from nomad_tpu.structs.alloc import TaskEvent, TaskState
from nomad_tpu.structs.job import RestartPolicy, Task

LOG = logging.getLogger(__name__)

# task_runner event types (structs.go TaskEvent consts)
EVENT_RECEIVED = "Received"
EVENT_TASK_SETUP = "Task Setup"
EVENT_STARTED = "Started"
EVENT_TERMINATED = "Terminated"
EVENT_RESTARTING = "Restarting"
EVENT_NOT_RESTARTING = "Not Restarting"
EVENT_KILLING = "Killing"
EVENT_KILLED = "Killed"
EVENT_DRIVER_FAILURE = "Driver Failure"

STATE_PENDING = "pending"
STATE_RUNNING = "running"
STATE_DEAD = "dead"


class RestartTracker:
    """taskrunner/restarts/restarts.go."""

    def __init__(self, policy: RestartPolicy, job_type: str) -> None:
        self.policy = policy
        self.job_type = job_type
        self.count = 0
        self.interval_start = time.time()

    def next_restart(self, exit_success: bool) -> (str, float):
        """Returns (decision, delay): decision in {restart, fail, exit}."""
        if exit_success and self.job_type in (
            consts.JOB_TYPE_BATCH, consts.JOB_TYPE_SYSBATCH,
        ):
            # batch-family tasks that succeed are done; service/system
            # tasks restart on any exit (restarts.go GetState)
            return "exit", 0.0
        now = time.time()
        if now - self.interval_start > self.policy.interval_s:
            self.interval_start = now
            self.count = 0
        self.count += 1
        if self.count <= self.policy.attempts:
            return "restart", self.policy.delay_s
        if self.policy.mode == "delay":
            remaining = self.policy.interval_s - (now - self.interval_start)
            return "restart", max(remaining, self.policy.delay_s)
        return "fail", 0.0


class TaskRunner:
    def __init__(
        self,
        alloc,
        task: Task,
        driver: DriverPlugin,
        alloc_dir: str,
        on_state_change: Callable[[str, TaskState], None],
        state_db=None,
        restart_policy: Optional[RestartPolicy] = None,
        extra_env: Optional[Dict[str, str]] = None,
        secrets=None,
        netns: str = "",
        network_isolation=None,
    ) -> None:
        self.alloc = alloc
        self.task = task
        self.driver = driver
        self.alloc_dir = alloc_dir
        self.on_state_change = on_state_change
        self.state_db = state_db
        # alloc-level env contributions (e.g. CSI volume mount paths)
        self.extra_env = extra_env or {}
        # bridge-mode network namespace the task must join (network_hook)
        self.netns = netns
        # driver-created group network (DriverNetworkManager spec)
        self.network_isolation = network_isolation
        # Vault/Consul data plane (vault_hook + template_hook sources)
        self.secrets = secrets
        self._vault_token = ""
        self._template_watcher = None
        self._changed_templates: List = []
        self._vault_watch_stop = threading.Event()
        #: token-validity poll cadence (tests shrink this)
        self.vault_poll_interval_s = 5.0
        # logmon collectors keyed by stream — started in prestart; a
        # stream whose collector failed falls back to a plain file
        self._logmons: Dict[str, object] = {}
        self.task_state = TaskState()
        self.handle: Optional[TaskHandle] = None
        policy = restart_policy or RestartPolicy()
        job_type = alloc.job.type if alloc.job is not None else consts.JOB_TYPE_SERVICE
        self.restart_tracker = RestartTracker(policy, job_type)
        self._kill = threading.Event()
        # user-requested restart (alloc_endpoint.go Restart): bounces
        # the task without counting against the restart policy
        self._restart = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._kill_reason = ""

    @property
    def task_id(self) -> str:
        return f"{self.alloc.id[:8]}-{self.task.name}"

    # --- events/state ---------------------------------------------------

    def _emit(self, event_type: str, message: str = "") -> None:
        self.task_state.events.append(
            TaskEvent(type=event_type, time_ns=time.time_ns(), message=message)
        )
        self._notify()

    def _set_state(self, state: str, failed: Optional[bool] = None) -> None:
        self.task_state.state = state
        if failed is not None:
            self.task_state.failed = failed
        if state == STATE_RUNNING and not self.task_state.started_at_ns:
            self.task_state.started_at_ns = time.time_ns()
        if state == STATE_DEAD:
            self.task_state.finished_at_ns = time.time_ns()
        self._notify()

    def _notify(self) -> None:
        self.on_state_change(self.task.name, self.task_state)
        if self.state_db is not None:
            try:
                self.state_db.put_task_state(
                    self.alloc.id, self.task.name,
                    local_state=self.task_state, task_handle=self.handle,
                )
            except Exception as e:              # noqa: BLE001
                LOG.warning("task %s: state persist failed: %s", self.task_id, e)

    # --- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, daemon=True, name=f"task-{self.task_id}"
        )
        self._thread.start()

    def run(self) -> None:
        """task_runner.go:498 Run: the main loop."""
        try:
            self._run_inner()
        except Exception as e:                  # noqa: BLE001
            LOG.warning("task %s: runner crashed: %s", self.task_id, e)
            self._set_state(STATE_DEAD, failed=True)
        finally:
            if self._template_watcher is not None:
                self._template_watcher.stop()
            self._vault_watch_stop.set()
            self._stop_logmons()
            self._done.set()

    def _run_inner(self) -> None:
        self._emit(EVENT_RECEIVED)
        # transient setup failures (artifact downloads) are recoverable
        # and retry under the restart policy (artifact_hook.go wraps as
        # recoverable); config errors (bad template, missing vault
        # block) kill the task immediately, as in the reference
        while True:
            try:
                self._prestart()
                break
            except Exception as e:              # noqa: BLE001
                self._emit(EVENT_TASK_SETUP, f"prestart failed: {e}")
                if not getattr(e, "recoverable", False):
                    self._set_state(STATE_DEAD, failed=True)
                    return
                decision, delay = self.restart_tracker.next_restart(False)
                if decision != "restart" or self._kill.wait(delay):
                    self._set_state(STATE_DEAD, failed=True)
                    return
        while not self._kill.is_set():
            try:
                self.handle = self.driver.start_task(self._task_config())
            except Exception as e:              # noqa: BLE001
                LOG.warning("task %s: driver start failed: %s",
                            self.task.name, e)
                self._emit(EVENT_DRIVER_FAILURE, str(e))
                decision, delay = self.restart_tracker.next_restart(False)
                if decision != "restart" or self._kill.wait(delay):
                    self._set_state(STATE_DEAD, failed=True)
                    break
                continue
            self._set_state(STATE_RUNNING)
            self._emit(EVENT_STARTED)

            result = None
            while result is None and not self._kill.is_set() \
                    and not self._restart.is_set():
                try:
                    result = self.driver.wait_task(self.task_id, timeout=0.25)
                except KeyError:
                    # task force-destroyed underneath us
                    self._set_state(STATE_DEAD, failed=False)
                    return
            if self._kill.is_set():
                self._handle_kill()
                break
            if self._restart.is_set():
                # user restart wins even if the task happened to exit in
                # the same poll window -- the caller was promised a
                # bounce, not policy-driven exit handling
                self._restart.clear()
                self._emit(EVENT_RESTARTING, "user requested restart")
                try:
                    self.driver.stop_task(
                        self.task_id, timeout=self.task.kill_timeout_s,
                        signal=self.task.kill_signal or "SIGTERM",
                    )
                    self.driver.destroy_task(self.task_id, force=True)
                except Exception:               # noqa: BLE001
                    pass
                continue
            success = result.successful()
            self._emit(
                EVENT_TERMINATED,
                f"exit code {result.exit_code}, signal {result.signal}"
                + (f", err {result.err}" if result.err else ""),
            )
            self.task_state.restarts = self.restart_tracker.count
            decision, delay = self.restart_tracker.next_restart(success)
            if decision == "exit":
                self._set_state(STATE_DEAD, failed=False)
                break
            if decision == "fail":
                self._emit(EVENT_NOT_RESTARTING, "exceeded restart policy")
                self._set_state(STATE_DEAD, failed=not success)
                break
            self._emit(EVENT_RESTARTING, f"restart in {delay:.1f}s")
            self.task_state.restarts = self.restart_tracker.count
            try:
                self.driver.destroy_task(self.task_id, force=True)
            except Exception:                   # noqa: BLE001
                pass
            if self._kill.wait(delay):
                self._handle_kill()
                break

    def _handle_kill(self) -> None:
        self._emit(EVENT_KILLING, self._kill_reason)
        try:
            self.driver.stop_task(
                self.task_id, timeout=self.task.kill_timeout_s,
                signal=self.task.kill_signal or "SIGTERM",
            )
        except Exception:                       # noqa: BLE001
            pass
        self._emit(EVENT_KILLED)
        self._set_state(STATE_DEAD, failed=False)

    def _prestart(self) -> None:
        """Built-in prestart hooks: validate + task dir + logs + vault
        + templates (task_runner_hooks.go validate/taskDir/logmon/
        vault/template subset)."""
        if not self.task.name:
            raise ValueError("task has no name")
        task_dir = os.path.join(self.alloc_dir, self.task.name)
        os.makedirs(os.path.join(task_dir, "local"), exist_ok=True)
        os.makedirs(os.path.join(task_dir, "secrets"), exist_ok=True)
        os.makedirs(os.path.join(self.alloc_dir, "alloc", "logs"), exist_ok=True)
        self._emit(EVENT_TASK_SETUP, "Building Task Directory")
        self._logmon_hook()
        self._artifact_hook(task_dir)
        self._vault_hook(task_dir)
        self._template_hook(task_dir)

    def _artifact_hook(self, task_dir: str) -> None:
        """artifact_hook.go: download each artifact stanza into the
        task dir before the driver starts; failure is a task setup
        failure (Failed Artifact Download event), retried under the
        restart policy like the reference's recoverable wrap."""
        if not self.task.artifacts:
            return
        from nomad_tpu.client.getter import ArtifactError, fetch_artifact

        self._emit(EVENT_TASK_SETUP, "Downloading Artifacts")
        for artifact in self.task.artifacts:
            try:
                fetch_artifact(artifact, task_dir)
            except ArtifactError as e:
                self._emit(EVENT_TASK_SETUP,
                           f"Failed Artifact Download: {e}")
                raise

    def _logmon_hook(self) -> None:
        """logmon_hook.go: one rotating collector per stream; the
        driver writes into the collector's FIFO."""
        from nomad_tpu.client.logmon import LogMon

        if self._logmons:
            return
        logs = os.path.join(self.alloc_dir, "alloc", "logs")
        for stream in ("stdout", "stderr"):
            lm = LogMon(
                os.path.join(logs, f"{self.task.name}.{stream}"),
                max_files=self.task.log_config.max_files,
                max_file_size_mb=self.task.log_config.max_file_size_mb,
            )
            try:
                lm.start()
            except OSError as e:
                LOG.warning("task %s: logmon %s failed (%s); driver "
                            "writes a plain file", self.task_id, stream, e)
                continue
            self._logmons[stream] = lm

    def _stop_logmons(self) -> None:
        for lm in self._logmons.values():
            lm.stop()
        self._logmons = {}

    def _vault_hook(self, task_dir: str) -> None:
        """vault_hook.go: derive the task's token via the server
        (Node.DeriveVaultToken), write it to secrets/vault_token,
        (with vault.env) expose VAULT_TOKEN, and watch the token —
        if it is revoked/expires out from under the task, re-derive
        and fire vault.change_mode (vault_hook.go renewal-failure →
        updatedVaultToken path)."""
        if self.task.vault is None:
            return
        if self.secrets is None:
            raise RuntimeError(
                f"task {self.task.name} has a vault block but the "
                "client has no Vault integration configured")
        self._derive_and_write_token(task_dir)
        self._emit(EVENT_TASK_SETUP, "Vault token derived")
        threading.Thread(
            target=self._vault_token_watch, args=(task_dir,),
            daemon=True, name=f"vault-watch-{self.task_id}",
        ).start()

    def _derive_and_write_token(self, task_dir: str) -> None:
        tokens = self.secrets.derive_vault_tokens(
            self.alloc.id, [self.task.name])
        self._vault_token = tokens.get(self.task.name, "")
        with open(os.path.join(task_dir, "secrets", "vault_token"), "w") as f:
            f.write(self._vault_token)

    def _vault_token_watch(self, task_dir: str) -> None:
        while not self._vault_watch_stop.wait(self.vault_poll_interval_s):
            if self._done.is_set():
                return
            try:
                if self.secrets.vault_token_valid(self._vault_token):
                    continue
                self._derive_and_write_token(task_dir)
            except Exception as e:              # noqa: BLE001
                # transient (Vault unreachable, server blip): keep the
                # watch alive and retry next poll — exiting here would
                # silently end rotation for the task's lifetime
                LOG.warning("task %s: vault token check/re-derive "
                            "failed (retrying): %s", self.task_id, e)
                continue
            mode = self.task.vault.change_mode
            if mode == "restart":
                self._emit(EVENT_RESTARTING, "Vault token rotated")
                self._restart.set()
            elif mode == "signal":
                sig = self.task.vault.change_signal or "SIGHUP"
                try:
                    self.driver.signal_task(self.task_id, sig)
                    self._emit(EVENT_TASK_SETUP,
                               f"Vault token rotated; sent {sig}")
                except Exception as e:          # noqa: BLE001
                    LOG.warning("task %s: vault signal failed: %s",
                                self.task_id, e)

    def _template_hook(self, task_dir: str) -> None:
        """template_hook.go / template.go: render each template into
        the task dir; watch live sources (Consul KV / Vault) and fire
        change_mode on re-render."""
        if not self.task.templates:
            return
        from nomad_tpu.client.template import (
            TemplateWatcher, uses_live_data, uses_vault,
        )

        sources = self._template_sources(task_dir)
        if self.task.vault is None and \
                any(uses_vault(src) for _, src in sources):
            raise RuntimeError(
                f"task {self.task.name}: template reads Vault secrets "
                "but the task has no vault block")
        self._render_templates(task_dir)
        live = any(uses_live_data(src) for _, src in sources)
        if live and self.secrets is not None:
            def rerender() -> bool:
                self._changed_templates = self._render_templates(task_dir)
                return bool(self._changed_templates)

            self._template_watcher = TemplateWatcher(
                poll_index=self.secrets.live_data_index,
                rerender=rerender,
                on_change=lambda: self._on_template_change(
                    self._changed_templates),
            )
            self._template_watcher.start()

    @staticmethod
    def _sandboxed_path(task_dir: str, rel: str) -> str:
        """Confine a jobspec-controlled template path to the allocation
        dir (template.go:572-601 escapingfs sandbox; CVE-2022-24683
        class: without this a submitted job reads/writes arbitrary host
        paths as the agent user). Paths resolve relative to the task
        dir but may reach the sibling shared ``alloc/`` dir, matching
        the reference's alloc-dir sandbox root."""
        full = os.path.realpath(os.path.join(task_dir, rel.lstrip("/")))
        root = os.path.realpath(os.path.dirname(task_dir.rstrip(os.sep)))
        if not (full == root or full.startswith(root + os.sep)):
            raise PermissionError(
                f"template path escapes task directory: {rel}")
        return full

    def _template_sources(self, task_dir: str):
        """Resolve each template to its source text; file-backed
        sources (source_path) read from the task's local dir."""
        out = []
        for tmpl in self.task.templates:
            src = tmpl.embedded_tmpl
            if not src and tmpl.source_path:
                path = self._sandboxed_path(
                    task_dir, os.path.join("local", tmpl.source_path))
                with open(path) as f:
                    src = f.read()
            out.append((tmpl, src))
        return out

    def _render_templates(self, task_dir: str):
        """Render every template; returns the templates whose output
        changed on disk."""
        from nomad_tpu.client.template import TemplateContext, render

        ctx = TemplateContext(
            env=self._base_env(),
            meta=dict(self.task.meta),
            node_attrs=self.secrets.node_attrs() if self.secrets else {},
            kv_get=self.secrets.kv_get if self.secrets else None,
            # secret reads carry the task's derived token so the
            # provider can enforce the task's policies; reading
            # self._vault_token at call time picks up re-derivations
            secret_get=(lambda p: self.secrets.read_secret(
                p, self._vault_token)) if self.secrets else None,
            kv_ls=self.secrets.kv_ls if self.secrets else None,
            services_get=(lambda n: self.secrets.services(
                self.alloc.namespace, n)) if self.secrets else None,
        )
        changed = []
        for tmpl, src in self._template_sources(task_dir):
            out = render(src, ctx)
            dest = self._sandboxed_path(
                task_dir, tmpl.dest_path or "local/rendered")
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            old = None
            try:
                with open(dest) as f:
                    old = f.read()
            except OSError:
                pass
            if out != old:
                with open(dest, "w") as f:
                    f.write(out)
                changed.append(tmpl)
        return changed

    def _on_template_change(self, changed) -> None:
        """Fire the strongest change_mode among the templates that
        actually re-rendered (template.go change-mode dispatch)."""
        modes = {t.change_mode for t in changed}
        if "restart" in modes:
            self._emit(EVENT_RESTARTING, "template re-rendered")
            self._restart.set()
        elif "signal" in modes:
            sig = next((t.change_signal for t in changed
                        if t.change_mode == "signal" and t.change_signal),
                       "SIGHUP")
            try:
                self.driver.signal_task(self.task_id, sig)
                self._emit(EVENT_TASK_SETUP,
                           f"template re-rendered; sent {sig}")
            except Exception as e:              # noqa: BLE001
                LOG.warning("task %s: template signal failed: %s",
                            self.task_id, e)

    def _base_env(self) -> Dict[str, str]:
        env = {
            "NOMAD_ALLOC_ID": self.alloc.id,
            "NOMAD_ALLOC_NAME": self.alloc.name,
            "NOMAD_TASK_NAME": self.task.name,
            "NOMAD_JOB_ID": self.alloc.job_id,
            "NOMAD_JOB_NAME": self.alloc.job.name if self.alloc.job else "",
            "NOMAD_TASK_DIR": os.path.join(self.alloc_dir, self.task.name, "local"),
            "NOMAD_SECRETS_DIR": os.path.join(self.alloc_dir, self.task.name, "secrets"),
        }
        env.update(self.extra_env)
        env.update(self.task.env)
        return env

    def _task_config(self) -> TaskConfig:
        logs = os.path.join(self.alloc_dir, "alloc", "logs")
        env = self._base_env()
        if self._vault_token and self.task.vault is not None \
                and self.task.vault.env:
            env["VAULT_TOKEN"] = self._vault_token
        def stream_path(stream: str) -> str:
            lm = self._logmons.get(stream)
            # collector's FIFO when running, plain file otherwise
            return lm.fifo_path if lm is not None else \
                os.path.join(logs, f"{self.task.name}.{stream}.0")

        out_path = stream_path("stdout")
        err_path = stream_path("stderr")
        return TaskConfig(
            id=self.task_id,
            name=self.task.name,
            alloc_id=self.alloc.id,
            job_name=self.alloc.job.name if self.alloc.job else "",
            task_group_name=self.alloc.task_group,
            env=env,
            driver_config=dict(self.task.config),
            resources=self.task.resources,
            std_out_path=out_path,
            std_err_path=err_path,
            alloc_dir=self.alloc_dir,
            netns=self.netns,
            network_isolation=self.network_isolation,
        )

    def restore(self, task_state: TaskState, handle: Optional[TaskHandle]) -> bool:
        """Reattach to a live task (task_runner.go:1154 restore ->
        driver RecoverTask). Returns True when the task is live again."""
        self.task_state = task_state or TaskState()
        if self.task_state.state == STATE_DEAD:
            # already finished in a previous agent life: nothing to run,
            # but the runner must read as done for GC/is_done
            self._done.set()
            return False
        if handle is None:
            return False
        try:
            self.driver.recover_task(handle)
            self.handle = handle
        except Exception as e:                  # noqa: BLE001
            LOG.info("task %s: recover failed, restarting: %s", self.task_id, e)
            return False
        # re-attach the log collectors: the surviving task process
        # still holds the FIFO open; mkfifo is a no-op and the new
        # reader resumes draining it
        try:
            self._logmon_hook()
        except Exception:                       # noqa: BLE001
            pass
        # resume waiting on the recovered task
        self._thread = threading.Thread(
            target=self._run_recovered, daemon=True, name=f"task-{self.task_id}"
        )
        self._thread.start()
        return True

    def _run_recovered(self) -> None:
        result = None
        while result is None and not self._kill.is_set():
            try:
                result = self.driver.wait_task(self.task_id, timeout=0.25)
            except KeyError:
                break
        if self._kill.is_set():
            self._handle_kill()
        elif result is not None:
            self._emit(EVENT_TERMINATED, f"exit code {result.exit_code}")
            self._set_state(STATE_DEAD, failed=not result.successful())
        self._stop_logmons()
        self._done.set()

    def restart(self, reason: str = "") -> None:
        """Bounce the running task (alloc_endpoint.go Restart)."""
        self._restart.set()

    def kill(self, reason: str = "") -> None:
        self._kill_reason = reason
        self._kill.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def is_done(self) -> bool:
        return self._done.is_set()
