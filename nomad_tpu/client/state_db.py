"""Client-local persistent state for restart recovery.

Reference behavior: client/state/state_database.go:105 -- boltdb
(helper/boltdd) persistence of allocation and task-runner state so a
restarted agent can restore its allocRunners and reattach to live
tasks (client.go:1109 restoreState). Backend here is sqlite3 (stdlib),
with pickled rows; an in-memory variant and an error-injecting variant
mirror client/state/memdb.go and errdb.go for tests.
"""

from __future__ import annotations

import os
import pickle
import sqlite3
import threading
from typing import Dict, List, Optional, Tuple


class StateDB:
    def __init__(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._lock:
            self._conn.executescript(
                """
                CREATE TABLE IF NOT EXISTS allocations (
                    alloc_id TEXT PRIMARY KEY,
                    data BLOB NOT NULL
                );
                CREATE TABLE IF NOT EXISTS task_state (
                    alloc_id TEXT NOT NULL,
                    task_name TEXT NOT NULL,
                    local_state BLOB,
                    task_handle BLOB,
                    PRIMARY KEY (alloc_id, task_name)
                );
                CREATE TABLE IF NOT EXISTS node_meta (
                    key TEXT PRIMARY KEY,
                    value BLOB NOT NULL
                );
                """
            )
            self._conn.commit()

    # --- allocations ----------------------------------------------------

    def put_allocation(self, alloc) -> None:
        # serialize before taking the connection lock (graftcheck R2):
        # the lock only needs to cover the sqlite write
        data = pickle.dumps(alloc)
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO allocations (alloc_id, data) VALUES (?, ?)",
                (alloc.id, data),
            )
            self._conn.commit()

    def get_allocations(self) -> List:
        with self._lock:
            rows = self._conn.execute(
                "SELECT data FROM allocations"
            ).fetchall()
        return [pickle.loads(r[0]) for r in rows]

    def delete_allocation(self, alloc_id: str) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM allocations WHERE alloc_id = ?", (alloc_id,)
            )
            self._conn.execute(
                "DELETE FROM task_state WHERE alloc_id = ?", (alloc_id,)
            )
            self._conn.commit()

    # --- task runner state ----------------------------------------------

    def put_task_state(self, alloc_id: str, task_name: str,
                       local_state=None, task_handle=None) -> None:
        local = pickle.dumps(local_state) if local_state is not None else None
        handle = pickle.dumps(task_handle) if task_handle is not None else None
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO task_state "
                "(alloc_id, task_name, local_state, task_handle) "
                "VALUES (?, ?, ?, ?)",
                (alloc_id, task_name, local, handle),
            )
            self._conn.commit()

    def get_task_state(self, alloc_id: str, task_name: str) -> Tuple[Optional[object], Optional[object]]:
        with self._lock:
            row = self._conn.execute(
                "SELECT local_state, task_handle FROM task_state "
                "WHERE alloc_id = ? AND task_name = ?",
                (alloc_id, task_name),
            ).fetchone()
        if row is None:
            return None, None
        local = pickle.loads(row[0]) if row[0] is not None else None
        handle = pickle.loads(row[1]) if row[1] is not None else None
        return local, handle

    # --- node meta (client ID persistence etc.) -------------------------

    def put_meta(self, key: str, value) -> None:
        data = pickle.dumps(value)
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO node_meta (key, value) VALUES (?, ?)",
                (key, data),
            )
            self._conn.commit()

    def get_meta(self, key: str):
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM node_meta WHERE key = ?", (key,)
            ).fetchone()
        return pickle.loads(row[0]) if row else None

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class MemStateDB(StateDB):
    """client/state/memdb.go analog."""

    def __init__(self) -> None:
        super().__init__(":memory:")


class ErrStateDB(MemStateDB):
    """client/state/errdb.go analog: fault injection for tests."""

    def __init__(self) -> None:
        super().__init__()
        self.fail = False

    def put_allocation(self, alloc) -> None:
        if self.fail:
            raise IOError("state db write failure (injected)")
        super().put_allocation(alloc)

    def put_task_state(self, *a, **kw) -> None:
        if self.fail:
            raise IOError("state db write failure (injected)")
        super().put_task_state(*a, **kw)
