"""Task template rendering (the consul-template analog).

Reference behavior: client/allocrunner/taskrunner/template/template.go
runs embedded consul-template: templates interpolate Consul KV, Vault
secrets, env vars, and node metadata into files under the task dir,
re-render when upstream data changes, and fire the template's
``change_mode`` (restart/signal/noop) on re-render.

This engine implements the interpolation functions the reference's
jobs use most, over the pluggable providers in server/secrets.py:

    {{ key "path" }}              Consul KV lookup
    {{ keyOrDefault "path" "d" }} Consul KV with fallback
    {{ secret "path" "field" }}   Vault KV field lookup
    {{ env "NAME" }}              task environment
    {{ meta "key" }}              task meta
    {{ node_attr "key" }}         node attribute

(The reference's full Go-template pipeline — ranges, scratch,
service() — is out of scope; jobs needing it would run a real
consul-template binary as a task.)
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, Optional

_FUNC_RE = re.compile(
    r"\{\{\s*(?P<fn>key|keyOrDefault|secret|env|meta|node_attr)"
    r"\s+\"(?P<a1>[^\"]*)\"(?:\s+\"(?P<a2>[^\"]*)\")?\s*\}\}"
)


class TemplateContext:
    """Data sources a render pulls from; any may be None (renders as
    empty, the consul-template missing-key default)."""

    def __init__(self, env: Optional[Dict[str, str]] = None,
                 meta: Optional[Dict[str, str]] = None,
                 node_attrs: Optional[Dict[str, str]] = None,
                 kv_get: Optional[Callable[[str], Optional[str]]] = None,
                 secret_get: Optional[Callable[[str], Optional[Dict]]] = None):
        self.env = env or {}
        self.meta = meta or {}
        self.node_attrs = node_attrs or {}
        self.kv_get = kv_get or (lambda k: None)
        self.secret_get = secret_get or (lambda p: None)


class MissingKeyError(KeyError):
    """A template referenced a key that has no value and no default.
    The reference blocks the task until the key appears; callers map
    this to 'template not yet renderable'."""


def render(tmpl: str, ctx: TemplateContext, strict: bool = False) -> str:
    def repl(m: re.Match) -> str:
        fn, a1, a2 = m.group("fn"), m.group("a1"), m.group("a2")
        val: Optional[str] = None
        if fn == "key":
            val = ctx.kv_get(a1)
        elif fn == "keyOrDefault":
            val = ctx.kv_get(a1)
            if val is None:
                val = a2 or ""
        elif fn == "secret":
            data = ctx.secret_get(a1)
            if data is not None:
                val = data.get(a2 or "value")
        elif fn == "env":
            val = ctx.env.get(a1)
        elif fn == "meta":
            val = ctx.meta.get(a1)
        elif fn == "node_attr":
            val = ctx.node_attrs.get(a1)
        if val is None:
            if strict:
                raise MissingKeyError(f"{fn} \"{a1}\" has no value")
            val = ""
        return str(val)

    return _FUNC_RE.sub(repl, tmpl)


def uses_live_data(tmpl: str) -> bool:
    """Does this template read sources that can change under a running
    task (KV/secrets)? Drives whether a change-watcher is needed."""
    return any(m.group("fn") in ("key", "keyOrDefault", "secret")
               for m in _FUNC_RE.finditer(tmpl))


def uses_vault(tmpl: str) -> bool:
    """Does this template read Vault secrets? Requires the task to
    carry a vault block (its derived token authorizes the reads)."""
    return any(m.group("fn") == "secret" for m in _FUNC_RE.finditer(tmpl))


class TemplateWatcher:
    """Re-render on upstream change and fire change_mode.

    The reference's template manager subscribes to consul-template's
    watcher; here the Dev providers expose a monotonic KV index that a
    small poll loop checks (the blocking-query analog at poll
    granularity).
    """

    def __init__(self, poll_index: Callable[[], int],
                 rerender: Callable[[], bool],
                 on_change: Callable[[], None],
                 interval_s: float = 1.0) -> None:
        self.poll_index = poll_index
        self.rerender = rerender
        self.on_change = on_change
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._last = self.poll_index()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="template-watcher"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                idx = self.poll_index()
                if idx == self._last:
                    continue
                self._last = idx
                if self.rerender():
                    self.on_change()
            except Exception:                   # noqa: BLE001
                continue
