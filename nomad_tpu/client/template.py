"""Task template rendering (the consul-template analog).

Reference behavior: client/allocrunner/taskrunner/template/template.go
runs embedded consul-template: templates interpolate Consul KV, Vault
secrets, env vars, and node metadata into files under the task dir,
re-render when upstream data changes, and fire the template's
``change_mode`` (restart/signal/noop) on re-render.

This engine implements a real subset of the Go text/template language
consul-template embeds — not just interpolation:

    {{ key "path" }}                    Consul KV lookup
    {{ keyOrDefault "path" "d" }}       Consul KV with fallback
    {{ secret "path" "field" }}         Vault KV field lookup
    {{ env "NAME" }} {{ meta "k" }} {{ node_attr "k" }}
    {{ ls "prefix" }}                   KV pairs under a prefix
    {{ service "name" }}                live service instances
    {{ if <pipe> }} … {{ else if }} … {{ else }} … {{ end }}
    {{ range <pipe> }} … {{ else }} … {{ end }}     (lists and maps)
    {{ range $i, $v := <pipe> }} … {{ end }}
    {{ with <pipe> }} … {{ end }}
    {{ $x := <pipe> }} and {{ $x }} / {{ $x.Field }}
    {{ .Field.Sub }} over the bound dot
    pipelines: {{ key "a" | toUpper }} (toUpper/toLower/trimSpace)

Missing-value semantics follow the engine's strict flag: in strict
mode a valueless key/secret/env/meta/node_attr raises MissingKeyError
(the reference blocks the task until the key appears); otherwise it
renders empty. Out of scope (documented): scratch, sprig's long
function tail, template-calling-template.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, List, Optional, Tuple

_ACTION_RE = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.S)
_WORD_RE = re.compile(
    r"\"(?:[^\"\\]|\\.)*\"" r"|:=|\||,"
    r"|\$[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z_][A-Za-z0-9_]*)*"
    r"|\.(?:[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z_][A-Za-z0-9_]*)*)?"
    r"|-?\d+(?:\.\d+)?"
    r"|[A-Za-z_][A-Za-z0-9_]*"
)

#: functions reading sources that change under a running task
_LIVE_FUNCS = ("key", "keyOrDefault", "secret", "ls", "service")


class MissingKeyError(KeyError):
    """A template referenced a key that has no value and no default.
    The reference blocks the task until the key appears; callers map
    this to 'template not yet renderable'."""


class TemplateSyntaxError(ValueError):
    pass


class TemplateContext:
    """Data sources a render pulls from; any may be None (renders as
    empty, the consul-template missing-key default)."""

    def __init__(self, env: Optional[Dict[str, str]] = None,
                 meta: Optional[Dict[str, str]] = None,
                 node_attrs: Optional[Dict[str, str]] = None,
                 kv_get: Optional[Callable[[str], Optional[str]]] = None,
                 secret_get: Optional[Callable[[str], Optional[Dict]]] = None,
                 kv_ls: Optional[Callable[[str], List[Tuple[str, str]]]] = None,
                 services_get: Optional[Callable[[str], List[Dict]]] = None):
        self.env = env or {}
        self.meta = meta or {}
        self.node_attrs = node_attrs or {}
        self.kv_get = kv_get or (lambda k: None)
        self.secret_get = secret_get or (lambda p: None)
        self.kv_ls = kv_ls or (lambda p: [])
        self.services_get = services_get or (lambda n: [])


# ---------------------------------------------------------------------------
# parse: template text -> node tree
# ---------------------------------------------------------------------------
# nodes: ("text", s) | ("out", pipe) | ("assign", var, pipe)
#        ("if", [(pipe, body), ...], else_body)
#        ("range", ivar, vvar, pipe, body, else_body)
#        ("with", pipe, body, else_body)
# pipe:  [command, ...] — each command is a term list; the previous
#        command's value is appended as the final argument (Go rules)
# term:  ("str", s) | ("num", x) | ("var", name) | ("dot", [fields])
#        | ("fn", name)


def _lex_action(text: str) -> List[str]:
    words = _WORD_RE.findall(text)
    if "".join(words).replace(" ", "") != text.replace(" ", ""):
        # something in the action didn't lex (unbalanced quote, stray
        # operator): surface it rather than render garbage
        leftover = text
        for w in words:
            leftover = leftover.replace(w, "", 1)
        if leftover.strip():
            raise TemplateSyntaxError(
                f"cannot parse action {text!r} (near {leftover.strip()!r})")
    return words


def _parse_term(word: str):
    if word.startswith('"'):
        return ("str", word[1:-1].replace('\\"', '"').replace("\\\\", "\\"))
    if word.startswith("$"):
        name, _, fields = word[1:].partition(".")
        return ("var", name, fields.split(".") if fields else [])
    if word == ".":
        return ("dot", [])
    if word.startswith("."):
        return ("dot", word[1:].split("."))
    if re.fullmatch(r"-?\d+(?:\.\d+)?", word):
        return ("num", float(word) if "." in word else int(word))
    return ("fn", word)


def _parse_pipe(words: List[str]):
    if not words:
        raise TemplateSyntaxError("empty pipeline")
    commands, current = [], []
    for w in words:
        if w == "|":
            if not current:
                raise TemplateSyntaxError("empty pipeline stage")
            commands.append(current)
            current = []
        else:
            current.append(_parse_term(w))
    if not current:
        raise TemplateSyntaxError("pipeline ends with |")
    commands.append(current)
    return commands


def _parse(tmpl: str):
    """Parse into a body; raises TemplateSyntaxError on unbalanced
    blocks."""
    tokens: List[Tuple[str, str]] = []
    pos = 0
    trim_next = False
    for m in _ACTION_RE.finditer(tmpl):
        if m.start() > pos:
            text = tmpl[pos:m.start()]
            if trim_next:              # previous action ended with -}}
                text = text.lstrip()
            tokens.append(("text", text))
        trim_next = False
        if m.group(1) and tokens and tokens[-1][0] == "text":
            # {{- : Go trims the whitespace before the action
            tokens[-1] = ("text", tokens[-1][1].rstrip())
        tokens.append(("action", m.group(2)))
        pos = m.end()
        if m.group(3):
            trim_next = True
    if pos < len(tmpl):
        text = tmpl[pos:]
        if trim_next:
            text = text.lstrip()
        tokens.append(("text", text))

    def parse_body(i: int, terminators: Tuple[str, ...]):
        body = []
        while i < len(tokens):
            kind, val = tokens[i]
            if kind == "text":
                body.append(("text", val))
                i += 1
                continue
            words = _lex_action(val)
            head = words[0] if words else ""
            if head in terminators or (
                    head == "else" and "else" in terminators):
                return body, i
            if head == "if":
                branches, else_body = [], []
                cond = _parse_pipe(words[1:])
                inner, i = parse_body(i + 1, ("end", "else"))
                branches.append((cond, inner))
                while True:
                    w2 = _lex_action(tokens[i][1])
                    if w2[0] == "end":
                        break
                    if w2[:2] and w2[0] == "else" and len(w2) > 1 \
                            and w2[1] == "if":
                        cond = _parse_pipe(w2[2:])
                        inner, i = parse_body(i + 1, ("end", "else"))
                        branches.append((cond, inner))
                        continue
                    # plain else
                    else_body, i = parse_body(i + 1, ("end",))
                    break
                body.append(("if", branches, else_body))
                i += 1
                continue
            if head == "range":
                rest = words[1:]
                ivar = vvar = None
                # a leading $var is a loop-variable declaration ONLY
                # when followed by "," or ":=" — `{{ range $x }}` after
                # `{{ $x := service "a" }}` (valid Go text/template)
                # iterates the variable itself
                if rest and rest[0].startswith("$"):
                    if len(rest) > 2 and rest[1] == "," \
                            and rest[2].startswith("$"):
                        ivar, vvar = rest[0][1:], rest[2][1:]
                        rest = rest[3:]
                        if rest[:1] == [":="]:
                            rest = rest[1:]
                    elif rest[1:2] == [":="]:
                        vvar = rest[0][1:]
                        rest = rest[2:]
                pipe = _parse_pipe(rest)
                inner, i = parse_body(i + 1, ("end", "else"))
                else_body = []
                if _lex_action(tokens[i][1])[0] == "else":
                    else_body, i = parse_body(i + 1, ("end",))
                body.append(("range", ivar, vvar, pipe, inner, else_body))
                i += 1
                continue
            if head == "with":
                pipe = _parse_pipe(words[1:])
                inner, i = parse_body(i + 1, ("end", "else"))
                else_body = []
                if _lex_action(tokens[i][1])[0] == "else":
                    else_body, i = parse_body(i + 1, ("end",))
                body.append(("with", pipe, inner, else_body))
                i += 1
                continue
            if head.startswith("$") and words[1:2] == [":="]:
                body.append(("assign", head[1:], _parse_pipe(words[2:])))
                i += 1
                continue
            body.append(("out", _parse_pipe(words)))
            i += 1
        if terminators:
            raise TemplateSyntaxError(
                f"unterminated block (missing {'/'.join(terminators)})")
        return body, i

    body, _ = parse_body(0, ())
    return body


# ---------------------------------------------------------------------------
# evaluate
# ---------------------------------------------------------------------------


class _Scope:
    def __init__(self, ctx: TemplateContext, strict: bool) -> None:
        self.ctx = ctx
        self.strict = strict
        self.vars: Dict[str, object] = {}
        self.dot: object = None


def _field(value, parts: List[str]):
    for p in parts:
        if value is None:
            return None
        if isinstance(value, dict):
            value = value.get(p)
        else:
            value = getattr(value, p, None)
    return value


def _truthy(v) -> bool:
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and v == 0:
        return False
    if isinstance(v, (str, list, tuple, dict)) and len(v) == 0:
        return False
    return True


#: function -> (min_args, max_args)
_ARITY = {
    "key": (1, 1), "keyOrDefault": (1, 2), "secret": (1, 2),
    "env": (1, 1), "meta": (1, 1), "node_attr": (1, 1),
    "ls": (1, 1), "service": (1, 1),
    "toUpper": (1, 1), "toLower": (1, 1), "trimSpace": (1, 1),
}


def _call(name: str, args: List, scope: _Scope):
    ctx = scope.ctx
    arity = _ARITY.get(name)
    if arity is None:
        raise TemplateSyntaxError(f"unknown function {name!r}")
    if not (arity[0] <= len(args) <= arity[1]):
        raise TemplateSyntaxError(
            f"{name} takes {arity[0]}"
            + (f"-{arity[1]}" if arity[1] != arity[0] else "")
            + f" argument(s), got {len(args)}")

    def need(val, what):
        if val is None:
            if scope.strict:
                raise MissingKeyError(f"{what} has no value")
            return ""
        return val

    if name == "key":
        return need(ctx.kv_get(str(args[0])), f'key "{args[0]}"')
    if name == "keyOrDefault":
        val = ctx.kv_get(str(args[0]))
        return val if val is not None else (args[1] if len(args) > 1 else "")
    if name == "secret":
        data = ctx.secret_get(str(args[0]))
        if len(args) > 1:
            val = None if data is None else data.get(str(args[1]))
            return need(val, f'secret "{args[0]}" field "{args[1]}"')
        if data is None and scope.strict:
            raise MissingKeyError(f'secret "{args[0]}" has no value')
        return data or {}
    if name == "env":
        return need(ctx.env.get(str(args[0])), f'env "{args[0]}"')
    if name == "meta":
        return need(ctx.meta.get(str(args[0])), f'meta "{args[0]}"')
    if name == "node_attr":
        return need(ctx.node_attrs.get(str(args[0])),
                    f'node_attr "{args[0]}"')
    if name == "ls":
        # consul-template ls: KeyPairs directly under the prefix
        # (path-boundary: "app" never matches "apple"), .Key relative
        out = []
        prefix = str(args[0]).rstrip("/")
        for k, v in ctx.kv_ls(prefix):
            if prefix:
                if not k.startswith(prefix + "/"):
                    continue
                rel = k[len(prefix) + 1:]
            else:
                rel = k
            if rel and "/" not in rel:
                out.append({"Key": rel, "Value": v})
        return out
    if name == "service":
        return ctx.services_get(str(args[0]))
    if name == "toUpper":
        return str(args[0]).upper()
    if name == "toLower":
        return str(args[0]).lower()
    if name == "trimSpace":
        return str(args[0]).strip()
    raise TemplateSyntaxError(f"unknown function {name!r}")


def _functions_used(tmpl: str) -> set:
    """Function names actually CALLED by the template (from the parsed
    tree, so names inside string literals never count). Unparsable
    templates fall back to a conservative raw-text scan."""
    used: set = set()

    def walk_pipe(pipe):
        for command in pipe:
            for term in command:
                if term[0] == "fn":
                    used.add(term[1])

    def walk(body):
        for node in body:
            kind = node[0]
            if kind == "out":
                walk_pipe(node[1])
            elif kind == "assign":
                walk_pipe(node[2])
            elif kind == "if":
                for cond, inner in node[1]:
                    walk_pipe(cond)
                    walk(inner)
                walk(node[2])
            elif kind == "with":
                walk_pipe(node[1])
                walk(node[2])
                walk(node[3])
            elif kind == "range":
                walk_pipe(node[3])
                walk(node[4])
                walk(node[5])

    try:
        walk(_parse(tmpl))
    except TemplateSyntaxError:
        for m in _ACTION_RE.finditer(tmpl):
            for fn in _ARITY:
                if re.search(rf"\b{fn}\b", m.group(2)):
                    used.add(fn)
    return used


def _eval_term(term, scope: _Scope):
    kind = term[0]
    if kind == "str" or kind == "num":
        return term[1]
    if kind == "var":
        if term[1] not in scope.vars:
            raise TemplateSyntaxError(f"undefined variable ${term[1]}")
        return _field(scope.vars[term[1]], term[2])
    if kind == "dot":
        return _field(scope.dot, term[1])
    raise TemplateSyntaxError(f"function {term[1]!r} used as argument")


def _eval_pipe(pipe, scope: _Scope):
    value = None
    for n, command in enumerate(pipe):
        head = command[0]
        rest = command[1:]
        args = [_eval_term(t, scope) for t in rest]
        if n > 0:
            args.append(value)
        if head[0] == "fn":
            value = _call(head[1], args, scope)
        else:
            if rest:
                raise TemplateSyntaxError("term does not take arguments")
            value = _eval_term(head, scope) if n == 0 else args[-1]
    return value


def _to_text(v) -> str:
    if v is None:
        return ""
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


def _exec(body, scope: _Scope, out: List[str]) -> None:
    for node in body:
        kind = node[0]
        if kind == "text":
            out.append(node[1])
        elif kind == "out":
            out.append(_to_text(_eval_pipe(node[1], scope)))
        elif kind == "assign":
            scope.vars[node[1]] = _eval_pipe(node[2], scope)
        elif kind == "if":
            _, branches, else_body = node
            for cond, inner in branches:
                if _truthy(_eval_pipe(cond, scope)):
                    _exec(inner, scope, out)
                    break
            else:
                _exec(else_body, scope, out)
        elif kind == "with":
            _, pipe, inner, else_body = node
            val = _eval_pipe(pipe, scope)
            if _truthy(val):
                saved = scope.dot
                scope.dot = val
                _exec(inner, scope, out)
                scope.dot = saved
            else:
                _exec(else_body, scope, out)
        elif kind == "range":
            _, ivar, vvar, pipe, inner, else_body = node
            val = _eval_pipe(pipe, scope)
            items: List[Tuple[object, object]]
            if isinstance(val, dict):
                items = sorted(val.items())
            elif isinstance(val, (list, tuple)):
                items = list(enumerate(val))
            elif val is None:
                items = []
            else:
                raise TemplateSyntaxError(
                    f"range over non-iterable {type(val).__name__}")
            if not items:
                _exec(else_body, scope, out)
                continue
            saved = scope.dot
            for k, v in items:
                if ivar is not None:
                    scope.vars[ivar] = k
                if vvar is not None:
                    scope.vars[vvar] = v
                scope.dot = v
                _exec(inner, scope, out)
            scope.dot = saved


def render(tmpl: str, ctx: TemplateContext, strict: bool = False) -> str:
    scope = _Scope(ctx, strict)
    out: List[str] = []
    _exec(_parse(tmpl), scope, out)
    return "".join(out)


def uses_live_data(tmpl: str) -> bool:
    """Does this template read sources that can change under a running
    task (KV/secrets/services)? Drives whether a change-watcher is
    needed. Classified on the parsed tree, so a KV key literally named
    "service" never counts."""
    return bool(_functions_used(tmpl) & set(_LIVE_FUNCS))


def uses_vault(tmpl: str) -> bool:
    """Does this template CALL the secret function? Requires the task
    to carry a vault block (its derived token authorizes the reads);
    a Consul key named "secret/db" does not count."""
    return "secret" in _functions_used(tmpl)


class TemplateWatcher:
    """Re-render on upstream change and fire change_mode.

    The reference's template manager subscribes to consul-template's
    watcher; here the Dev providers expose a monotonic KV index that a
    small poll loop checks (the blocking-query analog at poll
    granularity).
    """

    def __init__(self, poll_index: Callable[[], int],
                 rerender: Callable[[], bool],
                 on_change: Callable[[], None],
                 interval_s: float = 1.0) -> None:
        self.poll_index = poll_index
        self.rerender = rerender
        self.on_change = on_change
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._last = self.poll_index()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="template-watcher"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                idx = self.poll_index()
                if idx == self._last:
                    continue
                self._last = idx
                if self.rerender():
                    self.on_change()
            except Exception:                   # noqa: BLE001
                continue
