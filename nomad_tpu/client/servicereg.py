"""Client-side native service registration.

Reference behavior: client/serviceregistration/ -- the client registers
running tasks' ``provider = "nomad"`` services against the server's
ServiceRegistration endpoint (nsd/nsd.go RegisterWorkload) and removes
them when the workload stops (RemoveWorkload). Address comes from the
node fingerprint; port from the allocation's assigned port labels.

The "builtin" provider (this build's default, standing in for both
nomad- and consul-provided discovery) registers here too.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from nomad_tpu.structs.services import ServiceRegistration, registration_id

LOG = logging.getLogger(__name__)

PROVIDERS = ("nomad", "builtin")


class ServiceRegWrapper:
    def __init__(self, rpc, node) -> None:
        self.rpc = rpc
        self.node = node

    def _address(self) -> str:
        return str(self.node.attributes.get("unique.network.ip-address",
                                            "127.0.0.1"))

    def _port_for_label(self, alloc, label: str) -> int:
        """Resolve a service's port label against the alloc's assigned
        networks (serviceregistration GetAddress semantics)."""
        if not label:
            return 0
        nets = []
        res = alloc.allocated_resources
        if res is not None:
            if res.shared is not None:
                nets.extend(res.shared.networks)
                for p in res.shared.ports:
                    if p.label == label:
                        return p.value
            for tr in res.tasks.values():
                nets.extend(tr.networks)
        for net in nets:
            port = net.port_for_label(label)
            if port:
                return port
        return 0

    def build(self, alloc, services, task_name: str = "") -> List[ServiceRegistration]:
        regs = []
        for svc in services or []:
            if svc.provider not in PROVIDERS:
                continue
            regs.append(ServiceRegistration(
                id=registration_id(svc.name, alloc.id, task_name,
                                   svc.port_label),
                service_name=svc.name,
                namespace=alloc.namespace,
                node_id=alloc.node_id,
                datacenter=self.node.datacenter,
                job_id=alloc.job_id,
                alloc_id=alloc.id,
                tags=list(svc.tags),
                address=self._address(),
                port=self._port_for_label(alloc, svc.port_label),
            ))
        return regs

    def register(self, alloc, services, task_name: str = "") -> None:
        regs = self.build(alloc, services, task_name)
        if regs:
            try:
                self.rpc.register_services(regs)
            except Exception as e:              # noqa: BLE001
                LOG.warning("service registration for alloc %s: %s",
                            alloc.id, e)

    def deregister_alloc(self, alloc_id: str) -> None:
        try:
            self.rpc.deregister_services_by_alloc([alloc_id])
        except Exception as e:                  # noqa: BLE001
            LOG.warning("service deregistration for alloc %s: %s",
                        alloc_id, e)

    def deregister_task(self, alloc, services, task_name: str = "") -> None:
        """Pull one dead task's instances while its siblings keep
        running (RemoveWorkload at task granularity)."""
        ids = [
            registration_id(svc.name, alloc.id, task_name, svc.port_label)
            for svc in services or [] if svc.provider in PROVIDERS
        ]
        if ids:
            try:
                self.rpc.deregister_services(ids)
            except Exception as e:              # noqa: BLE001
                LOG.warning("service deregistration for task %s/%s: %s",
                            alloc.id, task_name, e)
