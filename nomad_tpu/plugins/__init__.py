"""Plugin boundary: drivers and devices.

Reference behavior: plugins/ (SURVEY.md section 2.5) -- every external
plugin is a subprocess speaking gRPC (go-plugin); built-in drivers are
registered in-process through the same interfaces
(helper/pluginutils/catalog/register.go). Here the interface layer is
the same shape (fingerprint streams, task lifecycle, device reserve);
built-ins run in-process, and the ``external`` transport runs a plugin
as a subprocess over a length-prefixed pipe protocol.
"""

from nomad_tpu.plugins.base import PluginInfo
from nomad_tpu.plugins.drivers import (
    DriverCapabilities,
    DriverPlugin,
    ExitResult,
    Fingerprint,
    TaskConfig,
    TaskHandle,
    TaskStatus,
)

__all__ = [
    "DriverCapabilities",
    "DriverPlugin",
    "ExitResult",
    "Fingerprint",
    "PluginInfo",
    "TaskConfig",
    "TaskHandle",
    "TaskStatus",
]
