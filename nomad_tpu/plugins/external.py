"""Out-of-process driver plugins.

Reference behavior: plugins/base + hashicorp/go-plugin — every
external plugin is a SUBPROCESS the agent launches, speaking an RPC
protocol over a private channel after a handshake
(plugins/drivers/proto/driver.proto is the wire contract). Here the
channel is newline-delimited JSON frames over the child's
stdin/stdout — same process-isolation boundary, same reattach-by-
handle semantics, debuggable with a text editor:

    handshake (child -> agent, first line):
        {"protocol": 1, "type": "driver", "name": "<driver>"}
    request  (agent -> child):  {"id": N, "method": M, "params": {...}}
    response (child -> agent):  {"id": N, "result": ...} |
                                {"id": N, "error": "..."}

The channel is one serial request/response stream per plugin: a
long-running call (exec_task) delays other calls to the same plugin.
Agent-side pollers keep their per-call timeouts short (task runners
wait in 0.25s slices), which bounds the head-of-line delay; a
multiplexed channel is the upgrade path if a driver needs
long-blocking calls.

Plugin authors implement :class:`~nomad_tpu.plugins.drivers.
DriverPlugin` and call :func:`serve_driver` under ``__main__``; the
agent side wraps the subprocess in :class:`ExternalDriver`, which is a
drop-in DriverPlugin. ``load_plugin_dir`` scans a directory the way
the reference's plugin loader does (helper/pluginutils/loader).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from nomad_tpu.plugins.base import PluginInfo
from nomad_tpu.plugins.drivers import (
    HEALTH_UNHEALTHY,
    DriverCapabilities,
    DriverPlugin,
    ExitResult,
    Fingerprint,
    NetworkIsolationSpec,
    TaskConfig,
    TaskHandle,
    TaskStatus,
)

LOG = logging.getLogger(__name__)
PROTOCOL_VERSION = 1


def _to_wire(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        # field-by-field (asdict would flatten NESTED dataclasses to
        # dicts before this recursion could tag them)
        return {"__dc__": type(obj).__name__,
                **{f.name: _to_wire(getattr(obj, f.name))
                   for f in dataclasses.fields(obj)}}
    if isinstance(obj, dict):
        return {k: _to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_wire(v) for v in obj]
    return obj


_DC_TYPES = {
    c.__name__: c for c in (
        Fingerprint, DriverCapabilities, TaskConfig, TaskHandle,
        ExitResult, TaskStatus, PluginInfo, NetworkIsolationSpec,
    )
}


def _from_wire(obj: Any) -> Any:
    if isinstance(obj, dict):
        name = obj.pop("__dc__", None)
        decoded = {k: _from_wire(v) for k, v in obj.items()}
        if name and name in _DC_TYPES:
            cls = _DC_TYPES[name]
            fields = {f.name for f in dataclasses.fields(cls)}
            return cls(**{k: v for k, v in decoded.items() if k in fields})
        return decoded
    if isinstance(obj, list):
        return [_from_wire(v) for v in obj]
    return obj


class PluginCrashed(RuntimeError):
    pass


class ExternalDriver(DriverPlugin):
    """Agent-side proxy: a DriverPlugin whose methods run in the
    plugin subprocess."""

    def __init__(self, argv: List[str], name_hint: str = "",
                 call_timeout: float = 60.0) -> None:
        self.argv = list(argv)
        self._lock = threading.Lock()
        self._next_id = 0
        # chatty plugins may print arbitrarily many stray lines between
        # responses; bound the wait by time, not line count
        self._call_timeout = call_timeout
        self._proc: Optional[subprocess.Popen] = None
        self.name = name_hint
        self._start_process()

    # -- process lifecycle ----------------------------------------------

    def _start_process(self) -> None:
        # python plugins dropped into a plugin_dir import the agent's
        # SDK (nomad_tpu.plugins.*); make the package root importable
        # from wherever the plugin file lives
        import nomad_tpu
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(nomad_tpu.__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        self._proc = subprocess.Popen(
            self.argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, bufsize=1, env=env,
        )
        try:
            import select
            # generous: a python plugin's interpreter+SDK import can
            # take seconds on a loaded machine; a crashed plugin still
            # fails fast via the EOF/readline path below
            r, _, _ = select.select([self._proc.stdout], [], [], 30.0)
            if not r:
                raise PluginCrashed(
                    f"plugin {self.argv}: handshake timeout")
            line = self._proc.stdout.readline()
            try:
                hs = json.loads(line)
            except (json.JSONDecodeError, TypeError):
                raise PluginCrashed(
                    f"plugin {self.argv}: bad handshake {line!r}")
            if hs.get("protocol") != PROTOCOL_VERSION or \
                    hs.get("type") != "driver":
                raise PluginCrashed(f"plugin {self.argv}: handshake {hs}")
        except PluginCrashed:
            # never leave a non-plugin executable running
            self.shutdown()
            raise
        self.name = hs.get("name", self.name)
        # pump stdout on a thread so _call can wait with a timeout;
        # readline() on the pipe directly cannot be time-bounded and
        # select() misses lines already sitting in the text buffer
        self._lines: "queue.Queue[Optional[str]]" = queue.Queue()
        reader = threading.Thread(
            target=self._pump_stdout, name=f"plugin-{self.name}-stdout",
            daemon=True)
        reader.start()

    def _pump_stdout(self) -> None:
        try:
            for line in self._proc.stdout:
                self._lines.put(line)
        except (ValueError, OSError):
            pass                                # stream closed
        self._lines.put(None)                   # EOF sentinel

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def shutdown(self) -> None:
        if self._proc is not None:
            try:
                self._proc.terminate()
                self._proc.wait(timeout=3)
            except Exception:                   # noqa: BLE001
                self._proc.kill()

    # -- rpc -------------------------------------------------------------

    def _call(self, method: str, **params: Any) -> Any:
        with self._lock:
            if not self.alive():
                raise PluginCrashed(f"plugin {self.name} is not running")
            self._next_id += 1
            frame = {"id": self._next_id, "method": method,
                     "params": _to_wire(params)}
            try:
                # graft: ok R2 - the lock IS the RPC framing: it pairs this request with its response on one pipe; frames are tiny and plugin calls are cold-path
                self._proc.stdin.write(json.dumps(frame) + "\n")
                self._proc.stdin.flush()

                resp = None
                deadline = time.monotonic() + self._call_timeout
                while True:
                    # the reader thread pumps stdout into _lines; a
                    # plugin that goes silent mid-call must not wedge
                    # the caller (and every later call, via
                    # self._lock) forever, so bound the wait by time
                    # rather than line count
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        line = self._lines.get(timeout=remaining)
                    except queue.Empty:
                        break
                    if line is None:            # reader hit EOF
                        raise PluginCrashed(
                            f"plugin {self.name} exited mid-call")
                    try:
                        # graft: ok R2 - response parse belongs to the same framed exchange the lock serializes
                        candidate = json.loads(line)
                    except json.JSONDecodeError:
                        # stray print() from the plugin: skip, stay
                        # in sync via the response id
                        LOG.warning("plugin %s: stray stdout %r",
                                    self.name, line[:120])
                        continue
                    if candidate.get("id") == self._next_id:
                        resp = candidate
                        break
                if resp is None:
                    raise PluginCrashed(
                        f"plugin {self.name}: no response within "
                        f"{self._call_timeout}s")
            except (BrokenPipeError, OSError) as e:
                raise PluginCrashed(f"plugin {self.name}: {e}")
        if resp.get("error"):
            if resp.get("error_type") == "KeyError":
                # the force-destroyed-task contract task_runner keys on
                raise KeyError(f"plugin {self.name}: {resp['error']}")
            raise RuntimeError(f"plugin {self.name}: {resp['error']}")
        return _from_wire(resp.get("result"))

    # -- DriverPlugin surface -------------------------------------------

    def plugin_info(self) -> PluginInfo:
        return self._call("plugin_info")

    def task_config_schema(self) -> Dict:
        return self._call("task_config_schema")

    def capabilities(self) -> DriverCapabilities:
        return self._call("capabilities")

    def fingerprint(self) -> Fingerprint:
        if not self.alive():
            return Fingerprint(health=HEALTH_UNHEALTHY,
                               health_description="plugin process exited")
        try:
            return self._call("fingerprint")
        except PluginCrashed as e:
            return Fingerprint(health=HEALTH_UNHEALTHY,
                               health_description=str(e))

    def start_task(self, config: TaskConfig) -> TaskHandle:
        return self._call("start_task", config=config)

    def recover_task(self, handle: TaskHandle) -> None:
        self._call("recover_task", handle=handle)

    def wait_task(self, task_id: str,
                  timeout: Optional[float] = None) -> Optional[ExitResult]:
        return self._call("wait_task", task_id=task_id, timeout=timeout)

    def stop_task(self, task_id: str, timeout: float = 5.0,
                  signal: str = "SIGTERM") -> None:
        self._call("stop_task", task_id=task_id, timeout=timeout,
                   signal=signal)

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        self._call("destroy_task", task_id=task_id, force=force)

    def inspect_task(self, task_id: str) -> TaskStatus:
        return self._call("inspect_task", task_id=task_id)

    def task_stats(self, task_id: str) -> Dict:
        return self._call("task_stats", task_id=task_id)

    def signal_task(self, task_id: str, signal: str) -> None:
        self._call("signal_task", task_id=task_id, signal=signal)

    def exec_task(self, task_id: str, cmd: List[str],
                  timeout: float = 30.0) -> Dict:
        return self._call("exec_task", task_id=task_id, cmd=cmd,
                          timeout=timeout)

    # DriverNetworkManager proxying: an external driver advertising
    # must_create_network must actually be ASKED (the base-class stub
    # would silently decline on the proxy's behalf)
    def create_network(self, alloc_id: str, port_mappings=None):
        return self._call("create_network", alloc_id=alloc_id,
                          port_mappings=list(port_mappings or []))

    def destroy_network(self, alloc_id: str, spec) -> None:
        self._call("destroy_network", alloc_id=alloc_id, spec=spec)

    def recover_network(self, alloc_id: str, port_mappings=None):
        return self._call("recover_network", alloc_id=alloc_id,
                          port_mappings=list(port_mappings or []))


def serve_driver(driver: DriverPlugin, name: str) -> None:
    """Plugin-side main loop: handshake then serve frames until EOF.

    KeyError from an unknown task id maps to the error field the
    proxy re-raises; everything else is caught so one bad request
    can't kill the plugin.
    """
    out = sys.stdout
    out.write(json.dumps({
        "protocol": PROTOCOL_VERSION, "type": "driver", "name": name,
    }) + "\n")
    out.flush()
    for line in sys.stdin:
        try:
            frame = json.loads(line)
        except json.JSONDecodeError:
            continue
        fid = frame.get("id")
        method = frame.get("method", "")
        params = _from_wire(frame.get("params") or {})
        try:
            fn = getattr(driver, method)
            if method.startswith("_") or not callable(fn):
                raise AttributeError(method)
            result = fn(**params)
            resp = {"id": fid, "result": _to_wire(result)}
        except Exception as e:                  # noqa: BLE001
            resp = {"id": fid, "error": f"{type(e).__name__}: {e}",
                    "error_type": type(e).__name__}
        out.write(json.dumps(resp) + "\n")
        out.flush()


def load_plugin_dir(plugin_dir: str) -> Dict[str, ExternalDriver]:
    """Scan a plugin directory (helper/pluginutils/loader analog):
    every executable file or ``*.py`` is launched and handshaken;
    failures are logged and skipped."""
    out: Dict[str, ExternalDriver] = {}
    if not plugin_dir or not os.path.isdir(plugin_dir):
        return out
    for entry in sorted(os.listdir(plugin_dir)):
        path = os.path.join(plugin_dir, entry)
        if not os.path.isfile(path):
            continue
        if entry.endswith(".py"):
            argv = [sys.executable, path]
        elif os.access(path, os.X_OK):
            argv = [path]
        else:
            continue
        try:
            drv = ExternalDriver(argv, name_hint=entry)
            if drv.name in out:
                LOG.warning("plugin %s: duplicate driver name %r; "
                            "keeping the first", path, drv.name)
                drv.shutdown()
                continue
            out[drv.name] = drv
        except (PluginCrashed, OSError) as e:
            LOG.warning("plugin %s failed to load: %s", path, e)
    return out
