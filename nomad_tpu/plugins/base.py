"""Base plugin contract.

Reference behavior: plugins/base/base.go:9 ``BasePlugin`` -- PluginInfo,
ConfigSchema, SetConfig. Config schemas here are plain dicts validated
by the plugin (the hclspec-proto analog, plugins/shared/hclspec).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict


PLUGIN_TYPE_DRIVER = "driver"
PLUGIN_TYPE_DEVICE = "device"


@dataclass
class PluginInfo:
    name: str
    type: str
    plugin_api_version: str = "v0.1.0"
    plugin_version: str = "0.1.0"


class BasePlugin:
    def plugin_info(self) -> PluginInfo:
        raise NotImplementedError

    def config_schema(self) -> Dict:
        """Declared config keys -> {type, default} (hclspec analog)."""
        return {}

    def set_config(self, config: Dict) -> None:
        self.config = dict(config or {})
