"""Device plugin API: where accelerators surface as schedulable resources.

Reference behavior: plugins/device/device.go:25 ``DevicePlugin`` --
Fingerprint (stream of device groups with attributes), Reserve(ids) ->
container env/mounts/devices, Stats (stream). This is the path by which
GPUs/TPUs become ``NodeDeviceResource``s the scheduler's DeviceChecker
and deviceAllocator consume (scheduler/feasible.go:1193, device.go:32).

The built-in ``TpuDevicePlugin`` fingerprints the local JAX TPU
devices -- the TPU build's equivalent of the reference's NVIDIA device
plugin.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from nomad_tpu.plugins.base import BasePlugin, PLUGIN_TYPE_DEVICE, PluginInfo
from nomad_tpu.structs.resources import NodeDeviceResource


@dataclass
class ReservationResponse:
    """device.proto Reserve: how the runtime exposes reserved devices."""

    container_res: Dict[str, str] = field(default_factory=dict)   # env vars
    mounts: List[Dict] = field(default_factory=list)
    devices: List[Dict] = field(default_factory=list)


class DevicePlugin(BasePlugin):
    def fingerprint(self) -> List[NodeDeviceResource]:
        raise NotImplementedError

    def reserve(self, device_ids: List[str]) -> ReservationResponse:
        raise NotImplementedError

    def stats(self) -> Dict[str, Dict]:
        return {}


class TpuDevicePlugin(DevicePlugin):
    """Fingerprints local TPU chips via jax.devices().

    Gated: on hosts without TPUs (or with jax forced to CPU) it reports
    nothing, exactly like the nvidia plugin on a GPU-less node.
    """

    def __init__(self, platform: str = "tpu") -> None:
        self.platform = platform

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name="tpu", type=PLUGIN_TYPE_DEVICE)

    def fingerprint(self) -> List[NodeDeviceResource]:
        try:
            import jax
            devs = [d for d in jax.devices() if d.platform == self.platform]
        except Exception:                       # noqa: BLE001
            return []
        if not devs:
            return []
        kind = getattr(devs[0], "device_kind", "tpu") or "tpu"
        return [
            NodeDeviceResource(
                vendor="google",
                type="tpu",
                name=str(kind),
                instance_ids=[f"tpu-{d.id}" for d in devs],
                attributes={"platform": self.platform, "count": str(len(devs))},
            )
        ]

    def reserve(self, device_ids: List[str]) -> ReservationResponse:
        visible = ",".join(i.rsplit("-", 1)[-1] for i in device_ids)
        return ReservationResponse(
            container_res={"TPU_VISIBLE_DEVICES": visible}
        )
