"""CSI plugin client interface.

Reference behavior: plugins/csi/client.go (~1.5k LoC) -- the gRPC
client Nomad uses to talk to CSI controller and node plugins
(ControllerPublishVolume / ControllerUnpublishVolume /
NodeStageVolume / NodePublishVolume / NodeUnpublishVolume /
ValidateVolumeCapabilities). The build exposes the same verb surface as
an in-process interface; real deployments would back it with a gRPC
channel to the plugin's unix socket, tests and the dev agent use
``FakeCSIClient`` (the analog of plugins/csi/fake/client.go).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple


class CSIClientError(Exception):
    pass


class CSIClient:
    """Verb surface of plugins/csi/client.go."""

    def plugin_probe(self) -> bool:
        raise NotImplementedError

    def plugin_get_info(self) -> Dict:
        raise NotImplementedError

    def controller_publish_volume(self, external_id: str, node_external_id: str,
                                  read_only: bool, capability: Dict) -> Dict:
        raise NotImplementedError

    def controller_unpublish_volume(self, external_id: str,
                                    node_external_id: str) -> None:
        raise NotImplementedError

    def controller_validate_capabilities(self, external_id: str,
                                         capabilities: List[Dict]) -> None:
        raise NotImplementedError

    def controller_create_volume(self, name: str, capacity_min: int,
                                 capacity_max: int,
                                 capabilities: List[Dict],
                                 parameters: Dict) -> Dict:
        raise NotImplementedError

    def controller_delete_volume(self, external_id: str) -> None:
        raise NotImplementedError

    def node_stage_volume(self, external_id: str, staging_path: str,
                          capability: Dict, context: Dict) -> None:
        raise NotImplementedError

    def node_unstage_volume(self, external_id: str, staging_path: str) -> None:
        raise NotImplementedError

    def node_publish_volume(self, external_id: str, staging_path: str,
                            target_path: str, read_only: bool,
                            capability: Dict) -> None:
        raise NotImplementedError

    def node_unpublish_volume(self, external_id: str, target_path: str) -> None:
        raise NotImplementedError


class FakeCSIClient(CSIClient):
    """In-process fake with scriptable failures
    (plugins/csi/fake/client.go)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (external_id, node) -> published
        self.controller_published: Set[Tuple[str, str]] = set()
        self.node_staged: Set[Tuple[str, str]] = set()
        self.node_published: Set[Tuple[str, str]] = set()
        self.created_volumes: Dict[str, Dict] = {}
        # scriptable failures: verb name -> error message
        self.fail: Dict[str, str] = {}
        self.calls: List[Tuple[str, tuple]] = []

    def _call(self, verb: str, *args) -> None:
        with self._lock:
            self.calls.append((verb, args))
            if verb in self.fail:
                raise CSIClientError(self.fail[verb])

    def plugin_probe(self) -> bool:
        self._call("plugin_probe")
        return True

    def plugin_get_info(self) -> Dict:
        self._call("plugin_get_info")
        return {"name": "fake-csi", "version": "1.0.0"}

    def controller_publish_volume(self, external_id, node_external_id,
                                  read_only, capability):
        self._call("controller_publish_volume", external_id, node_external_id)
        self.controller_published.add((external_id, node_external_id))
        return {"publish_context": {}}

    def controller_unpublish_volume(self, external_id, node_external_id):
        self._call("controller_unpublish_volume", external_id, node_external_id)
        self.controller_published.discard((external_id, node_external_id))

    def controller_validate_capabilities(self, external_id, capabilities):
        self._call("controller_validate_capabilities", external_id)

    def controller_create_volume(self, name, capacity_min, capacity_max,
                                 capabilities, parameters):
        self._call("controller_create_volume", name)
        ext_id = f"ext-{name}"
        self.created_volumes[ext_id] = {
            "name": name, "capacity": capacity_max or capacity_min,
        }
        return {"external_id": ext_id, "capacity": capacity_max or capacity_min}

    def controller_delete_volume(self, external_id):
        self._call("controller_delete_volume", external_id)
        self.created_volumes.pop(external_id, None)

    def node_stage_volume(self, external_id, staging_path, capability, context):
        self._call("node_stage_volume", external_id, staging_path)
        self.node_staged.add((external_id, staging_path))

    def node_unstage_volume(self, external_id, staging_path):
        self._call("node_unstage_volume", external_id, staging_path)
        self.node_staged.discard((external_id, staging_path))

    def node_publish_volume(self, external_id, staging_path, target_path,
                            read_only, capability):
        self._call("node_publish_volume", external_id, target_path)
        self.node_published.add((external_id, target_path))

    def node_unpublish_volume(self, external_id, target_path):
        self._call("node_unpublish_volume", external_id, target_path)
        self.node_published.discard((external_id, target_path))
