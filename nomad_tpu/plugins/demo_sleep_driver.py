"""Demo external driver plugin: runs "sleep" tasks out of process.

The external-plugin analog of the reference's skeleton driver
(nomad-driver-skeleton): implements DriverPlugin against real child
processes and serves it over the stdio JSON protocol. Launch
standalone (``python -m nomad_tpu.plugins.demo_sleep_driver``) or
drop into a client's plugin_dir.

Task config: {"duration": "10s", "exit_code": 0}
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Dict, Optional

from nomad_tpu.jobspec.hcl import duration_s
from nomad_tpu.plugins.base import PLUGIN_TYPE_DRIVER, PluginInfo
from nomad_tpu.plugins.drivers import (
    HEALTH_HEALTHY,
    TASK_STATE_EXITED,
    TASK_STATE_RUNNING,
    DriverCapabilities,
    DriverPlugin,
    ExitResult,
    Fingerprint,
    TaskConfig,
    TaskHandle,
    TaskStatus,
)


class _SleepTask:
    def __init__(self, duration: float, exit_code: int) -> None:
        self.proc = subprocess.Popen(["sleep", str(max(duration, 0.01))])
        self.exit_code = exit_code
        self.started_at = time.time()
        self.completed_at = 0.0

    def poll(self) -> Optional[ExitResult]:
        rc = self.proc.poll()
        if rc is None:
            return None
        if not self.completed_at:
            self.completed_at = time.time()
        if rc == 0:
            return ExitResult(exit_code=self.exit_code)
        return ExitResult(exit_code=rc if rc > 0 else 0,
                          signal=-rc if rc < 0 else 0)


class SleepDriver(DriverPlugin):
    NAME = "sleep"

    def __init__(self) -> None:
        self._tasks: Dict[str, _SleepTask] = {}
        self._lock = threading.Lock()

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name=self.NAME, type=PLUGIN_TYPE_DRIVER,
                          plugin_version="0.1.0")

    def capabilities(self) -> DriverCapabilities:
        return DriverCapabilities(send_signals=True, exec_=False)

    def fingerprint(self) -> Fingerprint:
        return Fingerprint(attributes={"driver.sleep": "1"},
                           health=HEALTH_HEALTHY)

    def start_task(self, config: TaskConfig) -> TaskHandle:
        duration = duration_s(config.driver_config.get("duration", "1s"))
        exit_code = int(config.driver_config.get("exit_code", 0))
        task = _SleepTask(duration, exit_code)
        with self._lock:
            self._tasks[config.id] = task
        return TaskHandle(
            driver=self.NAME, config=config, state=TASK_STATE_RUNNING,
            driver_state={"pid": task.proc.pid, "exit_code": exit_code},
        )

    def recover_task(self, handle: TaskHandle) -> None:
        raise RuntimeError("sleep tasks don't survive plugin restarts")

    def wait_task(self, task_id: str,
                  timeout: Optional[float] = None) -> Optional[ExitResult]:
        with self._lock:
            task = self._tasks[task_id]
        deadline = None if timeout is None else time.time() + timeout
        while True:
            res = task.poll()
            if res is not None:
                return res
            if deadline is not None and time.time() >= deadline:
                return None
            time.sleep(0.05)

    def stop_task(self, task_id: str, timeout: float = 5.0,
                  signal: str = "SIGTERM") -> None:
        with self._lock:
            task = self._tasks.get(task_id)
        if task is not None and task.proc.poll() is None:
            task.proc.terminate()

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        with self._lock:
            task = self._tasks.pop(task_id, None)
        if task is not None and task.proc.poll() is None:
            task.proc.kill()

    def inspect_task(self, task_id: str) -> TaskStatus:
        with self._lock:
            task = self._tasks[task_id]
        res = task.poll()
        return TaskStatus(
            id=task_id,
            state=TASK_STATE_EXITED if res else TASK_STATE_RUNNING,
            started_at=task.started_at,
            completed_at=task.completed_at,
            exit_result=res,
        )

    def signal_task(self, task_id: str, signal: str) -> None:
        import signal as _sig
        with self._lock:
            task = self._tasks[task_id]
        if task.proc.poll() is None:
            task.proc.send_signal(getattr(_sig, signal, _sig.SIGTERM))


if __name__ == "__main__":
    from nomad_tpu.plugins.external import serve_driver

    serve_driver(SleepDriver(), SleepDriver.NAME)
