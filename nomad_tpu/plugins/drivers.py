"""Task driver plugin API.

Reference behavior: plugins/drivers/driver.go:47 ``DriverPlugin`` and
the wire contract plugins/drivers/proto/driver.proto:13-87:
TaskConfigSchema, Capabilities, Fingerprint (stream), RecoverTask,
StartTask, WaitTask, StopTask, DestroyTask, InspectTask, TaskStats,
TaskEvents, SignalTask, ExecTask. ``TaskHandle`` (task_handle.go)
carries enough opaque driver state to reattach to a live task after an
agent restart.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from nomad_tpu.plugins.base import BasePlugin, PluginInfo

# Fingerprint health states (drivers/driver.go HealthState*)
HEALTH_UNDETECTED = "undetected"
HEALTH_UNHEALTHY = "unhealthy"
HEALTH_HEALTHY = "healthy"

# Task states (drivers/driver.go TaskState*)
TASK_STATE_UNKNOWN = "unknown"
TASK_STATE_RUNNING = "running"
TASK_STATE_EXITED = "exited"


@dataclass
class Fingerprint:
    attributes: Dict[str, str] = field(default_factory=dict)
    health: str = HEALTH_UNDETECTED
    health_description: str = ""


@dataclass
class DriverCapabilities:
    """drivers/driver.go Capabilities."""

    send_signals: bool = True
    exec_: bool = False
    fs_isolation: str = "none"       # none | chroot | image
    remote_tasks: bool = False
    # the driver owns group-network creation (drivers/driver.go:92
    # DriverNetworkManager + MustInitiateNetwork): docker containers
    # cannot join a client-made namespace, so the driver builds the
    # shared sandbox (pause container) and tasks attach to IT
    must_create_network: bool = False


@dataclass
class NetworkIsolationSpec:
    """drivers/driver.go NetworkIsolationSpec: how a task joins its
    group's shared network namespace — a named netns for exec-family
    drivers, or driver-private labels (the docker sandbox/pause
    container) for drivers that own the namespace."""

    mode: str = "group"
    netns: str = ""
    ip: str = ""                     # sandbox address (NOMAD_ALLOC_IP)
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class TaskConfig:
    """drivers/driver.go TaskConfig -- what StartTask receives."""

    id: str = ""                      # alloc_id + task name
    name: str = ""
    alloc_id: str = ""
    job_name: str = ""
    task_group_name: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    # driver-specific config block (the jobspec task "config" stanza)
    driver_config: Dict[str, Any] = field(default_factory=dict)
    resources: Optional[object] = None
    std_out_path: str = ""
    std_err_path: str = ""
    alloc_dir: str = ""
    # bridge-mode network namespace to join (networking_bridge_linux)
    netns: str = ""
    # driver-created group network to attach to (DriverNetworkManager)
    network_isolation: Optional[NetworkIsolationSpec] = None


@dataclass
class TaskHandle:
    """Opaque reattach state (plugins/drivers/task_handle.go)."""

    driver: str = ""
    config: Optional[TaskConfig] = None
    state: str = TASK_STATE_UNKNOWN
    # driver-private (e.g. pid, container id); must survive serialization
    driver_state: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ExitResult:
    exit_code: int = 0
    signal: int = 0
    oom_killed: bool = False
    err: str = ""

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and not self.err


@dataclass
class TaskStatus:
    id: str = ""
    name: str = ""
    state: str = TASK_STATE_UNKNOWN
    started_at: float = 0.0
    completed_at: float = 0.0
    exit_result: Optional[ExitResult] = None


class DriverPlugin(BasePlugin):
    """drivers/driver.go:47."""

    def task_config_schema(self) -> Dict:
        return {}

    def capabilities(self) -> DriverCapabilities:
        return DriverCapabilities()

    def fingerprint(self) -> Fingerprint:
        """One fingerprint sample; the driver manager polls this into a
        stream (driver.proto Fingerprint is server-streaming)."""
        raise NotImplementedError

    def start_task(self, config: TaskConfig) -> TaskHandle:
        raise NotImplementedError

    def recover_task(self, handle: TaskHandle) -> None:
        """Reattach to a live task after agent restart (driver.proto:35)."""
        raise NotImplementedError

    def wait_task(self, task_id: str, timeout: Optional[float] = None) -> Optional[ExitResult]:
        """Block until the task exits; None on timeout."""
        raise NotImplementedError

    def stop_task(self, task_id: str, timeout: float = 5.0, signal: str = "SIGTERM") -> None:
        raise NotImplementedError

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        raise NotImplementedError

    def inspect_task(self, task_id: str) -> TaskStatus:
        raise NotImplementedError

    def task_stats(self, task_id: str) -> Dict:
        return {"cpu": {}, "memory": {}}

    def signal_task(self, task_id: str, signal: str) -> None:
        raise NotImplementedError

    def exec_task(self, task_id: str, cmd: List[str], timeout: float = 30.0) -> Dict:
        raise NotImplementedError("driver does not support exec")

    def task_events(self) -> List[Dict]:
        """Drain buffered task events (driver.proto TaskEvents stream)."""
        return []

    # -- DriverNetworkManager (drivers/driver.go:92) ---------------------

    def create_network(self, alloc_id: str,
                       port_mappings: Optional[List] = None
                       ) -> Optional["NetworkIsolationSpec"]:
        """Create the allocation's shared network sandbox. Only drivers
        with ``capabilities().must_create_network`` implement this
        (docker's pause container); None means the CLIENT owns bridge
        networking for this driver."""
        return None

    def destroy_network(self, alloc_id: str,
                        spec: "NetworkIsolationSpec") -> None:
        """Tear down a sandbox created by ``create_network``."""

    def recover_network(self, alloc_id: str,
                        port_mappings: Optional[List] = None
                        ) -> Optional["NetworkIsolationSpec"]:
        """Re-adopt a sandbox that outlived an agent restart; None when
        no live sandbox exists for the alloc. ``port_mappings`` lets an
        unhealthy sandbox be recreated with its original ports."""
        return None
