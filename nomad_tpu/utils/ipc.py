"""CRC-framed IPC channel between the consensus process and scheduler
worker processes (ISSUE 17).

The wire shape is the WAL's (PR 13): every message is one framed
record — a fixed ``(length, crc32)`` header followed by a pickled
payload — so a torn or corrupted read surfaces as :class:`FrameError`
at the boundary instead of a partially-applied message deeper in. The
transport underneath is a plain ``socketpair`` stream: worker processes
are spawned as fresh interpreters (``subprocess``, not fork — forking
would clone JAX runtime state, thread locks, and the device mesh into
the child, exactly the objects graftcheck R6 polices off this
boundary) and inherit one end by file descriptor.

Discipline for what crosses a :class:`Channel` (enforced by R6):
plain data only — evals, plans, snapshot frames, span rows, dicts of
scalars. Never device-resident arrays, locks/witness locks, tracer or
mesh handles, sockets, or thread/process objects.
"""

from __future__ import annotations

import pickle
import select
import socket
import struct
import threading
import zlib
from typing import Any, Optional, Tuple

#: frame header: payload length, crc32 of the payload (WAL framing, PR 13)
_FRAME = struct.Struct(">II")

#: refuse absurd frames (a corrupt length header would otherwise make
#: the reader try to allocate/await gigabytes)
MAX_FRAME_BYTES = 1 << 31


class FrameError(RuntimeError):
    """A frame failed its length or CRC check (torn/corrupt message)."""


class Channel:
    """One endpoint of a framed duplex stream.

    ``send`` is thread-safe (the worker's scheduler threads, heartbeat
    ticker, and RPC replies all write the same stream); ``recv`` is
    single-reader by design — each endpoint owns one reader loop.
    """

    __slots__ = ("_sock", "_send_lock")

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        self._send_lock = threading.Lock()

    def send(self, obj: Any) -> None:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        header = _FRAME.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF)
        with self._send_lock:
            try:
                self._sock.sendall(header + payload)
            except BrokenPipeError:
                raise EOFError("channel peer is gone")

    def _read_exact(self, n: int) -> bytes:
        bufs = []
        while n:
            chunk = self._sock.recv(min(n, 1 << 20))
            if not chunk:
                raise EOFError("channel closed")
            bufs.append(chunk)
            n -= len(chunk)
        return b"".join(bufs)

    def recv(self, timeout: Optional[float] = None) -> Any:
        """Next message; None on timeout when ``timeout`` is given.
        Raises EOFError when the peer is gone, FrameError on a frame
        that fails its length/CRC check."""
        if timeout is not None and not self.poll(timeout):
            return None
        header = self._read_exact(_FRAME.size)
        length, crc = _FRAME.unpack(header)
        if length > MAX_FRAME_BYTES:
            raise FrameError(f"frame length {length} exceeds cap")
        payload = self._read_exact(length)
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise FrameError("frame CRC mismatch")
        return pickle.loads(payload)

    def poll(self, timeout: float = 0.0) -> bool:
        try:
            r, _w, _x = select.select([self._sock], [], [], timeout)
        except (OSError, ValueError):
            # closed under us: report readable so recv raises EOFError
            return True
        return bool(r)

    def fileno(self) -> int:
        return self._sock.fileno()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def channel_pair() -> Tuple[Channel, Channel]:
    """A connected (owner, peer) Channel pair over a socketpair. For
    cross-process use, hand the peer end's inheritable fd to the child
    (``channel_from_fd`` reconstructs there) and close it locally."""
    a, b = socket.socketpair()
    return Channel(a), Channel(b)


def socket_pair() -> Tuple[socket.socket, socket.socket]:
    """The raw sockets, for callers that ship one end to a subprocess
    by fd (``pass_fds``) before wrapping their own end in a Channel."""
    return socket.socketpair()


def channel_from_fd(fd: int) -> Channel:
    """Reconstruct a Channel in a child process from an inherited
    socketpair fd (the subprocess spawn path)."""
    return Channel(socket.socket(socket.AF_UNIX, socket.SOCK_STREAM,
                                 fileno=fd))
