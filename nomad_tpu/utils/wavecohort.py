"""Wave-boundary plan-drain rendezvous (ISSUE 10).

A batched worker's wave of B evals lands ~B plans on the leader's plan
queue, but staggered: each member resumes from the kernel rendezvous,
builds its allocations, and submits on its own thread. The applier's
``dequeue_batch`` historically popped whatever had arrived when it woke
— ~5.6 plans per raft entry at batch 32 — so one wave cost ~6 raft
entries and ~6 FSM applies instead of one.

This tracker is the hint that closes the gap. The coalescer arms it
when a wave's device launch completes (``note_wave`` — the members are
about to build plans); every plan enqueue drains it (``note_plan``).
``PlanQueue.dequeue_batch`` keeps its condition-wait open while a
cohort is still landing (``pending_wait_s``), bounded by an adaptive
deadline — an EWMA of how long a cohort actually takes to drain, the
same self-correcting-window idea as the coalescer's adaptive park
deadline — so members that never submit (failed placements, no-op
plans) cost at most the window, never a hang.

Latency discipline: the deadline is the ONLY added wait, it is capped
(``WINDOW_MAX_S``), and it applies only while a wave is in flight;
single-plan traffic and idle queues behave exactly as before. The
steady-state e2e p99 gate (bench ``trace_e2e_p99_ms``) is the
regression guard.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from nomad_tpu.utils.witness import witness_lock


class WaveCohortTracker:
    """Process-wide wave -> plan-queue drain accounting."""

    #: drain window = drain EWMA x this factor (headroom for jitter)
    WINDOW_FACTOR = 2.0
    WINDOW_MIN_S = 0.002
    WINDOW_MAX_S = 0.150
    #: first-cohort window before any drain sample exists
    WINDOW_DEFAULT_S = 0.025
    #: each landing plan keeps the window open this much longer (the
    #: cohort is visibly still draining); a shortfall therefore costs
    #: at most this gap past the LAST real plan
    ARRIVAL_GAP_S = 0.015
    #: absolute bound per armed cohort, whatever the flow does
    HARD_CAP_S = 0.250
    EWMA_ALPHA = 0.25

    def __init__(self) -> None:
        self._lock = witness_lock("WaveCohortTracker._lock")
        self._due = 0                 # plans still expected from fired waves
        self._deadline = 0.0
        self._hard = 0.0
        self._fire_t = 0.0
        self._drain_ewma: Optional[float] = None
        self.waves = 0
        self.cohort_plans = 0
        self.drained_cohorts = 0
        self.expired_cohorts = 0
        self.hard_cap_hits = 0

    def _window_s(self) -> float:
        if self._drain_ewma is None:
            return self.WINDOW_DEFAULT_S
        return min(max(self._drain_ewma * self.WINDOW_FACTOR,
                       self.WINDOW_MIN_S), self.WINDOW_MAX_S)

    def note_wave(self, members: int) -> None:
        """A wave of ``members`` evals just finished its device launch:
        ~that many plans are about to land on the queue."""
        if members <= 0:
            return
        now = time.monotonic()
        with self._lock:
            self.waves += 1
            if self._due <= 0:
                self._fire_t = now
            self._due += members
            self._hard = max(self._hard, now + self.HARD_CAP_S)
            want = max(self._deadline, now + self._window_s())
            if want > self._hard:
                self.hard_cap_hits += 1
            self._deadline = min(want, self._hard)

    def note_plan(self) -> None:
        """One plan enqueued. A flowing cohort keeps its window open
        (arrival extension, hard-capped); when the whole cohort has
        landed, record the drain latency sample and release it."""
        with self._lock:
            if self._due <= 0:
                return
            self._due -= 1
            self.cohort_plans += 1
            now = time.monotonic()
            if self._due == 0:
                sample = now - self._fire_t
                if self._drain_ewma is None:
                    self._drain_ewma = sample
                else:
                    self._drain_ewma += self.EWMA_ALPHA * (
                        sample - self._drain_ewma)
                self.drained_cohorts += 1
                self._deadline = 0.0
            else:
                want = max(self._deadline, now + self.ARRIVAL_GAP_S)
                if want > self._hard:
                    self.hard_cap_hits += 1
                self._deadline = min(want, self._hard)

    def pending_wait_s(self) -> float:
        """Seconds the applier should keep its drain window open
        (0.0 = nothing outstanding, commit what you have)."""
        now = time.monotonic()
        with self._lock:
            if self._due <= 0:
                return 0.0
            if now >= self._deadline:
                # cohort shortfall (failed placements / no-op plans):
                # expire rather than stall the applier
                self._due = 0
                self._deadline = 0.0
                self.expired_cohorts += 1
                return 0.0
            return self._deadline - now

    def reset_stats(self) -> None:
        """Counters only — the learned drain EWMA survives (it is
        timing calibration, not burst data)."""
        with self._lock:
            self.waves = 0
            self.cohort_plans = 0
            self.drained_cohorts = 0
            self.expired_cohorts = 0
            self.hard_cap_hits = 0

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "waves": self.waves,
                "cohort_plans": self.cohort_plans,
                "drained_cohorts": self.drained_cohorts,
                "expired_cohorts": self.expired_cohorts,
                "hard_cap_hits": self.hard_cap_hits,
                "drain_ewma_ms": (self._drain_ewma or 0.0) * 1e3,
                "due": self._due,
            }


#: process-wide (the coalescer arms it, the plan queue drains it)
wave_cohorts = WaveCohortTracker()
