"""Minimal RFC 6455 WebSocket: handshake + framing, both roles.

Reference behavior: `nomad alloc exec` runs over a websocket from the
CLI/SDK to the agent HTTP API (api/allocations_exec.go:13), which the
server forwards to the allocation's node. The environment has no
websocket library, so this implements the subset the exec path needs:
HTTP/1.1 upgrade, client-masked frames, text/binary/ping/pong/close,
no extensions, no fragmentation of outgoing messages (incoming
fragmented messages are reassembled).
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import ssl
import struct
import urllib.parse
from typing import Optional, Tuple

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# Largest message a peer may send; frames above this are rejected
# before allocation (exec stdio and API payloads sit far below this).
MAX_FRAME_BYTES = 16 * 1024 * 1024

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def accept_key(key: str) -> str:
    digest = hashlib.sha1((key + _GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def _xor_mask(payload: bytes, key: bytes) -> bytes:
    """Mask/unmask via one big-int XOR (a per-byte Python loop on the
    stdio hot path caps exec throughput at a few MB/s)."""
    n = len(payload)
    if n == 0:
        return payload
    full = key * (n // 4) + key[: n % 4]
    return (int.from_bytes(payload, "big")
            ^ int.from_bytes(full, "big")).to_bytes(n, "big")


def write_frame(wfile, opcode: int, payload: bytes, mask: bool = False) -> None:
    """One unfragmented frame. Clients MUST mask (RFC 6455 5.3)."""
    head = bytes([0x80 | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        head += bytes([mask_bit | n])
    elif n < 65536:
        head += bytes([mask_bit | 126]) + struct.pack(">H", n)
    else:
        head += bytes([mask_bit | 127]) + struct.pack(">Q", n)
    if mask:
        key = os.urandom(4)
        wfile.write(head + key + _xor_mask(payload, key))
    else:
        wfile.write(head + payload)
    wfile.flush()


def _read_exact(rfile, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = rfile.read(n - len(buf))
        if not chunk:
            raise ConnectionError("websocket peer closed")
        buf += chunk
    return buf


def read_frame(rfile) -> Tuple[int, bytes]:
    """Read one complete message (reassembles continuation frames)."""
    opcode = None
    payload = b""
    while True:
        b1, b2 = _read_exact(rfile, 2)
        fin = b1 & 0x80
        op = b1 & 0x0F
        masked = b2 & 0x80
        n = b2 & 0x7F
        if n == 126:
            n = struct.unpack(">H", _read_exact(rfile, 2))[0]
        elif n == 127:
            n = struct.unpack(">Q", _read_exact(rfile, 8))[0]
        if n > MAX_FRAME_BYTES or len(payload) + n > MAX_FRAME_BYTES:
            # peer-supplied 64-bit length: cap before allocating so a
            # hostile client can't drive unbounded memory growth (1009)
            raise ConnectionError(f"websocket frame too large: {n}")
        key = _read_exact(rfile, 4) if masked else b""
        data = _read_exact(rfile, n) if n else b""
        if masked:
            data = _xor_mask(data, key)
        if op in (OP_CLOSE, OP_PING, OP_PONG):
            return op, data            # control frames are never fragmented
        if opcode is None:
            opcode = op
        payload += data
        if fin:
            return opcode, payload


def server_handshake(handler) -> bool:
    """Upgrade an in-flight http.server request. Returns False (with a
    400 written) when the request is not a valid websocket upgrade."""
    key = handler.headers.get("Sec-WebSocket-Key", "")
    if not key:
        handler.send_response(400)
        handler.end_headers()
        return False
    handler.send_response(101, "Switching Protocols")
    handler.send_header("Upgrade", "websocket")
    handler.send_header("Connection", "Upgrade")
    handler.send_header("Sec-WebSocket-Accept", accept_key(key))
    handler.end_headers()
    handler.wfile.flush()
    return True


class WSConn:
    """Client-side connection (used by the SDK/CLI and by node
    forwarding when tunneling is not possible)."""

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.rfile = sock.makefile("rb")
        self.wfile = sock.makefile("wb")

    def send(self, payload: bytes, opcode: int = OP_TEXT) -> None:
        write_frame(self.wfile, opcode, payload, mask=True)

    def recv(self) -> Tuple[int, bytes]:
        return read_frame(self.rfile)

    def close(self) -> None:
        try:
            write_frame(self.wfile, OP_CLOSE, b"", mask=True)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def connect(url: str, token: str = "",
            tls_context: Optional[ssl.SSLContext] = None,
            timeout: float = 30.0) -> WSConn:
    """Dial ws over the agent's http(s) URL (http://host:port/path?q)."""
    parsed = urllib.parse.urlparse(url)
    host = parsed.hostname
    port = parsed.port or (443 if parsed.scheme == "https" else 80)
    sock = socket.create_connection((host, port), timeout=timeout)
    if parsed.scheme == "https":
        ctx = tls_context or ssl.create_default_context()
        sock = ctx.wrap_socket(sock, server_hostname=host)
    # the connect timeout must not apply to session reads: an exec
    # session idling past it would be torn down mid-stream
    sock.settimeout(None)
    key = base64.b64encode(os.urandom(16)).decode()
    path = parsed.path + (f"?{parsed.query}" if parsed.query else "")
    lines = [
        f"GET {path} HTTP/1.1",
        f"Host: {host}:{port}",
        "Upgrade: websocket",
        "Connection: Upgrade",
        f"Sec-WebSocket-Key: {key}",
        "Sec-WebSocket-Version: 13",
    ]
    if token:
        lines.append(f"X-Nomad-Token: {token}")
    try:
        sock.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())
        conn = WSConn(sock)
        status_line = conn.rfile.readline().decode(errors="replace")
        if " 101 " not in status_line and \
                not status_line.rstrip().endswith("101"):
            parts = status_line.split(None, 2)
            code = parts[1] if len(parts) > 1 else "?"
            # drain headers + any body snippet for the error message
            while True:
                line = conn.rfile.readline()
                if not line or line in (b"\r\n", b"\n"):
                    break
            raise ConnectionError(f"websocket upgrade refused: HTTP {code}")
        while True:
            line = conn.rfile.readline()
            if not line or line in (b"\r\n", b"\n"):
                break
        return conn
    except BaseException:
        # a refused/failed upgrade must not leak the socket (retrying
        # SDKs would accumulate fds to EMFILE)
        try:
            sock.close()
        except OSError:
            pass
        raise
