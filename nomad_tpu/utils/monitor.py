"""Live log monitoring and process introspection.

Reference behavior: command/agent/monitor/monitor.go -- `/v1/agent/
monitor` streams the agent's logs at a chosen level to HTTP clients
(the `nomad monitor` CLI); command/agent/pprof/pprof.go serves live
profiles. The Python analogs: a logging.Handler fan-out for the
monitor, a thread-stack dump for goroutine profiles, and a sampling
wall-clock profiler (10ms ticks over all threads) for CPU profiles.
"""

from __future__ import annotations

import collections
import logging
import queue
import sys
import threading
import time
import traceback
from typing import Dict, Iterator, List, Optional


class LogMonitor(logging.Handler):
    """Fan logging records out to stream subscribers (monitor.go)."""

    _installed: Optional["LogMonitor"] = None

    def __init__(self) -> None:
        super().__init__()
        self._lock2 = threading.Lock()
        self._subs: List[queue.Queue] = []
        self._saved_root_level: Optional[int] = None
        self.setFormatter(logging.Formatter(
            "%(asctime)s [%(levelname)s] %(name)s: %(message)s"
        ))

    @classmethod
    def install(cls) -> "LogMonitor":
        """Attach one shared handler to the root logger."""
        if cls._installed is None:
            cls._installed = cls()
            logging.getLogger().addHandler(cls._installed)
        return cls._installed

    def emit(self, record: logging.LogRecord) -> None:
        with self._lock2:
            subs = list(self._subs)
        if not subs:
            return
        try:
            line = self.format(record)
        except Exception:                       # noqa: BLE001
            return
        for q in subs:
            try:
                q.put_nowait((record.levelno, line))
            except queue.Full:
                pass   # slow consumer drops lines, never blocks logging

    def subscribe(self, level: str = "info") -> queue.Queue:
        q: queue.Queue = queue.Queue(maxsize=512)
        q.min_level = getattr(logging, level.upper(), logging.INFO)
        root = logging.getLogger()
        with self._lock2:
            if not self._subs:
                self._saved_root_level = root.level
            self._subs.append(q)
            self._apply_root_level(root)
        return q

    def unsubscribe(self, q: queue.Queue) -> None:
        root = logging.getLogger()
        with self._lock2:
            if q in self._subs:
                self._subs.remove(q)
            self._apply_root_level(root)

    def _apply_root_level(self, root: logging.Logger) -> None:
        """The unconfigured root logger gates at WARNING, which would
        suppress INFO/DEBUG records before they ever reach this handler
        (Go's monitor filters at the sink instead). While subscribers
        exist, lower the root level to the lowest subscribed level;
        restore the original level once the last one leaves. Stderr
        doesn't get noisier: logging.lastResort stays at WARNING."""
        if self._subs:
            floor = min(s.min_level for s in self._subs)
            if root.getEffectiveLevel() > floor:
                root.setLevel(floor)
        elif self._saved_root_level is not None:
            root.setLevel(self._saved_root_level)
            self._saved_root_level = None

    def stream(self, level: str = "info",
               stop: Optional[threading.Event] = None) -> Iterator[str]:
        """Yield formatted lines until `stop` is set."""
        q = self.subscribe(level)
        try:
            while stop is None or not stop.is_set():
                try:
                    levelno, line = q.get(timeout=0.5)
                except queue.Empty:
                    yield ""   # keepalive tick
                    continue
                if levelno >= q.min_level:
                    yield line
        finally:
            self.unsubscribe(q)


def thread_dump() -> str:
    """All live thread stacks (pprof goroutine analog)."""
    frames = sys._current_frames()
    names = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        t = names.get(ident)
        name = t.name if t else f"thread-{ident}"
        daemon = "daemon" if (t and t.daemon) else "main"
        out.append(f"thread {name} [{daemon}] (ident {ident}):")
        out.extend(l.rstrip() for l in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


def sample_profile(seconds: float = 1.0, hz: int = 100) -> str:
    """Statistical wall-clock profile across all threads (pprof
    profile analog): sample stacks at `hz`, aggregate by frame."""
    interval = 1.0 / hz
    counts: Dict[str, int] = collections.Counter()
    deadline = time.time() + seconds
    n_samples = 0
    me = threading.get_ident()
    while time.time() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            stack = traceback.extract_stack(frame)
            if not stack:
                continue
            leaf = stack[-1]
            counts[f"{leaf.name} ({leaf.filename}:{leaf.lineno})"] += 1
        n_samples += 1
        time.sleep(interval)
    total = sum(counts.values()) or 1
    lines = [f"samples: {n_samples} over {seconds:.1f}s at {hz}Hz", ""]
    for frame_id, n in sorted(counts.items(), key=lambda kv: -kv[1])[:60]:
        lines.append(f"{n:6d} {100.0 * n / total:5.1f}%  {frame_id}")
    return "\n".join(lines)


def heap_summary(top: int = 40) -> str:
    """Object counts by type (pprof heap analog)."""
    import gc

    counts: Dict[str, int] = collections.Counter()
    for obj in gc.get_objects():
        counts[type(obj).__name__] += 1
    lines = [f"live objects: {sum(counts.values())}", ""]
    for name, n in sorted(counts.items(), key=lambda kv: -kv[1])[:top]:
        lines.append(f"{n:8d}  {name}")
    return "\n".join(lines)
