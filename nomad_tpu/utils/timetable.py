"""TimeTable: map state indexes to wall-clock time.

Reference behavior: nomad/timetable.go (134 LoC) -- the leader
witnesses (raft index, time) pairs so GC can translate "older than 1
hour" into "modify_index <= N".
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import List, Tuple


class TimeTable:
    def __init__(self, limit: int = 4096) -> None:
        self._lock = threading.Lock()
        self._entries: List[Tuple[float, int]] = []   # (when, index) ascending
        self.limit = limit

    def witness(self, index: int, when: float = None) -> None:
        when = time.time() if when is None else when
        with self._lock:
            if self._entries and index <= self._entries[-1][1]:
                return
            self._entries.append((when, index))
            if len(self._entries) > self.limit:
                del self._entries[: len(self._entries) - self.limit]

    def nearest_index(self, when: float) -> int:
        """Largest witnessed index at or before `when` (0 if none)."""
        with self._lock:
            pos = bisect.bisect_right(self._entries, (when, float("inf")))
            if pos == 0:
                return 0
            return self._entries[pos - 1][1]
