"""Small self-contained libraries (reference lib/ and helper/)."""

from nomad_tpu.utils.delayheap import DelayHeap
from nomad_tpu.utils.kheap import ScoreHeap

__all__ = ["DelayHeap", "ScoreHeap"]
