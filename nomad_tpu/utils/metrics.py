"""Telemetry: in-memory metrics registry with prometheus exposition.

Reference behavior: armon/go-metrics with inmem + prometheus sinks
(command/agent/command.go:1044 setupTelemetry; /v1/metrics
http.go:383). Counters, gauges, and sample timers (with p50/p95/max
aggregation over a sliding window), labeled, concurrency-safe.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

_Key = Tuple[str, Tuple[Tuple[str, str], ...]]


def _key(name: str, labels: Optional[Dict[str, str]]) -> _Key:
    return name, tuple(sorted((labels or {}).items()))


class MetricsRegistry:
    def __init__(self, window_s: float = 60.0) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[_Key, float] = {}
        self._gauges: Dict[_Key, float] = {}
        self._samples: Dict[_Key, deque] = {}
        self.window_s = window_s

    def incr_counter(self, name: str, value: float = 1.0,
                     labels: Optional[Dict[str, str]] = None) -> None:
        k = _key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + value

    def set_gauge(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._gauges[_key(name, labels)] = value

    def add_sample(self, name: str, value: float,
                   labels: Optional[Dict[str, str]] = None) -> None:
        k = _key(name, labels)
        now = time.time()
        with self._lock:
            dq = self._samples.setdefault(k, deque(maxlen=4096))
            dq.append((now, value))

    def measure_since(self, name: str, start: float,
                      labels: Optional[Dict[str, str]] = None) -> None:
        self.add_sample(name, (time.time() - start) * 1000.0, labels)

    class _Timer:
        def __init__(self, reg: "MetricsRegistry", name: str, labels) -> None:
            self.reg, self.name, self.labels = reg, name, labels

        def __enter__(self):
            self.start = time.time()
            return self

        def __exit__(self, *exc):
            self.reg.measure_since(self.name, self.start, self.labels)

    def timer(self, name: str, labels: Optional[Dict[str, str]] = None):
        return self._Timer(self, name, labels)

    # -- exposition ------------------------------------------------------

    def _sample_stats(self, dq: deque) -> Dict[str, float]:
        cutoff = time.time() - self.window_s
        vals = sorted(v for t, v in dq if t >= cutoff)
        if not vals:
            return {"count": 0}
        n = len(vals)
        return {
            "count": n,
            "min": vals[0],
            "max": vals[-1],
            "mean": sum(vals) / n,
            "p50": vals[n // 2],
            "p95": vals[min(n - 1, int(n * 0.95))],
            "p99": vals[min(n - 1, int(n * 0.99))],
        }

    def summary(self) -> Dict:
        with self._lock:
            return {
                "Counters": [
                    {"Name": name, "Labels": dict(labels), "Count": v}
                    for (name, labels), v in sorted(self._counters.items())
                ],
                "Gauges": [
                    {"Name": name, "Labels": dict(labels), "Value": v}
                    for (name, labels), v in sorted(self._gauges.items())
                ],
                "Samples": [
                    {"Name": name, "Labels": dict(labels),
                     **self._sample_stats(dq)}
                    for (name, labels), dq in sorted(self._samples.items())
                ],
                "Timestamp": time.strftime("%Y-%m-%d %H:%M:%S +0000 UTC",
                                           time.gmtime()),
            }

    def prometheus_text(self) -> str:
        """Prometheus text exposition format."""

        def fmt_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
            if not labels:
                return ""
            inner = ",".join(f'{k}="{v}"' for k, v in labels)
            return "{" + inner + "}"

        def sanitize(name: str) -> str:
            return name.replace(".", "_").replace("-", "_")

        lines: List[str] = []
        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                n = sanitize(name)
                lines.append(f"# TYPE {n} counter")
                lines.append(f"{n}{fmt_labels(labels)} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                n = sanitize(name)
                lines.append(f"# TYPE {n} gauge")
                lines.append(f"{n}{fmt_labels(labels)} {v}")
            for (name, labels), dq in sorted(self._samples.items()):
                n = sanitize(name)
                stats = self._sample_stats(dq)
                if not stats.get("count"):
                    continue
                lines.append(f"# TYPE {n} summary")
                for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                    ql = labels + (("quantile", q),)
                    lines.append(f"{n}{fmt_labels(ql)} {stats[key]}")
                lines.append(f"{n}_count{fmt_labels(labels)} {stats['count']}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._samples.clear()


global_registry = MetricsRegistry()
