"""Runtime lock witness: FreeBSD-WITNESS-style order checking.

graftcheck's R2 builds a lock-acquisition-order graph STATICALLY —
but static naming cannot unify every lock identity (two modules
reaching the same store lock through different attribute chains), and
it only sees orders the source spells out lexically. This module is
the runtime companion: a drop-in wrapper for the project's locks that
records the cross-thread acquisition orders that ACTUALLY execute,
fails fast on order-inversion cycles (the A→B / B→A pattern that is a
deadlock the interleaving just hasn't hit yet), and feeds per-lock
hold-time distributions into the PR 8 streaming-histogram /
Prometheus-exporter infrastructure
(``nomad_tpu_latency_seconds{op="lock_hold_<name>"}``).

Cost model:

- **Disabled (the default):** ``witness_lock(name)`` returns a plain
  ``threading.Lock`` / ``RLock`` — literally zero overhead, no
  wrapper object anywhere on the hot path.
- **Enabled** (``NOMAD_TPU_WITNESS=1`` at process start, or
  ``witness.enable()`` before constructing the objects under test):
  each acquire walks the held-lock stack (almost always depth ≤ 2),
  consults the order graph under its own small mutex, and each
  release records one histogram sample.

The stress tier (``pytest -m stress``) constructs its brokers /
coalescers / membership under an enabled witness and asserts ZERO
inversion reports; ``NOMAD_TPU_WITNESS_RAISE=1`` additionally raises
``WitnessInversion`` at the offending acquire for fail-fast
debugging. See docs/ANALYSIS.md ("The runtime lock witness").
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "WitnessInversion", "enable", "disable", "enabled", "reset",
    "violations", "order_edges", "witness_lock", "WitnessLock",
]


class WitnessInversion(RuntimeError):
    """Raised at acquire time (opt-in) when the acquisition would
    close a cycle in the observed lock-order graph."""


_ENABLED = os.environ.get("NOMAD_TPU_WITNESS", "") not in ("", "0")
_RAISE = os.environ.get("NOMAD_TPU_WITNESS_RAISE", "") not in ("", "0")

#: witness bookkeeping mutex (never held while blocking on a wrapped
#: lock — order checks run BEFORE the inner acquire, updates after)
_graph_lock = threading.Lock()
#: observed order edges: name -> names acquired while it was held
_edges: Dict[str, Set[str]] = {}
#: inversion reports: (held, acquiring, cycle path, thread name)
_violations: List[Tuple[str, str, Tuple[str, ...], str]] = []

_tls = threading.local()


def enable() -> None:
    """Instrument locks created from now on (existing plain locks stay
    plain — construct the objects under test AFTER enabling)."""
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def reset() -> None:
    """Clear the order graph and the violation reports (test cells)."""
    with _graph_lock:
        _edges.clear()
        del _violations[:]


def violations() -> List[Tuple[str, str, Tuple[str, ...], str]]:
    with _graph_lock:
        return list(_violations)


def order_edges() -> Dict[str, Set[str]]:
    with _graph_lock:
        return {k: set(v) for k, v in _edges.items()}


#: witness names where nesting two DIFFERENT instances of the same
#: name is sanctioned (FreeBSD WITNESS's DUPOK): order between
#: same-name instances is inherently ambiguous at name granularity,
#: so it is flagged unless listed here. Empty on purpose — nothing in
#: the tree nests same-name locks today.
DUP_OK: Set[str] = set()


def _held_stack() -> List[Tuple[str, int, float]]:
    """Per-thread stack of (name, id(inner lock), acquire time)."""
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _reachable(src: str, dst: str) -> Optional[Tuple[str, ...]]:
    """Path src→…→dst in the edge graph (caller holds _graph_lock)."""
    seen = {src}
    stack: List[Tuple[str, Tuple[str, ...]]] = [(src, (src,))]
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + (nxt,)))
    return None


def _before_acquire(name: str, key: int) -> None:
    held = _held_stack()
    if not held:
        return
    if any(k == key for _, k, _ in held):
        # reentrant re-acquire of the SAME lock instance (RLock):
        # no new ordering information
        return
    held_names = {h for h, _, _ in held}
    if name in held_names:
        # a DIFFERENT instance under the same witness name: order
        # between same-name instances is ambiguous at name
        # granularity — a cross-instance ABBA here would otherwise
        # hide behind the reentrancy skip, so flag it (DUPOK-style)
        # unless the name is explicitly sanctioned
        if name not in DUP_OK:
            with _graph_lock:
                _violations.append(
                    (name, name, ("DUPOK", name),
                     threading.current_thread().name))
                if _RAISE:
                    raise WitnessInversion(
                        f"nesting two instances of witness lock "
                        f"{name!r}: same-name order is unverifiable — "
                        f"give the instances distinct names or add "
                        f"the name to witness.DUP_OK")
        return
    with _graph_lock:
        for h in held_names:
            # adding edge h→name closes a cycle iff name already
            # reaches h; record the inversion with the witness path
            path = _reachable(name, h)
            if path is not None:
                _violations.append(
                    (h, name, path + (name,),
                     threading.current_thread().name))
                if _RAISE:
                    raise WitnessInversion(
                        f"lock order inversion: acquiring {name!r} "
                        f"while holding {h!r}, but the observed order "
                        f"is {' -> '.join(path + (name,))}")
        for h in held_names:
            _edges.setdefault(h, set()).add(name)


def _on_acquired(name: str, key: int) -> None:
    _held_stack().append((name, key, time.perf_counter()))


def _on_release(name: str, key: int) -> Optional[float]:
    """Pop the held entry; returns the hold duration. The caller
    records it AFTER releasing the inner lock — the histogram's own
    lock and record cost must not run inside the witnessed critical
    section (it would lengthen the very hold times being measured)."""
    held = _held_stack()
    for i in range(len(held) - 1, -1, -1):
        if held[i][1] == key:
            _, _, t0 = held.pop(i)
            return time.perf_counter() - t0
    return None


def _record_hold(name: str, dt: Optional[float]) -> None:
    if dt is None:
        return
    try:
        from nomad_tpu.telemetry.histogram import histograms

        histograms.get(f"lock_hold_{name}").record(dt)
    except Exception:                       # noqa: BLE001 - metric only
        pass


class WitnessLock:
    """Order-checked, hold-timed wrapper over a threading lock.

    Duck-compatible with ``threading.Lock``/``RLock`` including the
    private hooks ``threading.Condition`` uses, so
    ``threading.Condition(witness_lock("X"))`` works and the wait/
    notify fast path keeps witness bookkeeping consistent across the
    release-reacquire inside ``wait()``.
    """

    __slots__ = ("_name", "_inner")

    def __init__(self, name: str, inner) -> None:
        self._name = name
        self._inner = inner

    # -- core lock protocol ----------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        _before_acquire(self._name, id(self._inner))
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _on_acquired(self._name, id(self._inner))
        return ok

    def release(self) -> None:
        dt = _on_release(self._name, id(self._inner))
        self._inner.release()
        _record_hold(self._name, dt)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # -- Condition integration (delegates preserve RLock semantics) ------

    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        dt = _on_release(self._name, id(self._inner))
        inner = self._inner
        if hasattr(inner, "_release_save"):
            state = inner._release_save()
        else:
            inner.release()
            state = None
        _record_hold(self._name, dt)
        return state

    def _acquire_restore(self, state) -> None:
        _before_acquire(self._name, id(self._inner))
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        _on_acquired(self._name, id(self._inner))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<WitnessLock {self._name} over {self._inner!r}>"


def witness_lock(name: str, rlock: bool = False):
    """A lock for project hot-path objects: plain when the witness is
    disabled (zero overhead), order-checked + hold-timed when enabled.
    ``name`` should be stable and unique-ish (``Class.attr``) — it is
    the lock's identity in the order graph and its histogram label."""
    inner = threading.RLock() if rlock else threading.Lock()
    if not _ENABLED:
        return inner
    return WitnessLock(name, inner)
