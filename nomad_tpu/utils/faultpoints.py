"""Deterministic fault-injection plane (ISSUE 12).

The failure half of the capability bar: the cluster's soundness story
is not "nothing fails" but "the pipeline converges when things die
mid-flight" (Raft failover, crashed eval threads, rejected plan
commits, missed heartbeats). This module is the seam layer that lets
the chaos cell (bench/trace_report.run_chaos_burst) and pinned-seed
regression tests exercise those failures ON PURPOSE, at the exact
points where real ones land, without any test-only forks of the
production code paths.

Cost discipline — the ``witness_lock`` pattern (utils/witness.py):

- **Disarmed (the default):** ``fault("name")`` is one module-global
  boolean check and an immediate return. No dict lookup, no lock, no
  allocation. The steady-burst CI gates (0 jit misses, plan-group
  size, h2d share) run with every point compiled in and disarmed.
- **Armed** (``arm(schedule, seed=...)``): each hit takes the small
  registry lock, bumps the point's hit counter, and consults its
  deterministic schedule. Sleeps (latency injection) happen OUTSIDE
  the registry lock.

Schedules are DETERMINISTIC AND SEEDED: each point draws its
per-hit decisions from ``random.Random(crc32(point) ^ seed)``, so
re-arming the same ``(schedule, seed)`` pair replays the same
decision at each HIT INDEX. ``nth``/``every`` triggers therefore fire
at exactly the same crossings run to run; for ``p``-based triggers on
points crossed by multiple threads, WHICH crossing maps to which hit
index depends on OS scheduling, so the fire pattern is
seed-deterministic per index but not per wall-clock crossing — pinned
regression schedules use ``nth``/``every``
(docs/ROBUSTNESS.md "Reproducing a chaos failure from its seed").
Spec keys per point::

    {"kind": "error"}                      # raise FaultError every hit
    {"kind": "error", "nth": 3}            # raise on hit #3 exactly
    {"kind": "error", "every": 5}          # raise on every 5th hit
    {"kind": "error", "p": 0.1}            # seeded Bernoulli per hit
    {"kind": "latency", "sleep_s": 0.01, "p": 0.5}   # seeded stalls
    {"kind": "kill", "nth": 4}             # FaultThreadKill on hit #4
    {..., "max_fires": 2}                  # cap total fires (kill: 1)

``kind="kill"`` raises :class:`FaultThreadKill`, deliberately a
``BaseException`` subclass: the eval workers confine ``Exception``
(ack/nack + keep the loop alive), so an injected kill sails past that
confinement and the thread dies exactly like a crashed one —
``finally`` blocks still unwind (rendezvous slots are released, pool
bookkeeping runs), but nothing acks, nacks, or responds. Recovery
must come from the TIMEOUT machinery (broker nack deadlines, plan
futures, the group-commit abnormal unwind), which is the point.

Per-point hit/fire counters are served by :func:`stats` and exported
as ``nomad_tpu_fault_hits_total{point=...}`` /
``nomad_tpu_fault_fires_total{point=...,kind=...}`` plus the
``nomad_tpu_fault_armed`` gauge (telemetry/exporter.py). The wired
point catalog lives in docs/ROBUSTNESS.md.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "FaultError", "FaultThreadKill", "fault", "arm", "disarm", "armed",
    "reset", "stats", "fires", "fire_log",
]


class FaultError(RuntimeError):
    """Raised by an armed fault point with ``kind="error"``. A
    RuntimeError on purpose: every seam's existing error handling
    (worker nack, plan-future respond, replicator retry) must treat it
    exactly like the real failure it stands in for."""

    def __init__(self, point: str) -> None:
        super().__init__(f"injected fault at {point!r}")
        self.point = point


class FaultThreadKill(BaseException):
    """Kills the current thread (``kind="kill"``). A BaseException so
    ``except Exception`` confinement does NOT catch it — the thread
    dies as a crashed one would, with only ``finally`` unwinding."""

    def __init__(self, point: str) -> None:
        super().__init__(f"injected thread kill at {point!r}")
        self.point = point


#: the one disarmed-path cost: a module-global boolean read
_ARMED = False

_lock = threading.Lock()
_seed = 0
#: point name -> _Point (created at arm() for scheduled points, and
#: lazily on first hit for wired-but-unscheduled ones, so stats()
#: reports hit counts for every point the run actually crossed)
_points: Dict[str, "_Point"] = {}
#: bounded log of FIRED faults (ISSUE 15: the failover timeline
#: merges firings with the consensus event stream). Only appended
#: while armed — the disarmed path stays one boolean check.
_fire_log: deque = deque(maxlen=1024)


class _Point:
    __slots__ = ("name", "spec", "kind", "nth", "every", "p", "sleep_s",
                 "max_fires", "rng", "hits", "fires")

    def __init__(self, name: str, spec: Optional[Dict], seed: int) -> None:
        import random

        self.name = name
        self.spec = spec
        self.hits = 0
        self.fires = 0
        if spec is None:
            self.kind = None
            return
        self.kind = spec.get("kind", "error")
        if self.kind not in ("error", "latency", "kill"):
            raise ValueError(
                f"fault point {name!r}: unknown kind {self.kind!r}")
        self.nth = spec.get("nth")
        self.every = spec.get("every")
        self.p = spec.get("p")
        self.sleep_s = float(spec.get("sleep_s", 0.0))
        default_cap = 1 if (self.kind == "kill" or self.nth) else None
        self.max_fires = spec.get("max_fires", default_cap)
        # deterministic per-point stream: decisions depend only on
        # (schedule seed, point name, hit index) — re-arming the same
        # pair replays the same decisions hit for hit
        self.rng = random.Random(zlib.crc32(name.encode()) ^ seed)

    def decide(self) -> Optional[str]:
        """Called under _lock at each hit; returns the action to take
        ("error"/"latency"/"kill") or None."""
        self.hits += 1
        if self.kind is None:
            return None
        if self.max_fires is not None and self.fires >= self.max_fires:
            return None
        if self.nth is not None:
            if self.hits != self.nth:
                return None
        elif self.every is not None:
            if self.hits % self.every != 0:
                return None
        if self.p is not None and self.rng.random() >= self.p:
            return None
        self.fires += 1
        return self.kind


def arm(schedule: Dict[str, Dict], seed: int = 0) -> None:
    """Arm the plane with a (schedule, seed) pair. Replaces any prior
    schedule; counters reset so a run's stats are its own."""
    global _ARMED, _seed
    with _lock:
        _seed = seed
        _points.clear()
        _fire_log.clear()
        for name, spec in schedule.items():
            _points[name] = _Point(name, dict(spec), seed)
        _ARMED = True


def disarm() -> None:
    """Back to the no-op path. Counters survive for post-run stats();
    reset() clears them."""
    global _ARMED
    _ARMED = False


def armed() -> bool:
    return _ARMED


def reset() -> None:
    global _ARMED
    with _lock:
        _ARMED = False
        _points.clear()
        _fire_log.clear()


def stats() -> Dict[str, Dict]:
    """{point: {"hits": n, "fires": n, "kind": k}} for every point the
    run scheduled or crossed."""
    with _lock:
        return {
            name: {"hits": p.hits, "fires": p.fires, "kind": p.kind}
            for name, p in sorted(_points.items())
        }


def fires() -> int:
    with _lock:
        return sum(p.fires for p in _points.values())


def fire_log() -> List[Dict]:
    """Every fired fault this arming window, oldest first:
    ``{"t": monotonic, "point": name, "kind": action}`` — the failover
    timeline's fault feed (telemetry/timeline.py). Cleared by arm()
    and reset()."""
    with _lock:
        return [dict(f) for f in _fire_log]


def fault(name: str) -> None:
    """A named fault point. Disarmed: one boolean check. Armed: bump
    the point's counters and execute its scheduled action — raise
    :class:`FaultError`, sleep, or raise :class:`FaultThreadKill`.

    Call-site discipline: place the point OUTSIDE any held lock where
    possible (failures land at the seam boundary, and latency
    injection must not stretch critical sections the R2 rule keeps
    clean)."""
    if not _ARMED:
        return
    with _lock:
        point = _points.get(name)
        if point is None:
            point = _points[name] = _Point(name, None, _seed)
        action = point.decide()
        sleep_s = point.sleep_s if action == "latency" else 0.0
        if action is not None:
            _fire_log.append({"t": time.monotonic(), "point": name,
                              "kind": action})
    if action is None:
        return
    if action == "error":
        raise FaultError(name)
    if action == "kill":
        raise FaultThreadKill(name)
    # latency: sleep OUTSIDE the registry lock
    if sleep_s > 0.0:
        time.sleep(sleep_s)
