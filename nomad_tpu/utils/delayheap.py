"""Time-ordered heap of waiting items.

Reference behavior: lib/delayheap/delay_heap.go -- used by the eval
broker for WaitUntil evaluations (nomad/eval_broker.go:758-809) and by
the drainer for deadlines. Items are keyed by id so they can be removed
or have their wait time updated in place.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Any, List, Optional, Tuple


class DelayHeap:
    def __init__(self) -> None:
        self._heap: List[Tuple[float, int, str]] = []
        self._entries: dict = {}          # id -> (wait_until, seq, item)
        self._seq = itertools.count()

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, item_id: str) -> bool:
        return item_id in self._entries

    def push(self, item_id: str, wait_until: float, item: Any) -> None:
        if item_id in self._entries:
            self.remove(item_id)
        seq = next(self._seq)
        self._entries[item_id] = (wait_until, seq, item)
        heapq.heappush(self._heap, (wait_until, seq, item_id))

    def remove(self, item_id: str) -> bool:
        # lazy deletion: entry dropped from the map; stale heap nodes are
        # skipped on pop (delay_heap.go uses container/heap Fix/Remove;
        # lazy deletion is equivalent and simpler)
        return self._entries.pop(item_id, None) is not None

    def update(self, item_id: str, wait_until: float) -> bool:
        entry = self._entries.get(item_id)
        if entry is None:
            return False
        self.push(item_id, wait_until, entry[2])
        return True

    def peek(self) -> Optional[Tuple[str, float, Any]]:
        """Earliest (id, wait_until, item) or None."""
        while self._heap:
            wait_until, seq, item_id = self._heap[0]
            entry = self._entries.get(item_id)
            if entry is None or entry[1] != seq:
                heapq.heappop(self._heap)   # stale
                continue
            return item_id, wait_until, entry[2]
        return None

    def pop_due(self, now: float) -> List[Tuple[str, Any]]:
        """Pop every item whose wait time has passed."""
        due: List[Tuple[str, Any]] = []
        while True:
            head = self.peek()
            if head is None or head[1] > now:
                break
            item_id, _, item = head
            heapq.heappop(self._heap)
            del self._entries[item_id]
            due.append((item_id, item))
        return due
