"""Bounded top-K score heap.

Reference behavior: lib/kheap/score_heap.go -- keeps the K highest-score
items; used for the per-eval AllocMetric's top node scores
(nomad/structs/structs.go AllocMetric.TopScores).
"""

from __future__ import annotations

import heapq
import itertools
from typing import Any, List, Tuple


class ScoreHeap:
    def __init__(self, capacity: int = 5) -> None:
        self.capacity = capacity
        self._heap: List[Tuple[float, int, Any]] = []   # min-heap of scores
        self._seq = itertools.count()

    def __len__(self) -> int:
        return len(self._heap)

    def push(self, score: float, item: Any) -> None:
        entry = (score, next(self._seq), item)
        if len(self._heap) < self.capacity:
            heapq.heappush(self._heap, entry)
        elif score > self._heap[0][0]:
            heapq.heapreplace(self._heap, entry)

    def items(self) -> List[Tuple[float, Any]]:
        """Descending by score."""
        return [(s, it) for s, _, it in sorted(self._heap, key=lambda e: -e[0])]
