"""Operator snapshot archive save/restore.

Reference behavior: helper/snapshot — a tar.gz archive carrying raft
metadata + the FSM state, written by /v1/operator/snapshot and restored
via the same endpoint. Here: gzip'd tar with `meta.json` (index, term,
timestamp, sha256) and `state.bin` (StateStore.to_snapshot_bytes).
"""

from __future__ import annotations

import gzip
import hashlib
import io
import json
import tarfile
import time


def archive_snapshot(server) -> bytes:
    """Build the archive from the server's current state."""
    state_bytes = server.state.to_snapshot_bytes()
    meta = {
        "Index": server.state.latest_index(),
        "Term": getattr(server.raft, "current_term", 0) if server.raft else 0,
        "Timestamp": time.time(),
        "SHA256": hashlib.sha256(state_bytes).hexdigest(),
        "Version": 1,
    }
    buf = io.BytesIO()
    with gzip.GzipFile(fileobj=buf, mode="wb") as gz:
        with tarfile.open(fileobj=gz, mode="w") as tar:
            meta_bytes = json.dumps(meta).encode()
            mi = tarfile.TarInfo("meta.json")
            mi.size = len(meta_bytes)
            tar.addfile(mi, io.BytesIO(meta_bytes))
            si = tarfile.TarInfo("state.bin")
            si.size = len(state_bytes)
            tar.addfile(si, io.BytesIO(state_bytes))
    return buf.getvalue()


def read_snapshot(data: bytes) -> tuple:
    """-> (meta dict, state bytes); verifies the digest."""
    buf = io.BytesIO(data)
    with gzip.GzipFile(fileobj=buf, mode="rb") as gz:
        with tarfile.open(fileobj=gz, mode="r") as tar:
            meta = json.loads(tar.extractfile("meta.json").read())
            state_bytes = tar.extractfile("state.bin").read()
    digest = hashlib.sha256(state_bytes).hexdigest()
    if digest != meta.get("SHA256"):
        raise ValueError("snapshot digest mismatch (corrupt archive)")
    return meta, state_bytes


def restore_snapshot(server, data: bytes) -> None:
    """Replace server state from an archive (operator restore)."""
    _meta, state_bytes = read_snapshot(data)
    server.state.restore_from_bytes(state_bytes)
