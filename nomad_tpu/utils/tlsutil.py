"""TLS material generation and socket wrapping.

Reference behavior: helper/tlsutil/config.go builds the agent's mTLS
configs (CA-verified HTTPS + RPC, optional verify_https_client), and
the operator generates cluster certs with a CA. Here: a minimal CA +
cert issuer over the `cryptography` package, plus ssl.SSLContext
builders for the HTTP agent (server side) and the SDK (client side).
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def generate_ca(common_name: str = "nomad-tpu CA",
                days: int = 1825) -> Tuple[bytes, bytes]:
    """Self-signed CA; returns (cert_pem, key_pem)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_now() - datetime.timedelta(minutes=5))
        .not_valid_after(_now() + datetime.timedelta(days=days))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .add_extension(
            x509.KeyUsage(digital_signature=True, key_cert_sign=True,
                          crl_sign=True, content_commitment=False,
                          key_encipherment=False, data_encipherment=False,
                          key_agreement=False, encipher_only=False,
                          decipher_only=False),
            critical=True)
        .sign(key, hashes.SHA256())
    )
    return (
        cert.public_bytes(serialization.Encoding.PEM),
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        ),
    )


def generate_cert(ca_cert_pem: bytes, ca_key_pem: bytes,
                  common_name: str,
                  san_dns: Optional[List[str]] = None,
                  san_ips: Optional[List[str]] = None,
                  days: int = 365,
                  server: bool = True,
                  client: bool = True) -> Tuple[bytes, bytes]:
    """CA-signed leaf cert; returns (cert_pem, key_pem).

    The reference's convention: server certs carry the
    `server.<region>.nomad` name the RPC layer verifies; pass it in
    san_dns the same way. localhost/127.0.0.1 are always included so
    dev agents verify.
    """
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

    ca_cert = x509.load_pem_x509_certificate(ca_cert_pem)
    ca_key = serialization.load_pem_private_key(ca_key_pem, password=None)
    key = ec.generate_private_key(ec.SECP256R1())
    sans: List[x509.GeneralName] = [x509.DNSName("localhost")]
    for d in (san_dns or []):
        sans.append(x509.DNSName(d))
    for ip in ["127.0.0.1"] + list(san_ips or []):
        sans.append(x509.IPAddress(ipaddress.ip_address(ip)))
    ekus = []
    if server:
        ekus.append(ExtendedKeyUsageOID.SERVER_AUTH)
    if client:
        ekus.append(ExtendedKeyUsageOID.CLIENT_AUTH)
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_now() - datetime.timedelta(minutes=5))
        .not_valid_after(_now() + datetime.timedelta(days=days))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .add_extension(x509.ExtendedKeyUsage(ekus), critical=False)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )
    return (
        cert.public_bytes(serialization.Encoding.PEM),
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        ),
    )


@dataclass
class TLSConfig:
    """Agent TLS block (config tls{} stanza; tlsutil/config.go)."""

    enabled: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    #: require client certs on the HTTPS API (mTLS)
    verify_https_client: bool = False

    def write_bundle(self, directory: str, ca: Tuple[bytes, bytes],
                     cert: Tuple[bytes, bytes]) -> "TLSConfig":
        """Persist generated material and point this config at it."""
        os.makedirs(directory, exist_ok=True)
        paths = {}
        for name, data in (("ca.pem", ca[0]), ("ca-key.pem", ca[1]),
                           ("cert.pem", cert[0]), ("key.pem", cert[1])):
            p = os.path.join(directory, name)
            with open(p, "wb") as f:
                f.write(data)
            os.chmod(p, 0o600)
            paths[name] = p
        self.ca_file = paths["ca.pem"]
        self.cert_file = paths["cert.pem"]
        self.key_file = paths["key.pem"]
        self.enabled = True
        return self


def server_context(cfg: TLSConfig) -> ssl.SSLContext:
    """SSLContext for the HTTP agent's listener."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_cert_chain(cfg.cert_file, cfg.key_file)
    if cfg.verify_https_client:
        ctx.load_verify_locations(cfg.ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_context(ca_file: str, cert_file: str = "",
                   key_file: str = "") -> ssl.SSLContext:
    """SSLContext for SDK/CLI connections (NOMAD_CACERT /
    NOMAD_CLIENT_CERT / NOMAD_CLIENT_KEY)."""
    ctx = ssl.create_default_context(cafile=ca_file or None)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    if cert_file and key_file:
        ctx.load_cert_chain(cert_file, key_file)
    return ctx
