"""Minimal 5-field cron parser for periodic jobs.

Reference behavior: nomad/periodic.go uses gorhill/cronexpr; periodic
jobs declare ``cron`` specs (structs.go PeriodicConfig). Supported
syntax: ``* a,b a-b */n a-b/n`` per field (minute, hour, day-of-month,
month, day-of-week), plus the shorthands ``@hourly``/``@daily`` and the
non-standard ``@every <seconds>s`` used widely in tests.
"""

from __future__ import annotations

import calendar
import time
from typing import List, Optional, Set

_FIELD_RANGES = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]


def _parse_field(spec: str, lo: int, hi: int) -> Set[int]:
    values: Set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start, end = int(a), int(b)
        else:
            start = end = int(part)
        for v in range(start, end + 1, step):
            if lo <= v <= hi:
                values.add(v)
    return values


class CronExpr:
    def __init__(self, spec: str) -> None:
        self.spec = spec.strip()
        self.every_s: Optional[float] = None
        if self.spec.startswith("@every"):
            # "@every 5s" / "@every 2m"
            arg = self.spec.split(None, 1)[1].strip()
            mult = 1.0
            if arg.endswith("ms"):
                mult, arg = 0.001, arg[:-2]
            elif arg.endswith("s"):
                arg = arg[:-1]
            elif arg.endswith("m"):
                mult, arg = 60.0, arg[:-1]
            elif arg.endswith("h"):
                mult, arg = 3600.0, arg[:-1]
            self.every_s = float(arg) * mult
            return
        aliases = {
            "@hourly": "0 * * * *",
            "@daily": "0 0 * * *",
            "@weekly": "0 0 * * 0",
            "@monthly": "0 0 1 * *",
        }
        spec = aliases.get(self.spec, self.spec)
        fields = spec.split()
        if len(fields) != 5:
            raise ValueError(f"cron spec must have 5 fields: {spec!r}")
        self.minutes, self.hours, self.doms, self.months, self.dows = (
            _parse_field(f, lo, hi)
            for f, (lo, hi) in zip(fields, _FIELD_RANGES)
        )

    def next_after(self, now: Optional[float] = None) -> float:
        """Epoch seconds of the next firing strictly after `now`."""
        now = time.time() if now is None else now
        if self.every_s is not None:
            return now + self.every_s
        t = time.localtime(now + 60 - (now % 60))   # next whole minute
        # bounded scan: four years of minutes is plenty
        for _ in range(366 * 4 * 24 * 60):
            if (
                t.tm_min in self.minutes
                and t.tm_hour in self.hours
                and t.tm_mday in self.doms
                and t.tm_mon in self.months
                and (t.tm_wday + 1) % 7 in self.dows
            ):
                return time.mktime(t)
            t = time.localtime(time.mktime(t) + 60)
        raise ValueError(f"cron spec {self.spec!r} never fires")
