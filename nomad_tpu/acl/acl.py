"""The compiled ACL object.

Reference behavior: acl/acl.go:43 — an ACL is compiled from one or more
parsed policies into per-namespace capability sets (deny wins), plus
coarse node/agent/operator dispositions (max of read<write, deny wins).
Wildcard namespace rules apply by glob match with longest-prefix
priority (simplified here to fnmatch + most-specific-pattern-wins).
"""

from __future__ import annotations

import fnmatch
from typing import Dict, Iterable, List, Optional

from nomad_tpu.acl.policy import NS_DENY, ParsedPolicy


def _merge_disposition(cur: str, new: str) -> str:
    order = {"": 0, "read": 1, "write": 2, "deny": 3}
    return new if order.get(new, 0) > order.get(cur, 0) else cur


class ACL:
    def __init__(self, management: bool = False) -> None:
        self.management = management
        # exact-or-glob namespace pattern -> capability set
        self._ns_caps: Dict[str, set] = {}
        self._node = ""
        self._agent = ""
        self._operator = ""
        self._quota = ""
        self._plugin = ""

    @classmethod
    def compile(cls, policies: Iterable[ParsedPolicy]) -> "ACL":
        acl = cls()
        for p in policies:
            for rule in p.namespaces:
                caps = acl._ns_caps.setdefault(rule.name, set())
                caps.update(rule.capabilities)
            acl._node = _merge_disposition(acl._node, p.node)
            acl._agent = _merge_disposition(acl._agent, p.agent)
            acl._operator = _merge_disposition(acl._operator, p.operator)
            acl._quota = _merge_disposition(acl._quota, p.quota)
            acl._plugin = _merge_disposition(acl._plugin, p.plugin)
        return acl

    # -- namespace capabilities (acl.go AllowNamespaceOperation) ---------

    def _caps_for(self, namespace: str) -> Optional[set]:
        if namespace in self._ns_caps:
            return self._ns_caps[namespace]
        # glob rules: most-specific (longest pattern) match wins
        best: Optional[str] = None
        for pattern in self._ns_caps:
            if ("*" in pattern or "?" in pattern) and fnmatch.fnmatch(
                namespace, pattern
            ):
                if best is None or len(pattern) > len(best):
                    best = pattern
        return self._ns_caps.get(best) if best is not None else None

    def allow_ns_op(self, namespace: str, capability: str) -> bool:
        if self.management:
            return True
        caps = self._caps_for(namespace)
        if caps is None:
            return False
        if NS_DENY in caps:
            return False
        return capability in caps

    def allow_namespace(self, namespace: str) -> bool:
        if self.management:
            return True
        caps = self._caps_for(namespace)
        return bool(caps) and NS_DENY not in caps

    def allow_any_ns_op(self, capability: str) -> bool:
        """Does ANY namespace rule grant this capability? (the
        subscribe-time gate for cross-namespace streams: a token with
        no read grant anywhere has no business holding one open)"""
        if self.management:
            return True
        return any(capability in caps and NS_DENY not in caps
                   for caps in self._ns_caps.values())

    # -- coarse scopes ---------------------------------------------------

    def _allow(self, disposition: str, write: bool) -> bool:
        if self.management:
            return True
        if disposition == "deny":
            return False
        if write:
            return disposition == "write"
        return disposition in ("read", "write")

    def allow_node_read(self) -> bool:
        return self._allow(self._node, write=False)

    def allow_node_write(self) -> bool:
        return self._allow(self._node, write=True)

    def allow_agent_read(self) -> bool:
        return self._allow(self._agent, write=False)

    def allow_agent_write(self) -> bool:
        return self._allow(self._agent, write=True)

    def allow_operator_read(self) -> bool:
        return self._allow(self._operator, write=False)

    def allow_operator_write(self) -> bool:
        return self._allow(self._operator, write=True)

    def allow_quota_read(self) -> bool:
        return self._allow(self._quota, write=False)

    def allow_plugin_read(self) -> bool:
        return self._allow(self._plugin, write=False)

    def is_management(self) -> bool:
        return self.management


MANAGEMENT_ACL = ACL(management=True)
ANONYMOUS_ACL = ACL()
