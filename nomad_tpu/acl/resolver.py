"""Token -> compiled ACL resolution.

Reference behavior: nomad/acl.go ResolveToken — look up the secret in
the acl_token table, compile the token's policies (cached by policy
set), management tokens short-circuit, blank tokens resolve to the
anonymous policy. Bootstrap (acl_endpoint.go Bootstrap) mints the
initial management token exactly once.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from nomad_tpu.acl.acl import ACL, ANONYMOUS_ACL, MANAGEMENT_ACL
from nomad_tpu.acl.policy import ACLToken


class ACLDeniedError(Exception):
    pass


class TokenResolver:
    def __init__(self, server) -> None:
        self.server = server
        # policy-name tuple -> (acl_policy table index at compile, ACL);
        # an entry is valid only while the table index matches, so a
        # compile racing a policy edit can never poison the cache
        self._cache: Dict[Tuple[str, ...], Tuple[int, ACL]] = {}
        self._lock = threading.Lock()
        self._bootstrapped = False

    def bootstrap(self) -> dict:
        """Mint the initial management token (acl_endpoint.go Bootstrap)."""
        from nomad_tpu.server import fsm as fsm_msgs

        with self._lock:
            if self._bootstrapped or self.server.state.acl_tokens():
                raise ValueError("ACL bootstrap already done")
            self._bootstrapped = True
        token = ACLToken.create(name="Bootstrap Token", type="management",
                                global_=True)
        index = self.server.raft_apply(
            fsm_msgs.ACL_TOKEN_UPSERT, {"tokens": [token]}
        )
        return {
            "AccessorID": token.accessor_id,
            "SecretID": token.secret_id,
            "Name": token.name,
            "Type": token.type,
            "Global": token.global_,
            "CreateIndex": index,
        }

    def resolve(self, secret: str) -> ACL:
        if not secret:
            return self._anonymous()
        token = self.server.state.acl_token_by_secret(secret)
        if token is None:
            raise PermissionError("ACL token not found")
        return self.resolve_token(token)

    def resolve_token(self, token: ACLToken) -> ACL:
        if token.is_management():
            return MANAGEMENT_ACL
        key = tuple(sorted(token.policies))
        # a policy edit must invalidate compiled ACLs (the reference
        # recompiles on ACL-table changes); the acl_policy table index
        # read BEFORE compiling tags the entry, so a policy edited
        # mid-compile yields an entry that is already stale on arrival
        policy_index = self.server.state.table_index(["acl_policy"])
        with self._lock:
            entry = self._cache.get(key)
            if entry is not None and entry[0] == policy_index:
                return entry[1]
        parsed = []
        for name in token.policies:
            p = self.server.state.acl_policy_by_name(name)
            if p is not None:
                parsed.append(p.parsed())
        acl = ACL.compile(parsed)
        with self._lock:
            cur = self._cache.get(key)
            if cur is None or cur[0] <= policy_index:
                self._cache[key] = (policy_index, acl)
        return acl

    def invalidate(self) -> None:
        with self._lock:
            self._cache.clear()

    def _anonymous(self) -> ACL:
        anon = self.server.state.acl_policy_by_name("anonymous")
        if anon is None:
            return ANONYMOUS_ACL
        return ACL.compile([anon.parsed()])
