"""ACL system: policies, tokens, compiled capability sets.

Reference: acl/acl.go (ACL object :43), acl/policy.go (HCL policy
parsing), nomad/acl.go (ResolveToken), enforced per-endpoint.
"""

from nomad_tpu.acl.acl import ACL, ANONYMOUS_ACL, MANAGEMENT_ACL  # noqa: F401
from nomad_tpu.acl.policy import ACLPolicy, ACLToken, parse_policy  # noqa: F401
from nomad_tpu.acl.resolver import TokenResolver  # noqa: F401
