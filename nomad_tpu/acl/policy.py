"""ACL policy + token models and policy-text parsing.

Reference behavior: acl/policy.go — policies are HCL documents with
`namespace "name" { policy = "read" capabilities = [...] }`, plus
node/agent/operator/quota/plugin/host_volume blocks; dispositions
expand to capability sets (expandNamespacePolicy). Tokens
(structs.go ACLToken) are client (policy-bound) or management.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Dict, List

# namespace capabilities (acl/policy.go:26-48)
NS_DENY = "deny"
NS_LIST_JOBS = "list-jobs"
NS_READ_JOB = "read-job"
NS_SUBMIT_JOB = "submit-job"
NS_DISPATCH_JOB = "dispatch-job"
NS_READ_LOGS = "read-logs"
NS_READ_FS = "read-fs"
NS_ALLOC_EXEC = "alloc-exec"
NS_ALLOC_LIFECYCLE = "alloc-lifecycle"
NS_SCALE_JOB = "scale-job"
NS_SENTINEL_OVERRIDE = "sentinel-override"
NS_CSI_REGISTER_PLUGIN = "csi-register-plugin"
NS_CSI_WRITE_VOLUME = "csi-write-volume"
NS_CSI_READ_VOLUME = "csi-read-volume"
NS_CSI_LIST_VOLUME = "csi-list-volume"
NS_CSI_MOUNT_VOLUME = "csi-mount-volume"

# disposition -> capability expansion (acl/policy.go expandNamespacePolicy)
_READ_CAPS = [
    NS_LIST_JOBS, NS_READ_JOB, NS_CSI_LIST_VOLUME, NS_CSI_READ_VOLUME,
    NS_READ_LOGS, NS_READ_FS,
]
_WRITE_CAPS = _READ_CAPS + [
    NS_SUBMIT_JOB, NS_DISPATCH_JOB, NS_ALLOC_EXEC, NS_ALLOC_LIFECYCLE,
    NS_CSI_WRITE_VOLUME, NS_CSI_MOUNT_VOLUME, NS_SCALE_JOB,
]


def expand_namespace_policy(disposition: str) -> List[str]:
    if disposition == "deny":
        return [NS_DENY]
    if disposition == "read":
        return list(_READ_CAPS)
    if disposition == "write":
        return list(_WRITE_CAPS)
    if disposition == "scale":
        return [NS_LIST_JOBS, NS_READ_JOB, NS_SCALE_JOB]
    raise ValueError(f"invalid namespace policy '{disposition}'")


@dataclass
class NamespaceRule:
    name: str = ""
    policy: str = ""
    capabilities: List[str] = field(default_factory=list)


@dataclass
class ParsedPolicy:
    namespaces: List[NamespaceRule] = field(default_factory=list)
    node: str = ""        # read | write | deny
    agent: str = ""
    operator: str = ""
    quota: str = ""
    plugin: str = ""
    host_volumes: List[NamespaceRule] = field(default_factory=list)


def parse_policy(rules: str) -> ParsedPolicy:
    """Parse HCL policy text (acl/policy.go Parse)."""
    from nomad_tpu.jobspec.hcl import parse

    body = parse(rules)
    p = ParsedPolicy()
    for labels, nb in body.get_blocks("namespace"):
        rule = NamespaceRule(
            name=labels[0] if labels else "default",
            policy=str(nb.attrs.get("policy", "")),
            capabilities=[str(c) for c in nb.attrs.get("capabilities", [])],
        )
        if rule.policy:
            rule.capabilities = sorted(
                set(rule.capabilities) | set(expand_namespace_policy(rule.policy))
            )
        p.namespaces.append(rule)
    for labels, hb in body.get_blocks("host_volume"):
        p.host_volumes.append(NamespaceRule(
            name=labels[0] if labels else "*",
            policy=str(hb.attrs.get("policy", "")),
            capabilities=[str(c) for c in hb.attrs.get("capabilities", [])],
        ))
    for scope in ("node", "agent", "operator", "quota", "plugin"):
        blk = body.first_block(scope)
        if blk is not None:
            setattr(p, scope if scope != "host_volumes" else scope,
                    str(blk[1].attrs.get("policy", "")))
    return p


@dataclass
class ACLPolicy:
    """Stored policy (structs.go ACLPolicy)."""

    name: str = ""
    description: str = ""
    rules: str = ""
    create_index: int = 0
    modify_index: int = 0

    def validate(self) -> None:
        import re

        if not re.fullmatch(r"[a-zA-Z0-9-]{1,128}", self.name):
            raise ValueError(f"invalid policy name '{self.name}'")
        parse_policy(self.rules)  # raises on bad rules

    def parsed(self) -> ParsedPolicy:
        return parse_policy(self.rules)


@dataclass
class ACLToken:
    """Stored token (structs.go ACLToken)."""

    accessor_id: str = ""
    secret_id: str = ""
    name: str = ""
    type: str = "client"      # client | management
    policies: List[str] = field(default_factory=list)
    global_: bool = False
    create_time_ns: int = 0
    create_index: int = 0
    modify_index: int = 0

    @classmethod
    def create(cls, name: str = "", type: str = "client",
               policies: List[str] = (), global_: bool = False) -> "ACLToken":
        import time

        if type not in ("client", "management"):
            raise ValueError(f"invalid token type '{type}'")
        if type == "client" and not policies:
            raise ValueError("client tokens must have at least one policy")
        if type == "management" and policies:
            raise ValueError("management tokens cannot carry policies")
        return cls(
            accessor_id=str(uuid.uuid4()),
            secret_id=str(uuid.uuid4()),
            name=name,
            type=type,
            policies=list(policies),
            global_=global_,
            create_time_ns=int(time.time() * 1e9),
        )

    def is_management(self) -> bool:
        return self.type == "management"
