"""Pallas TPU kernel for the batched placement hot path (alternative
backend).

One program per evaluation; all node planes live in VMEM for the whole
placement loop (`fori_loop` over the K steps, masked global argmax and
one-hot deduction as pure VPU work), so HBM sees each shared plane
once per launch.

**Measured status (round 5, REAL TPU v5e chip, 10k nodes, B=512,
best-of-3 materialized timing):** `pallas_topk_place_batch` (full-
width pass + approx_max_k in XLA, the K-step candidate deduction scan
as one VMEM-resident pallas program, 256-row batch tiles) runs at
**98.9k evals/s vs the all-XLA candidate kernel's 119.8k — 82% —
at exact score parity** (same 170,607 score sum / 204,800 placements
on the same ask stream). Two findings from getting it on-chip:
(1) a loop-carried bool vector trips a Mosaic layout-inference bug
(scf.yield on vector<8x128xi1>); the validity flag is carried as f32.
(2) per-program grid overhead dominates small batch tiles — tb=8
measured ~10% slower than tb=256.

The remaining gap is NOT the scan (it is a small fraction of launch
time): it is the full-width scoring sweep + top-k, where XLA's fused
sweep and hardware-tuned approx_max_k are already near the HBM
roofline. Fusing them into this program would mean re-implementing
approx_max_k's bucketed selection in VPU ops to save one [B,N]
intermediate round-trip — measured headroom under 20%, so the
scheduler and bench stay on the XLA path via per-machine calibration
(bench.py `_calibrate_and_size` times both and picks the winner; on
this chip it correctly picks XLA). The kernel remains the pallas-side
evolution seam, now proven on hardware end to end.

Feature coverage is the **lean binpack variant** (the common service/
batch ask: cpu/mem/disk feasibility + binpack/spread fit + job
anti-affinity + penalty + node-affinity planes, no ports/devices/
cores/bandwidth/spread-stanza/distinct/preferred planes). The host
falls back to the XLA kernel for asks outside this envelope — the
same static-specialization seam `infer_features` already provides.

Semantics parity (same pointers as ops/kernel.py):
- feasibility: funcs.go:166 AllocsFit dimensions cpu/mem/disk
- score: funcs.go:259 ScoreFitBinPack / :286 ScoreFitSpread, /18
  (rank.go:547), anti-affinity rank.go:588, penalty rank.go:655,
  affinity rank.go:730, appended-plane normalization rank.go:764
- per-step deduction between placements of one task group
  (generic_sched.go computePlacements sequential accounting)
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1.0e30
LANES = 128
K_SLOTS = 128          # output columns per eval (one aligned lane row)


class PallasOut(NamedTuple):
    chosen: jnp.ndarray      # i32[B, K]
    scores: jnp.ndarray      # f32[B, K]
    found: jnp.ndarray       # bool[B, K]


def _place_kernel(scal_f, scal_i,
                  cap_cpu, cap_mem, cap_disk,
                  used_cpu, used_mem, used_disk,
                  base, jobtg, penalty, aff,
                  chosen_ref, score_ref, found_ref,
                  *, k_steps: int, r: int):
    b = pl.program_id(0)
    a_cpu = scal_f[b, 0]
    a_mem = scal_f[b, 1]
    a_disk = scal_f[b, 2]
    algo_spread = scal_f[b, 3]
    n_steps = scal_i[b, 0]
    desired = scal_i[b, 1]

    cc = cap_cpu[:]
    cm = cap_mem[:]
    cd = cap_disk[:]
    base_m = base[:] > 0.0
    pen = penalty[:] > 0.0
    affs = aff[:]

    rows = jax.lax.broadcasted_iota(jnp.int32, (r, LANES), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (r, LANES), 1)
    flat = rows * LANES + cols
    kcol = jax.lax.broadcasted_iota(jnp.int32, (1, K_SLOTS), 1)
    # outputs are one full (B, K) block revisited by every program; each
    # program row-masks its own writes (TPU blocks need >=8 sublanes, so
    # a (1, K) per-program block is not lowerable)
    out_rows = jax.lax.broadcasted_iota(jnp.int32, chosen_ref.shape, 0)
    mine = out_rows == b

    denom = jnp.maximum(desired.astype(jnp.float32), 1.0)
    aff_on = affs != 0.0
    pen_f = jnp.where(pen, -1.0, 0.0)
    extra_planes = pen.astype(jnp.float32) + aff_on.astype(jnp.float32)
    aff_sum = jnp.where(aff_on, affs, 0.0) + pen_f

    def body(i, carry):
        uc, um, ud, utg, ch, sc, fo = carry
        feas = (
            base_m
            & ((cc - uc) >= a_cpu)
            & ((cm - um) >= a_mem)
            & ((cd - ud) >= a_disk)
        )
        # computeFreePercentage with zero-capacity guard (funcs.go:235)
        fc = jnp.where(cc > 0, 1.0 - (uc + a_cpu) / cc, 0.0)
        fm = jnp.where(cm > 0, 1.0 - (um + a_mem) / cm, 0.0)
        total = jnp.power(10.0, fc) + jnp.power(10.0, fm)
        binpack = jnp.clip(20.0 - total, 0.0, 18.0)
        spreadfit = jnp.clip(total - 2.0, 0.0, 18.0)
        fit = jnp.where(algo_spread > 0, spreadfit, binpack) / 18.0

        coll = utg.astype(jnp.float32)
        anti_on = coll > 0
        ssum = fit + jnp.where(anti_on, -(coll + 1.0) / denom, 0.0) + aff_sum
        nplanes = 1.0 + anti_on.astype(jnp.float32) + extra_planes
        final = ssum / nplanes

        active = i < n_steps
        masked = jnp.where(feas & active, final, NEG_INF)
        amax = jnp.max(masked)
        # first-max index (jnp.argmax parity): min flat id at the max
        idx = jnp.min(jnp.where(masked == amax, flat, jnp.int32(2**30)))
        fnd = amax > NEG_INF / 2

        one = (flat == idx) & fnd
        onef = one.astype(jnp.float32)
        uc = uc + onef * a_cpu
        um = um + onef * a_mem
        ud = ud + onef * a_disk
        utg = utg + one.astype(jnp.int32)

        at_i = kcol == i
        ch = jnp.where(at_i, jnp.where(fnd, idx, -1), ch)
        sc = jnp.where(at_i, jnp.where(fnd, amax, 0.0), sc)
        fo = jnp.where(at_i, fnd.astype(jnp.int32), fo)
        return uc, um, ud, utg, ch, sc, fo

    init = (
        used_cpu[:], used_mem[:], used_disk[:],
        jobtg[:].astype(jnp.int32),
        jnp.full((1, K_SLOTS), -1, jnp.int32),
        jnp.zeros((1, K_SLOTS), jnp.float32),
        jnp.zeros((1, K_SLOTS), jnp.int32),
    )
    _, _, _, _, ch, sc, fo = jax.lax.fori_loop(0, k_steps, body, init)
    chosen_ref[:] = jnp.where(mine, ch, chosen_ref[:])
    score_ref[:] = jnp.where(mine, sc, score_ref[:])
    found_ref[:] = jnp.where(mine, fo, found_ref[:])


@functools.partial(
    jax.jit,
    static_argnames=("k_steps", "interpret"),
)
def pallas_place_batch(cap_cpu, cap_mem, cap_disk,
                       used_cpu, used_mem, used_disk,
                       base_mask, job_tg_count, penalty, aff_score,
                       ask_cpu, ask_mem, ask_disk,
                       n_steps, desired_count, algorithm_spread,
                       k_steps: int, interpret: bool = False) -> PallasOut:
    """Place k_steps allocations for each of B evals in one launch.

    Plane args are f32[N] (N % 128 == 0, bool planes pre-cast to 0/1
    f32); ask args are per-eval vectors [B]; desired_count /
    algorithm_spread broadcast scalars or [B].
    """
    n = cap_cpu.shape[0]
    assert n % LANES == 0, f"node axis {n} not lane-aligned"
    assert 0 < k_steps <= K_SLOTS
    r = n // LANES
    real_b = ask_cpu.shape[0]
    # the (B, K_SLOTS) output block needs >=8 sublanes to lower on
    # TPU; pad tail batches up and slice the extras back off
    B = max(8, real_b)
    if real_b < B:
        pad = B - real_b
        zpad = lambda x: jnp.pad(jnp.asarray(x), (0, pad))  # noqa: E731
        ask_cpu, ask_mem = zpad(ask_cpu), zpad(ask_mem)
        n_steps = zpad(n_steps)   # padded evals place 0 steps

    def plane(x):
        return jnp.asarray(x, jnp.float32).reshape(r, LANES)

    bcast = lambda x: jnp.broadcast_to(jnp.asarray(x), (B,))  # noqa: E731
    scal_f = jnp.stack([
        jnp.asarray(ask_cpu, jnp.float32),
        jnp.asarray(ask_mem, jnp.float32),
        bcast(ask_disk).astype(jnp.float32),
        bcast(algorithm_spread).astype(jnp.float32),
    ], axis=1)
    scal_i = jnp.stack([
        jnp.asarray(n_steps, jnp.int32),
        bcast(desired_count).astype(jnp.int32),
    ], axis=1)

    shared_spec = pl.BlockSpec(
        (r, LANES), lambda b, *_: (0, 0), memory_space=pltpu.VMEM,
    )
    out_spec = pl.BlockSpec((B, K_SLOTS), lambda b, *_: (0, 0),
                            memory_space=pltpu.VMEM)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[shared_spec] * 10,
        out_specs=[out_spec, out_spec, out_spec],
    )
    chosen, scores, found = pl.pallas_call(
        functools.partial(_place_kernel, k_steps=k_steps, r=r),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, K_SLOTS), jnp.int32),
            jax.ShapeDtypeStruct((B, K_SLOTS), jnp.float32),
            jax.ShapeDtypeStruct((B, K_SLOTS), jnp.int32),
        ],
        interpret=interpret,
    )(
        scal_f, scal_i,
        plane(cap_cpu), plane(cap_mem), plane(cap_disk),
        plane(used_cpu), plane(used_mem), plane(used_disk),
        plane(base_mask), plane(job_tg_count), plane(penalty),
        plane(aff_score),
    )
    return PallasOut(
        chosen=chosen[:real_b, :k_steps],
        scores=scores[:real_b, :k_steps],
        found=found[:real_b, :k_steps] > 0,
    )


# ---------------------------------------------------------------------------
# Fused candidate-set scan: the hybrid hot path.
#
# The XLA candidate-set kernel (ops/kernel.place_taskgroup_topk) is one
# full-width scoring pass + approx_max_k + a K-wide deduction scan. The
# scan is tiny compute ([B, ~32] tensors) but unrolls to ~30 XLA ops per
# placement step — per-op overhead dominates it. This kernel keeps the
# full-width pass + approx_max_k in XLA (one fused elementwise pass over
# [B, N] + the TPU-optimized selection) and runs the ENTIRE deduction
# scan as one pallas program: candidate planes live in VMEM registers,
# each step is pure VPU work on a (TB, 128) tile, and the bound check
# (place_taskgroup_topk's `valid`) is tracked in-register. Exactness is
# inherited from the same rest-max bound: when `valid` is False the
# caller re-runs the full-width kernel.
# ---------------------------------------------------------------------------

C_LANES = 128           # candidate axis, one lane row
_SCAL_LANES = 8         # per-eval scalars packed into lanes of one row


def _cand_scan_kernel(scal, cap_cpu, cap_mem, cap_disk,
                      used_cpu, used_mem, used_disk,
                      base, jobtg, penalty, aff, node_id,
                      chosen_ref, score_ref, found_ref, valid_ref,
                      *, k_steps: int, tb: int):
    cols = jax.lax.broadcasted_iota(jnp.int32, (tb, C_LANES), 1)

    def lane(j):
        return jnp.sum(jnp.where(cols == j, scal[:], 0.0), axis=1,
                       keepdims=True)

    a_cpu = lane(0)
    a_mem = lane(1)
    a_disk = lane(2)
    algo_spread = lane(3)
    n_steps = lane(4)
    desired = lane(5)
    rest_max = lane(6)

    cc = cap_cpu[:]
    cm = cap_mem[:]
    cd = cap_disk[:]
    base_m = base[:] > 0.0
    pen = penalty[:] > 0.0
    affs = aff[:]
    nid = node_id[:]

    denom = jnp.maximum(desired, 1.0)
    aff_on = affs != 0.0
    pen_f = jnp.where(pen, -1.0, 0.0)
    extra_planes = pen.astype(jnp.float32) + aff_on.astype(jnp.float32)
    aff_sum = jnp.where(aff_on, affs, 0.0) + pen_f

    def body(i, carry):
        uc, um, ud, utg, ch, sc, fo, ok = carry
        feas = (
            base_m
            & ((cc - uc) >= a_cpu)
            & ((cm - um) >= a_mem)
            & ((cd - ud) >= a_disk)
        )
        fc = jnp.where(cc > 0, 1.0 - (uc + a_cpu) / cc, 0.0)
        fm = jnp.where(cm > 0, 1.0 - (um + a_mem) / cm, 0.0)
        total = jnp.power(10.0, fc) + jnp.power(10.0, fm)
        binpack = jnp.clip(20.0 - total, 0.0, 18.0)
        spreadfit = jnp.clip(total - 2.0, 0.0, 18.0)
        fit = jnp.where(algo_spread > 0, spreadfit, binpack) / 18.0

        coll = utg
        anti_on = coll > 0
        ssum = fit + jnp.where(anti_on, -(coll + 1.0) / denom, 0.0) + aff_sum
        nplanes = 1.0 + anti_on.astype(jnp.float32) + extra_planes
        final = ssum / nplanes

        active = i.astype(jnp.float32) < n_steps            # [TB, 1]
        masked = jnp.where(feas & active, final, NEG_INF)
        rowmax = jnp.max(masked, axis=1, keepdims=True)      # [TB, 1]
        # first-max lane (argmax parity with the XLA candidate order)
        at_max = masked == rowmax
        lane_idx = jnp.min(
            jnp.where(at_max, cols, jnp.int32(2 ** 30)), axis=1,
            keepdims=True)
        fnd = rowmax > NEG_INF / 2
        # chosen NODE id: duplicate candidate rows of one node share
        # deductions (preferred-pin duplicates in the XLA path)
        chosen_id = jnp.sum(
            jnp.where(cols == lane_idx, nid, 0.0), axis=1, keepdims=True)
        share = (nid == chosen_id) & fnd & (active > 0)
        upd = share.astype(jnp.float32)
        uc = uc + upd * a_cpu
        um = um + upd * a_mem
        ud = ud + upd * a_disk
        utg = utg + upd
        # bound check: best candidate must still beat the rest of the
        # cluster (place_taskgroup_topk's ok accumulation). Carried as
        # f32 0/1: a loop-carried bool vector trips a Mosaic layout-
        # inference bug (scf.yield on vector<8x128xi1> with vpad
        # mismatch) on current TPU toolchains
        ok = ok * ((active <= 0) | ~fnd
                   | (rowmax >= rest_max)).astype(jnp.float32)

        at_i = cols == i
        placed = fnd & (active > 0)
        ch = jnp.where(at_i, jnp.where(placed, chosen_id, -1.0), ch)
        sc = jnp.where(at_i, jnp.where(placed, rowmax, 0.0), sc)
        fo = jnp.where(at_i, placed.astype(jnp.float32), fo)
        return uc, um, ud, utg, ch, sc, fo, ok

    init = (
        used_cpu[:], used_mem[:], used_disk[:], jobtg[:],
        jnp.full((tb, C_LANES), -1.0, jnp.float32),
        jnp.zeros((tb, C_LANES), jnp.float32),
        jnp.zeros((tb, C_LANES), jnp.float32),
        jnp.ones((tb, 1), jnp.float32),
    )
    _, _, _, _, ch, sc, fo, ok = jax.lax.fori_loop(0, k_steps, body, init)

    # a missing placement while the rest of the cluster might still fit
    # also invalidates the run (candidates exhausted, full kernel could
    # place) — place_taskgroup_topk's `missing` check
    want = (cols < k_steps) & (cols.astype(jnp.float32) < n_steps)
    missing = jnp.any(want & (fo <= 0.0), axis=1, keepdims=True)
    rest_bad = rest_max <= NEG_INF / 2
    valid = (ok > 0.0) & (~missing | rest_bad)

    chosen_ref[:] = ch.astype(jnp.int32)
    score_ref[:] = sc
    found_ref[:] = (fo > 0.0).astype(jnp.int32)
    valid_ref[:] = jnp.broadcast_to(
        valid.astype(jnp.int32), (tb, C_LANES))


@functools.partial(
    jax.jit,
    static_argnames=("k_steps", "k_cand", "interpret"),
)
def pallas_topk_place_batch(cap_cpu, cap_mem, cap_disk,
                            used_cpu, used_mem, used_disk,
                            base_mask, job_tg_count, penalty, aff_score,
                            ask_cpu, ask_mem, ask_disk,
                            n_steps, desired_count, algorithm_spread,
                            k_steps: int, k_cand: int = 64,
                            interpret: bool = False):
    """Candidate-set placement for a batch of B lean evals, pallas scan.

    Shared planes are f32/bool[N] (the wave's common snapshot); asks are
    per-eval [B]. Returns (chosen i32[B,K] node rows, scores f32[B,K],
    found bool[B,K], valid bool[B]) — `valid=False` members must re-run
    via the full-width kernel, exactly like place_taskgroup_topk.
    """
    n = cap_cpu.shape[0]
    real_b = ask_cpu.shape[0]
    k_cand = min(k_cand, n, C_LANES)
    assert 0 < k_steps <= C_LANES

    f32 = lambda x: jnp.asarray(x, jnp.float32)          # noqa: E731
    bcast = lambda x: jnp.broadcast_to(jnp.asarray(x), (real_b,))  # noqa: E731
    cc, cm, cd = f32(cap_cpu), f32(cap_mem), f32(cap_disk)
    uc, um, ud = f32(used_cpu), f32(used_mem), f32(used_disk)
    base = jnp.asarray(base_mask, bool)
    utg = f32(job_tg_count)
    pen = jnp.asarray(penalty, bool)
    aff = f32(aff_score)
    a_cpu = f32(ask_cpu)[:, None]
    a_mem = f32(ask_mem)[:, None]
    a_disk = f32(bcast(ask_disk))[:, None]
    algo = f32(bcast(algorithm_spread))[:, None]
    desired = f32(bcast(desired_count))[:, None]

    # ---- full-width pass (XLA fuses this into one HBM sweep) ----
    feas = (
        base[None, :]
        & ((cc - uc)[None, :] >= a_cpu)
        & ((cm - um)[None, :] >= a_mem)
        & ((cd - ud)[None, :] >= a_disk)
    )
    fc = jnp.where(cc[None, :] > 0, 1.0 - (uc[None, :] + a_cpu) / cc[None, :], 0.0)
    fm = jnp.where(cm[None, :] > 0, 1.0 - (um[None, :] + a_mem) / cm[None, :], 0.0)
    total = jnp.power(10.0, fc) + jnp.power(10.0, fm)
    binpack = jnp.clip(20.0 - total, 0.0, 18.0)
    spreadfit = jnp.clip(total - 2.0, 0.0, 18.0)
    fit = jnp.where(algo > 0, spreadfit, binpack) / 18.0
    coll = utg[None, :]
    anti_on = coll > 0
    pen_f = jnp.where(pen, -1.0, 0.0)[None, :]
    aff_on = (aff != 0.0)[None, :]
    ssum = (fit + jnp.where(anti_on, -(coll + 1.0) / jnp.maximum(desired, 1.0),
                            0.0)
            + jnp.where(aff_on, aff[None, :], 0.0) + pen_f)
    nplanes = (1.0 + anti_on.astype(jnp.float32) + aff_on.astype(jnp.float32)
               + pen.astype(jnp.float32)[None, :])
    final0 = ssum / nplanes
    masked0 = jnp.where(feas, final0, NEG_INF)           # [B, N]

    _, cand_idx = jax.lax.approx_max_k(masked0, k_cand, recall_target=0.95)
    rows = jnp.arange(real_b)[:, None]
    rest_max = jnp.max(masked0.at[rows, cand_idx].set(NEG_INF), axis=1)

    # ---- gather candidate planes, pad to the lane width ----
    pad_c = C_LANES - k_cand

    def gpad(x, fill):
        g = x[cand_idx].astype(jnp.float32)              # [B, k_cand]
        return jnp.pad(g, ((0, 0), (0, pad_c)), constant_values=fill)

    planes = [
        gpad(cc, 0.0), gpad(cm, 0.0), gpad(cd, 0.0),
        gpad(uc, 0.0), gpad(um, 0.0), gpad(ud, 0.0),
        gpad(base, 0.0),                                  # pad infeasible
        gpad(utg, 0.0), gpad(pen, 0.0), gpad(aff, 0.0),
        jnp.pad(cand_idx.astype(jnp.float32), ((0, 0), (0, pad_c)),
                constant_values=-1.0),                    # node ids
    ]

    scal = jnp.zeros((real_b, _SCAL_LANES), jnp.float32)
    scal = scal.at[:, 0].set(a_cpu[:, 0])
    scal = scal.at[:, 1].set(a_mem[:, 0])
    scal = scal.at[:, 2].set(a_disk[:, 0])
    scal = scal.at[:, 3].set(algo[:, 0])
    scal = scal.at[:, 4].set(jnp.asarray(n_steps, jnp.float32))
    scal = scal.at[:, 5].set(desired[:, 0])
    scal = scal.at[:, 6].set(rest_max)
    scal = jnp.pad(scal, ((0, 0), (0, C_LANES - _SCAL_LANES)))

    # batch-tile: large tiles amortize per-program grid overhead (the
    # whole working set is ~12 x tb x 128 x 4B — ~1.5MiB at tb=256,
    # comfortably VMEM-resident); tiny batches still round to the
    # native 8-sublane tile
    tb = max(8, min(256, 1 << (real_b - 1).bit_length()))
    b_pad = (-real_b) % tb
    if b_pad:
        planes = [jnp.pad(p, ((0, b_pad), (0, 0))) for p in planes]
        scal = jnp.pad(scal, ((0, b_pad), (0, 0)))       # n_steps=0 pad
    B = real_b + b_pad

    blk = pl.BlockSpec((tb, C_LANES), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
    chosen, scores, found, valid = pl.pallas_call(
        functools.partial(_cand_scan_kernel, k_steps=k_steps, tb=tb),
        grid=(B // tb,),
        in_specs=[blk] * 12,
        out_specs=[blk] * 4,
        out_shape=[
            jax.ShapeDtypeStruct((B, C_LANES), jnp.int32),
            jax.ShapeDtypeStruct((B, C_LANES), jnp.float32),
            jax.ShapeDtypeStruct((B, C_LANES), jnp.int32),
            jax.ShapeDtypeStruct((B, C_LANES), jnp.int32),
        ],
        interpret=interpret,
    )(scal, *planes)
    return (
        chosen[:real_b, :k_steps],
        scores[:real_b, :k_steps],
        found[:real_b, :k_steps] > 0,
        valid[:real_b, 0] > 0,
    )


def make_schedule_apply_step_pallas(k_steps: int, interpret: bool = False):
    """Drop-in replacement for batching.make_schedule_apply_step's lean
    variant: same signature, same optimistic-batch + scatter-commit
    semantics, pallas placement inside."""

    # deferred: batching lazily imports this module for the fused
    # top-k scan, so a module-level import here would be circular
    from nomad_tpu.parallel.batching import _jit_donating

    def step(shared, used_cpu, used_mem, ask_cpu, ask_mem, n_steps):
        out = pallas_place_batch(
            shared.cap_cpu, shared.cap_mem, shared.cap_disk,
            used_cpu, used_mem, shared.used_disk,
            shared.base_mask, shared.job_tg_count, shared.penalty,
            shared.aff_score,
            ask_cpu, ask_mem, shared.ask_disk,
            n_steps, shared.desired_count, shared.algorithm_spread,
            k_steps=k_steps, interpret=interpret,
        )
        rows = out.chosen.reshape(-1)
        ok = out.found.reshape(-1)
        w_cpu = (jnp.broadcast_to(ask_cpu[:, None], out.chosen.shape)
                 .reshape(-1) * ok)
        w_mem = (jnp.broadcast_to(ask_mem[:, None], out.chosen.shape)
                 .reshape(-1) * ok)
        safe = jnp.where(ok, rows, 0)
        used_cpu2 = used_cpu.at[safe].add(jnp.where(ok, w_cpu, 0.0))
        used_mem2 = used_mem.at[safe].add(jnp.where(ok, w_mem, 0.0))
        return out, used_cpu2, used_mem2

    # donation through the owning wrapper (PR 2/10 discipline): a raw
    # donate_argnums jit here is handed caller-owned ``jnp.asarray``
    # planes — the runtime can't always use them ("Some donated
    # buffers were not usable: float32[16384]" leaking into the bench
    # tail), and when it CAN they alias caller memory
    return _jit_donating(step, (1, 2))


# ---------------------------------------------------------------------------
# Fused wave mega-kernel (ISSUE 19): the whole joint wave — feasibility
# masking, binpack/spread scoring, the per-step capacity-carry scan,
# and top-k selection — as ONE pallas program whose intermediate planes
# (masked scores, penalty unions, candidate sets) never leave
# VMEM/registers between stages. The body runs the SAME scan core as
# the XLA composite (ops/kernel.place_taskgroups_joint) over values
# read from the kernel refs, so bit-identity with the composite holds
# by construction across the whole supported feature lattice; what
# fusion adds is the program boundary: one dispatch, one packed
# readback (ops/kernel.FusedWaveOut), zero HBM round-trips between the
# former composite stages. Interpret mode off-TPU keeps CPU tier-1
# running the exact fused program the TPU path dispatches.
# ---------------------------------------------------------------------------


def fused_wave_place(kin, step_member, step_local, t_steps: int,
                     features, interpret: bool = True):
    """One-dispatch fused wave: (stacked KernelIn, step maps) ->
    ops/kernel.FusedWaveOut. Mirrors place_taskgroups_joint + the
    launcher's eager-fetch packing in a single pallas program."""
    from nomad_tpu.ops.kernel import (
        TOPK,
        FusedWaveOut,
        KernelIn,
        fused_pack_len,
        pack_fused_wave,
        place_taskgroups_joint,
    )

    b = int(kin.n_steps.shape[0])
    n = int(kin.cap_cpu.shape[-1])
    leaves = list(kin)
    # rank-0 leaves (wave-shared scalars) ship as (1,) rows — pallas
    # refs want at least one axis — and are restored inside the body
    scalar = tuple(jnp.ndim(x) == 0 for x in leaves)
    ins = [jnp.reshape(x, (1,)) if s else jnp.asarray(x)
           for x, s in zip(leaves, scalar)]

    def body(sm_ref, sl_ref, *refs):
        kin_refs = refs[:len(leaves)]
        packed_ref, ti_ref, ts_ref, ac_ref, am_ref, ad_ref = \
            refs[len(leaves):]
        vals = [r[...][0] if s else r[...]
                for r, s in zip(kin_refs, scalar)]
        out = place_taskgroups_joint(
            KernelIn(*vals), sm_ref[...], sl_ref[...], t_steps,
            features)
        packed_ref[...] = pack_fused_wave(out, t_steps, b)
        ti_ref[...] = out.topk_idx
        ts_ref[...] = out.topk_scores
        ac_ref[...] = out.a_cpu
        am_ref[...] = out.a_mem
        ad_ref[...] = out.a_disk

    out_shape = (
        jax.ShapeDtypeStruct((fused_pack_len(t_steps, b),), jnp.float32),
        jax.ShapeDtypeStruct((t_steps, TOPK), jnp.int32),
        jax.ShapeDtypeStruct((t_steps, TOPK), jnp.float32),
        jax.ShapeDtypeStruct((n,), jnp.float32),
        jax.ShapeDtypeStruct((n,), jnp.float32),
        jax.ShapeDtypeStruct((n,), jnp.float32),
    )
    res = pl.pallas_call(body, out_shape=out_shape,
                         interpret=interpret)(
        step_member, step_local, *ins)
    return FusedWaveOut(*res)


def _fused_wave_run(kin, step_member, step_local, t_steps: int,
                    features):
    # interpret everywhere but real TPU: tier-1 CPU runs the exact
    # fused program; on-chip the same body compiles through Mosaic
    return fused_wave_place(kin, step_member, step_local, t_steps,
                            features,
                            interpret=jax.default_backend() != "tpu")


fused_wave_place_jit = jax.jit(_fused_wave_run, static_argnums=(3, 4))


def make_fused_wave_apply(t_steps: int, features,
                          interpret: bool = True):
    """Fused wave + carry commit with owned-buffer donation (the
    PR 10/18 discipline): ``fn(kin, used_cpu, used_mem, step_member,
    step_local) -> (FusedWaveOut, used_cpu', used_mem')`` where the
    used planes are donated INTO their post-wave successors. Donation
    routes through batching._jit_donating, which copies the donated
    args into buffers the jit owns — handing it caller-owned
    ``jnp.asarray`` planes neither corrupts them nor trips the
    "donated buffers were not usable" warning conftest promotes to an
    error."""
    from nomad_tpu.parallel.batching import _jit_donating

    def step(kin, used_cpu, used_mem, step_member, step_local):
        kin2 = kin._replace(used_cpu=used_cpu, used_mem=used_mem)
        out = fused_wave_place(kin2, step_member, step_local, t_steps,
                               features, interpret=interpret)
        return out, used_cpu + out.a_cpu, used_mem + out.a_mem

    return _jit_donating(step, (1, 2))
