"""The batched placement kernel.

Semantics parity map (Go reference -> tensor formulation):

- FeasibilityWrapper + checkers (feasible.go:1050, :135-1193): host-side
  per-class evaluation folded into ``base_mask``; numeric resource checks
  (cpu/mem/disk/ports/devices/bandwidth/cores) run on device as mask algebra.
- BinPackIterator.Next (rank.go:193-557): utilization = proposed + ask;
  score = ScoreFitBinPack (funcs.go:259) or ScoreFitSpread (funcs.go:286)
  under the cluster scheduler algorithm, normalized by 18 (rank.go:547).
- JobAntiAffinityIterator (rank.go:560): penalty -(collisions+1)/count,
  plane appended only where collisions > 0.
- NodeReschedulingPenaltyIterator (rank.go:630): -1 plane on penalty nodes.
- NodeAffinityIterator (rank.go:674): weighted-sum plane appended where
  nonzero (host precomputes the per-node normalized score).
- SpreadIterator (spread.go:116-245): desired-count boost and
  evenSpreadScoreBoost reproduced on device from bucket counts.
- ScoreNormalizationIterator (rank.go:764): mean over *appended* planes --
  reproduced exactly via per-plane appended masks.
- LimitIterator/MaxScoreIterator (select.go): replaced by global argmax
  over ALL feasible nodes (strictly better placement quality than the
  log2-limited iteration; SURVEY.md section 7.2).
- Sequential resource deduction between placements of one task group
  (generic_sched.go computePlacements loop): ``lax.scan`` steps that
  deduct the chosen node's planes before the next argmax.

Everything is static-shaped; node axis padded (ClusterTensors.n_pad),
placement axis padded to step buckets (``pad_steps``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from nomad_tpu.tensors.schema import (
    MAX_DEV_REQS,
    MAX_SPREADS,
    SPREAD_BUCKETS,
    ClusterTensors,
    EvalTensors,
)

def _machine_cache_tag() -> str:
    """A fingerprint of what makes an XLA:CPU AOT artifact loadable on
    THIS host: the CPU feature set (plus arch and jax version, which
    change the serialized format).

    The persistent compilation cache stores machine-code artifacts;
    XLA's ``cpu_aot_loader`` loads them back with only a LOG-AND-FALL-
    BACK check against the host's features, so a cache dir carried
    across machines (a baked container image, a shared home volume, a
    migrated VM) floods stderr with "Target machine feature
    +prefer-no-gather is not supported" walls on every variant load —
    hundreds of them per warmup pass. Namespacing the cache dir by
    this tag makes a foreign machine's artifacts simply invisible:
    stale caches degrade to a clean recompile (into the new
    namespace), never a spew."""
    import hashlib
    import platform

    bits = [platform.machine(), jax.__version__]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 "flags", arm64 "Features" — the first CPU's line
                # is the loadability contract cpu_aot_loader checks
                if line.startswith(("flags", "Features")):
                    bits.append(" ".join(sorted(
                        line.split(":", 1)[1].split())))
                    break
    except OSError:
        # no /proc (macOS, containers without procfs): arch + version
        # still split caches across the incompatibility classes that
        # have bitten (different container hosts)
        pass
    return hashlib.sha256("|".join(bits).encode()).hexdigest()[:16]


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache (set up when the kernel module
    loads, i.e. only for consumers that actually touch the device path).

    The scheduler compiles one kernel variant per (wave size, step
    bucket, feature set); on TPU a cold compile is tens of seconds.
    The persistent cache makes every variant a one-time cost per
    machine instead of per process — without it, a fresh server paying
    full compiles mid-scheduling can outlive the eval broker's nack
    timeout and thrash redeliveries. Respects an existing user-set
    cache dir; disable with NOMAD_TPU_COMPILE_CACHE=0.

    The cache lives in a per-machine-fingerprint subdirectory
    (``_machine_cache_tag``): AOT artifacts are machine code, and a
    cache dir that outlives its machine (image bake, shared volume)
    otherwise floods stderr through XLA's cpu_aot_loader on every
    load attempt before falling back.
    """
    import os

    try:
        if jax.config.jax_compilation_cache_dir:
            return
        cache_dir = os.environ.get(
            "NOMAD_TPU_COMPILE_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache", "nomad_tpu_xla"),
        )
        if cache_dir and cache_dir != "0":
            cache_dir = os.path.join(cache_dir, _machine_cache_tag())
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 - cache is an optimization only
        pass


_enable_compile_cache()

NEG_INF = -1.0e30
TOPK = 8          # top-K score metadata returned per placement (AllocMetric)
MAX_PENALTY_NODES = 4   # previous nodes penalized per rescheduled placement
_STEP_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


def pad_steps(k: int) -> int:
    for b in _STEP_BUCKETS:
        if k <= b:
            return b
    return ((k + 4095) // 4096) * 4096


#: live-path floor for the placement-axis bucket: a follow-up eval
#: placing 1-2 leftover allocs used to compile its own tiny step
#: variant per (wave, k) pair — padding every live launch to at least
#: 8 steps collapses those onto the primary evals' programs (inactive
#: steps are a few microseconds of device scan; a cold compile is tens
#: of seconds)
MIN_STEP_BUCKET = 8


def pad_steps_live(k: int) -> int:
    return pad_steps(max(k, MIN_STEP_BUCKET))


class NeutralPlanes(NamedTuple):
    """Read-only neutral planes shared BY IDENTITY across evaluations.

    The per-eval tensor build allocates a dozen O(nodes) planes that
    stay all-neutral for the common ask (no devices, no affinities, no
    in-plan ports, fresh job): allocating them per eval was the
    dominant host cost of the live path, and distinct-but-equal arrays
    also defeat the wave coalescer's identity-based sharing (every
    member would ship its own copy of the same zeros). One frozen
    singleton per padded node size serves every eval; writers must
    copy-on-write (the arrays are non-writeable, so a missed copy
    raises instead of corrupting a neighbor eval).
    """

    zeros_f32: np.ndarray       # [N]
    zeros_i32: np.ndarray       # [N]
    zeros_bool: np.ndarray      # [N]
    zeros_dev: np.ndarray       # [N, MAX_DEV_REQS] f32
    neg1_spread_bucket: np.ndarray   # [S, N] i32
    zeros_spread_counts: np.ndarray  # [S, SPREAD_BUCKETS] f32
    neg1_spread_desired: np.ndarray  # [S, SPREAD_BUCKETS] f32
    zeros_spread_flags: np.ndarray   # [S] bool
    zeros_spread_weight: np.ndarray  # [S] f32
    arange_i32: np.ndarray      # [N] identity node_perm


def _frozen(a: np.ndarray) -> np.ndarray:  # graft: frozen
    a.flags.writeable = False
    return a


_NEUTRAL_CACHE: dict = {}


def neutral_planes(n: int) -> NeutralPlanes:  # graft: frozen
    got = _NEUTRAL_CACHE.get(n)
    if got is None:
        got = NeutralPlanes(
            zeros_f32=_frozen(np.zeros(n, np.float32)),
            zeros_i32=_frozen(np.zeros(n, np.int32)),
            zeros_bool=_frozen(np.zeros(n, bool)),
            zeros_dev=_frozen(np.zeros((n, MAX_DEV_REQS), np.float32)),
            neg1_spread_bucket=_frozen(
                np.full((MAX_SPREADS, n), -1, np.int32)),
            zeros_spread_counts=_frozen(
                np.zeros((MAX_SPREADS, SPREAD_BUCKETS), np.float32)),
            neg1_spread_desired=_frozen(
                np.full((MAX_SPREADS, SPREAD_BUCKETS), -1.0, np.float32)),
            zeros_spread_flags=_frozen(np.zeros(MAX_SPREADS, bool)),
            zeros_spread_weight=_frozen(np.zeros(MAX_SPREADS, np.float32)),
            arange_i32=_frozen(np.arange(n, dtype=np.int32)),
        )
        _NEUTRAL_CACHE[n] = got
    return got


_NEUTRAL_WORDS_CACHE: dict = {}


def neutral_port_words(n: int, w: int) -> np.ndarray:  # graft: frozen
    """Frozen all-zero [N, W] u32 port-conflict words."""
    got = _NEUTRAL_WORDS_CACHE.get((n, w))
    if got is None:
        got = _frozen(np.zeros((n, w), np.uint32))
        _NEUTRAL_WORDS_CACHE[(n, w)] = got
    return got


_NEUTRAL_STEP_CACHE: dict = {}


def neutral_step_planes(k_pad: int):  # graft: frozen
    """(step_penalty[k,P]=-1, step_preferred[k]=-1) singletons."""
    got = _NEUTRAL_STEP_CACHE.get(k_pad)
    if got is None:
        got = (
            _frozen(np.full((k_pad, MAX_PENALTY_NODES), -1, np.int32)),
            _frozen(np.full(k_pad, -1, np.int32)),
        )
        _NEUTRAL_STEP_CACHE[k_pad] = got
    return got


class KernelFeatures(NamedTuple):
    """Static specialization flags (hashable; a jit static argument).

    The reference's iterator pipeline only pays for the checkers a job
    actually uses (stack.go wires checkers per ask); the tensor
    formulation gets the same effect by compiling a lean kernel variant
    per feature combination. Disabling a feature removes its planes
    from the compiled program entirely; semantics are unchanged because
    the host only disables features whose inputs are neutral (no ports
    asked, no spreads, ...).
    """

    n_spreads: int = MAX_SPREADS
    with_topk: bool = True        # per-step top-K score metadata (AllocMetric)
    with_devices: bool = True
    with_ports: bool = True
    with_cores: bool = True
    with_network: bool = True     # bandwidth accounting
    with_distinct: bool = True    # distinct_hosts masks in the scan
    with_step_penalties: bool = True  # per-placement penalty node ids
    with_preferred: bool = True   # per-placement preferred-node pins
    # per-eval node-order decorrelation (shuffleNodes util.go:464): the
    # argmax runs over a seeded permutation, so concurrent evals break
    # score TIES on different nodes instead of all piling onto row 0;
    # scores and non-tied choices are unchanged
    with_shuffle: bool = False


FULL_FEATURES = KernelFeatures()


def canonical_features(f: KernelFeatures) -> KernelFeatures:
    """Collapse near-identical feature sets onto one compiled variant.

    Every distinct ``KernelFeatures`` value is a separate XLA compile
    (tens of seconds cold on TPU), and the live path was forking
    variants on axes that don't pay for their slot: a job with 2
    spread stanzas compiled a different program than one with 3, and a
    wave whose single rescheduled member enabled ``with_step_penalties``
    compiled apart from the identical wave that also pinned a
    preferred node. Canonicalization rounds UP onto a coarser lattice:

    - ``n_spreads`` is 0 or MAX_SPREADS (inactive stanzas are no-ops
      by kernel definition, so extra spread slots only cost device
      time on a tiny [S] axis);
    - ``with_step_penalties``/``with_preferred`` travel together (both
      read tiny per-step planes whose neutral rows -1 are no-ops).

    Enabling a feature for an ask that ships neutral planes never
    changes placements — that is the coalescer's existing union
    contract — so this only trades a sliver of device time for a
    bounded variant count. Axes that change semantics (``with_shuffle``)
    or materially change program cost (ports/devices/network/cores
    over the wide node axis) are left alone.
    """
    aux = f.with_step_penalties or f.with_preferred
    return f._replace(
        n_spreads=0 if f.n_spreads == 0 else MAX_SPREADS,
        with_step_penalties=aux,
        with_preferred=aux,
    )

#: the lean cpu/mem/disk binpack envelope — what a plain service/batch
#: ask compiles to, and the exact feature set the pallas backend
#: (ops/pallas_kernel.py) implements; bench + parity tests pin it
LEAN_FEATURES = KernelFeatures(
    n_spreads=0, with_topk=False, with_devices=False, with_ports=False,
    with_cores=False, with_network=False, with_distinct=False,
    with_step_penalties=False, with_preferred=False,
)


class KernelIn(NamedTuple):
    """Device-side planes for one (eval, task group). All arrays."""

    # cluster planes (f32/i32/bool over padded node axis)
    cap_cpu: jnp.ndarray
    cap_mem: jnp.ndarray
    cap_disk: jnp.ndarray
    free_cores: jnp.ndarray
    shares_per_core: jnp.ndarray
    free_dyn: jnp.ndarray
    # eval planes
    base_mask: jnp.ndarray
    used_cpu: jnp.ndarray
    used_mem: jnp.ndarray
    used_disk: jnp.ndarray
    used_cores: jnp.ndarray
    used_mbits: jnp.ndarray
    avail_mbits: jnp.ndarray
    port_conflict: jnp.ndarray       # bool[N]: ask reserved port already used
    dev_free: jnp.ndarray            # f32[N, MAX_DEV_REQS]
    dev_aff_score: jnp.ndarray       # f32[N]
    has_dev_affinity: jnp.ndarray    # bool scalar
    job_tg_count: jnp.ndarray        # i32[N]
    penalty: jnp.ndarray             # bool[N]
    aff_score: jnp.ndarray           # f32[N]
    node_perm: jnp.ndarray           # i32[N]: seeded tie-break permutation
    # per-step planes (placement axis K): rescheduled allocs penalize
    # their previous node(s) (rank.go:630 SetPenaltyNodes is per-Select)
    # and sticky/preferred placements pin a node (stack.go:120-139)
    step_penalty: jnp.ndarray        # i32[K, MAX_PENALTY_NODES], -1 pad
    step_preferred: jnp.ndarray      # i32[K], -1 none
    # distinct_hosts enforcement inside the scan (feasible.go:526):
    # job-level forbids co-location with any of the job's allocs,
    # tg-level with the same task group's
    job_any_count: jnp.ndarray       # i32[N] job allocs on node (any tg)
    distinct_hosts_job: jnp.ndarray  # bool scalar
    distinct_hosts_tg: jnp.ndarray   # bool scalar
    # spreads, stacked [S, ...]
    spread_active: jnp.ndarray       # bool[S]
    spread_even: jnp.ndarray         # bool[S]
    spread_weight: jnp.ndarray       # f32[S]
    spread_bucket: jnp.ndarray       # i32[S, N]
    spread_counts: jnp.ndarray       # f32[S, B]
    spread_desired: jnp.ndarray      # f32[S, B]
    # ask scalars
    ask_cpu: jnp.ndarray
    ask_mem: jnp.ndarray
    ask_disk: jnp.ndarray
    ask_cores: jnp.ndarray
    ask_dyn_ports: jnp.ndarray
    ask_has_reserved_ports: jnp.ndarray  # bool scalar
    ask_dev: jnp.ndarray             # f32[MAX_DEV_REQS]
    ask_mbits: jnp.ndarray
    desired_count: jnp.ndarray       # i32 scalar (anti-affinity denominator)
    algorithm_spread: jnp.ndarray    # bool scalar: ScoreFitSpread mode
    n_steps: jnp.ndarray             # i32 scalar: real placements wanted


#: rank of each KernelIn leaf in the SINGLE-problem (unbatched) layout.
#: The joint wave kernel accepts leaves either unbatched (shared by
#: every member — e.g. the cluster capacity planes and a wave's common
#: snapshot utilization) or stacked with a leading member axis; a leaf
#: whose rank equals the entry here +1 is batched. Shipping shared
#: planes once instead of B times is what keeps wave upload bytes flat
#: in wave size on a remote-device transport.
KIN_UNBATCHED_RANKS = KernelIn(
    cap_cpu=1, cap_mem=1, cap_disk=1, free_cores=1, shares_per_core=1,
    free_dyn=1, base_mask=1, used_cpu=1, used_mem=1, used_disk=1,
    used_cores=1, used_mbits=1, avail_mbits=1, port_conflict=1,
    dev_free=2, dev_aff_score=1, has_dev_affinity=0, job_tg_count=1,
    penalty=1, aff_score=1, node_perm=1, step_penalty=2,
    step_preferred=1, job_any_count=1, distinct_hosts_job=0,
    distinct_hosts_tg=0, spread_active=1, spread_even=1, spread_weight=1,
    spread_bucket=2, spread_counts=2, spread_desired=2, ask_cpu=0,
    ask_mem=0, ask_disk=0, ask_cores=0, ask_dyn_ports=0,
    ask_has_reserved_ports=0, ask_dev=1, ask_mbits=0, desired_count=0,
    algorithm_spread=0, n_steps=0,
)


class KernelOut(NamedTuple):
    chosen: jnp.ndarray          # i32[K]: node row per placement (-1 none)
    scores: jnp.ndarray          # f32[K]: final normalized score
    found: jnp.ndarray           # bool[K]
    topk_idx: jnp.ndarray        # i32[K, TOPK]
    topk_scores: jnp.ndarray     # f32[K, TOPK]
    # metrics from the first step's masks (AllocMetric inputs)
    nodes_evaluated: jnp.ndarray     # i32: base-eligible nodes
    nodes_feasible: jnp.ndarray      # i32: passed all resource checks
    exhausted_cpu: jnp.ndarray
    exhausted_mem: jnp.ndarray
    exhausted_disk: jnp.ndarray
    exhausted_ports: jnp.ndarray
    exhausted_devices: jnp.ndarray
    exhausted_cores: jnp.ndarray


def _feasible(kin: KernelIn, st, f: KernelFeatures) -> tuple:
    """Resource-fit mask planes for the current carry state."""
    true_plane = jnp.ones_like(kin.base_mask)
    free_cpu = kin.cap_cpu - st["used_cpu"]
    free_mem = kin.cap_mem - st["used_mem"]
    free_disk = kin.cap_disk - st["used_disk"]
    # Optional dimensions apply only when the ask requests them — the
    # reference checks bandwidth/ports/devices/cores inside the assign
    # paths it only enters for a non-empty ask (rank.go:270-492), so a
    # node overcommitted on a dimension the ask doesn't use stays
    # feasible. This also makes the lean variants exactly equivalent.
    if f.with_cores:
        ask_cpu_total = (
            kin.ask_cpu + kin.ask_cores.astype(jnp.float32) * kin.shares_per_core
        )
        fit_cores = (kin.ask_cores <= 0) | (
            (kin.free_cores - st["used_cores"]) >= kin.ask_cores
        )
    else:
        ask_cpu_total = kin.ask_cpu
        fit_cores = true_plane
    fit_cpu = free_cpu >= ask_cpu_total
    fit_mem = free_mem >= kin.ask_mem
    fit_disk = free_disk >= kin.ask_disk
    if f.with_ports:
        fit_dyn = (kin.ask_dyn_ports <= 0) | (st["free_dyn"] >= kin.ask_dyn_ports)
        fit_ports = ~(st["port_conflict"] & kin.ask_has_reserved_ports) & fit_dyn
    else:
        fit_ports = true_plane
    if f.with_devices:
        fit_dev = jnp.all(
            (kin.ask_dev[None, :] <= 0) | (st["dev_free"] >= kin.ask_dev[None, :]),
            axis=1,
        )
    else:
        fit_dev = true_plane
    if f.with_network:
        fit_bw = (kin.ask_mbits <= 0) | (
            (st["used_mbits"] + kin.ask_mbits) <= kin.avail_mbits
        )
    else:
        fit_bw = true_plane
    if f.with_distinct:
        distinct_ok = ~(
            (kin.distinct_hosts_job & (st["job_any_count"] > 0))
            | (kin.distinct_hosts_tg & (st["job_tg_count"] > 0))
        )
    else:
        distinct_ok = true_plane
    feasible = (
        kin.base_mask
        & fit_cpu & fit_mem & fit_disk & fit_cores
        & fit_ports & fit_dev & fit_bw & distinct_ok
    )
    return feasible, ask_cpu_total, dict(
        fit_cpu=fit_cpu, fit_mem=fit_mem, fit_disk=fit_disk,
        fit_cores=fit_cores, fit_ports=fit_ports, fit_dev=fit_dev,
    )


def _score(kin: KernelIn, st, ask_cpu_total, penalty,
           f: KernelFeatures, spread_onehot=None) -> tuple:
    """Score planes + appended-mask normalization (rank.go semantics)."""
    util_cpu = st["used_cpu"] + ask_cpu_total
    util_mem = st["used_mem"] + kin.ask_mem

    # computeFreePercentage (funcs.go:235) with zero-capacity guard
    fc = jnp.where(kin.cap_cpu > 0, 1.0 - util_cpu / kin.cap_cpu, 0.0)
    fm = jnp.where(kin.cap_mem > 0, 1.0 - util_mem / kin.cap_mem, 0.0)
    total = jnp.power(10.0, fc) + jnp.power(10.0, fm)
    binpack = jnp.clip(20.0 - total, 0.0, 18.0)        # funcs.go:259
    spreadfit = jnp.clip(total - 2.0, 0.0, 18.0)       # funcs.go:286
    fit = jnp.where(kin.algorithm_spread, spreadfit, binpack) / 18.0

    # plane sum with per-plane appended masks (ScoreNormalizationIterator
    # averages only appended scores, rank.go:764)
    score_sum = fit
    nplanes = jnp.ones_like(fit)

    # device affinity (rank.go:549-554): appended when the ask has device
    # affinities at all
    if f.with_devices:
        dev_on = kin.has_dev_affinity
        score_sum = score_sum + jnp.where(dev_on, kin.dev_aff_score, 0.0)
        nplanes = nplanes + jnp.where(dev_on, 1.0, 0.0)

    # job anti-affinity (rank.go:588-607)
    collisions = st["job_tg_count"].astype(jnp.float32)
    denom = jnp.maximum(kin.desired_count.astype(jnp.float32), 1.0)
    anti = -(collisions + 1.0) / denom
    anti_on = collisions > 0
    score_sum = score_sum + jnp.where(anti_on, anti, 0.0)
    nplanes = nplanes + anti_on.astype(jnp.float32)

    # rescheduling penalty (rank.go:655-663)
    score_sum = score_sum + jnp.where(penalty, -1.0, 0.0)
    nplanes = nplanes + penalty.astype(jnp.float32)

    # node affinity (rank.go:730-745): appended where nonzero
    aff_on = kin.aff_score != 0.0
    score_sum = score_sum + jnp.where(aff_on, kin.aff_score, 0.0)
    nplanes = nplanes + aff_on.astype(jnp.float32)

    # spread (spread.go:116-245)
    if f.n_spreads > 0:
        spread_total = _spread_score(kin, st, spread_onehot, f.n_spreads)
        spread_on = spread_total != 0.0
        score_sum = score_sum + jnp.where(spread_on, spread_total, 0.0)
        nplanes = nplanes + spread_on.astype(jnp.float32)

    return score_sum / nplanes


def _spread_score(kin: KernelIn, st, spread_onehot,
                  n_spreads: int) -> jnp.ndarray:
    """Sum of per-stanza spread boosts for every node.

    TPU formulation: boosts are a function of the node's BUCKET, so
    compute them over the tiny bucket axis (B=SPREAD_BUCKETS) and
    scatter to nodes with one one-hot matmul per stanza — the MXU
    replaces a 10k-wide gather (2x faster measured, and the
    bucket-axis math is ~100x narrower than node-axis math)."""
    n = kin.cap_cpu.shape[0]
    total = jnp.zeros(n, jnp.float32)
    counts = st["spread_counts"]  # [S, B]
    for s in range(n_spreads):     # static unroll, S is tiny
        counts_b = counts[s]                     # f32[B]
        # -- desired-count path (spread.go:158-183): usedCount+1 --
        des_b = kin.spread_desired[s]            # f32[B], -1 = even mode
        desired_b = jnp.where(
            des_b > 0.0,
            ((des_b - (counts_b + 1.0)) / des_b) * kin.spread_weight[s],
            -1.0,
        )
        # -- even-spread path (spread.go evenSpreadScoreBoost :193) --
        present = counts_b > 0.0
        any_alloc = jnp.any(present)
        minc = jnp.min(jnp.where(present, counts_b, jnp.inf))
        maxc = jnp.max(jnp.where(present, counts_b, -jnp.inf))
        delta_b = jnp.where(
            minc > 0, (minc - counts_b) / jnp.maximum(minc, 1.0), -1.0)
        even_b = jnp.where(
            counts_b != minc,
            delta_b,
            jnp.where(
                minc == maxc,
                -1.0,
                jnp.where(minc == 0, 1.0,
                          (maxc - minc) / jnp.maximum(minc, 1.0)),
            ),
        )
        even_b = jnp.where(any_alloc, even_b, 0.0)
        stanza_b = jnp.where(kin.spread_even[s], even_b, desired_b)
        # bucket -> node: one-hot matmul (zero rows for bucket-less
        # nodes, which score the missing penalty instead). HIGHEST
        # precision: default TPU matmul rounds f32 through bf16 on the
        # MXU, which would break Go-score parity on close boosts
        node_boost = jnp.matmul(
            spread_onehot[s], stanza_b,
            precision=jax.lax.Precision.HIGHEST)            # f32[N]
        missing = kin.spread_bucket[s] < 0
        stanza = jnp.where(missing, -1.0, node_boost)
        total = total + jnp.where(kin.spread_active[s], stanza, 0.0)
    return total


def place_taskgroup(
    kin: KernelIn, k_steps: int, features: KernelFeatures = FULL_FEATURES
) -> KernelOut:
    """Place up to ``k_steps`` allocations of one task group.

    Each scan step: mask -> score -> argmax -> deduct chosen node's
    planes. Steps past ``kin.n_steps`` are inactive (static padding).
    ``features`` statically removes planes the ask does not use.
    """
    n = kin.cap_cpu.shape[0]
    f = features

    init = dict(
        used_cpu=kin.used_cpu,
        used_mem=kin.used_mem,
        used_disk=kin.used_disk,
        job_tg_count=kin.job_tg_count,
    )
    if f.with_cores:
        init["used_cores"] = kin.used_cores
    if f.with_network:
        init["used_mbits"] = kin.used_mbits
    if f.with_ports:
        init["free_dyn"] = kin.free_dyn
        init["port_conflict"] = kin.port_conflict
    if f.with_devices:
        init["dev_free"] = kin.dev_free
    if f.with_distinct:
        init["job_any_count"] = kin.job_any_count
    if f.n_spreads > 0:
        init["spread_counts"] = kin.spread_counts
    # node->bucket one-hot derived on device once per launch (XLA
    # keeps it live across the scan); 0/1 rows, zero for bucket-less
    # nodes, so the MXU projections are exact where they must be
    spread_onehot = None
    if f.n_spreads > 0:
        sb = kin.spread_bucket[:f.n_spreads]
        spread_onehot = (
            jax.nn.one_hot(jnp.clip(sb, 0, SPREAD_BUCKETS - 1),
                           SPREAD_BUCKETS, dtype=jnp.float32)
            * (sb >= 0)[..., None]
        )

    # metrics from the initial state (one extra mask pass, outside scan)
    feas0, _, dims0 = _feasible(kin, init, f)
    base_i = kin.base_mask
    exhausted = lambda fit: jnp.sum(base_i & ~fit).astype(jnp.int32)  # noqa: E731

    iota = jnp.arange(n, dtype=jnp.int32)

    def step(st, i):
        feasible, ask_cpu_total, _ = _feasible(kin, st, f)
        # per-step penalty node ids OR'd into the eval-level plane
        penalty = kin.penalty
        if f.with_step_penalties:
            pen_ids = kin.step_penalty[i]                   # i32[P]
            step_pen = jnp.any(iota[:, None] == pen_ids[None, :], axis=1)
            penalty = penalty | step_pen
        final = _score(kin, st, ask_cpu_total, penalty, f, spread_onehot)
        active = i < kin.n_steps
        masked = jnp.where(feasible & active, final, NEG_INF)
        if f.with_shuffle:
            # argmax over the permuted plane: equal-score candidates
            # resolve in permutation order (shuffleNodes util.go:464)
            best = kin.node_perm[jnp.argmax(masked[kin.node_perm])]
        else:
            best = jnp.argmax(masked)
        # preferred-node pin: take it when feasible (stack.go preferred-
        # source select), else fall back to the global argmax
        if f.with_preferred:
            pref = kin.step_preferred[i]
            pref_ok = (pref >= 0) & feasible[jnp.clip(pref, 0, n - 1)] & active
            idx = jnp.where(pref_ok, jnp.clip(pref, 0, n - 1), best)
        else:
            idx = best
        found = masked[idx] > NEG_INF / 2

        if f.with_topk:
            topv, topi = jax.lax.top_k(masked, TOPK)
        else:
            topv = jnp.full(TOPK, NEG_INF)
            topi = jnp.zeros(TOPK, jnp.int32)

        # deduct the chosen node's planes (only when found & active)
        upd = (found & active).astype(jnp.float32)
        updi = (found & active).astype(jnp.int32)
        one = jax.nn.one_hot(idx, n, dtype=jnp.float32) * upd
        onei = jax.nn.one_hot(idx, n, dtype=jnp.int32) * updi
        st2 = dict(
            used_cpu=st["used_cpu"] + one * ask_cpu_total,
            used_mem=st["used_mem"] + one * kin.ask_mem,
            used_disk=st["used_disk"] + one * kin.ask_disk,
            job_tg_count=st["job_tg_count"] + onei,
        )
        if f.with_cores:
            st2["used_cores"] = st["used_cores"] + onei * kin.ask_cores
        if f.with_network:
            st2["used_mbits"] = st["used_mbits"] + onei * kin.ask_mbits
        if f.with_ports:
            st2["free_dyn"] = st["free_dyn"] - onei * kin.ask_dyn_ports
            # same reserved ports collide on the chosen node next step
            st2["port_conflict"] = st["port_conflict"] | (
                (one > 0) & kin.ask_has_reserved_ports
            )
        if f.with_devices:
            st2["dev_free"] = st["dev_free"] - one[:, None] * kin.ask_dev[None, :]
        if f.with_distinct:
            st2["job_any_count"] = st["job_any_count"] + onei
        if f.n_spreads > 0:
            st2["spread_counts"] = _bump_spread(
                kin, st["spread_counts"], one, spread_onehot, f.n_spreads
            )
        out = (
            jnp.where(found, idx, -1).astype(jnp.int32),
            jnp.where(found, masked[idx], 0.0),
            found & active,
            topi.astype(jnp.int32),
            topv,
        )
        return st2, out

    _, (chosen, scores, found, topk_idx, topk_scores) = jax.lax.scan(
        step, init, jnp.arange(k_steps)
    )

    return KernelOut(
        chosen=chosen,
        scores=scores,
        found=found,
        topk_idx=topk_idx,
        topk_scores=topk_scores,
        nodes_evaluated=jnp.sum(base_i).astype(jnp.int32),
        nodes_feasible=jnp.sum(feas0).astype(jnp.int32),
        exhausted_cpu=exhausted(dims0["fit_cpu"]),
        exhausted_mem=exhausted(dims0["fit_mem"]),
        exhausted_disk=exhausted(dims0["fit_disk"]),
        exhausted_ports=exhausted(dims0["fit_ports"]),
        exhausted_devices=exhausted(dims0["fit_dev"]),
        exhausted_cores=exhausted(dims0["fit_cores"]),
    )


def _bump_spread(kin: KernelIn, counts, one, spread_onehot,
                 n_spreads: int = MAX_SPREADS):
    """counts[s, bucket_of_chosen] += 1 for active stanzas.

    ``one`` is the chosen node's one-hot plane (f32[N], zeros when
    nothing placed); projecting it through the node->bucket one-hot
    gives the chosen bucket row without a dynamic gather (zero row
    when the chosen node has no bucket value)."""
    bump = jnp.zeros_like(counts)
    for s in range(n_spreads):
        row = one @ spread_onehot[s]              # f32[B]
        bump = bump.at[s].add(
            jnp.where(kin.spread_active[s], row, 0.0))
    return counts + bump


place_taskgroup_jit = jax.jit(place_taskgroup, static_argnums=(1, 2))


def place_taskgroup_topk(
    kin: KernelIn, k_steps: int, features: KernelFeatures = FULL_FEATURES,
    n_candidates: int = 0,
) -> tuple:
    """Candidate-set placement: full-width scoring ONCE, sequential
    deduction over a top-K candidate subset.

    The full kernel recomputes feasibility + scores for every node at
    every scan step — O(N * k). But with the binpack fit function
    (funcs.go:259) a placement only changes the CHOSEN node's planes,
    and every score-mutating plane (utilization, job anti-affinity
    counts, penalties) moves non-chosen scores DOWN or not at all, so
    the (K+1)-th initial score upper-bounds everything outside the
    candidate set for the whole scan. One O(N log K) top_k then a
    K-wide scan gives identical placements — the tensor formulation of
    the reference's LimitIterator candidate bound (stack.go:84-91),
    with exact top-K candidates instead of log2(n) random ones.

    Validity: requires no spread stanzas (spread boosts can RAISE
    non-candidate scores) — callers gate on features.n_spreads == 0.
    The returned ``valid`` scalar is False when the bound was ever
    breached mid-scan (candidate max fell below the rest bound, e.g.
    under the cluster-wide spread fit function, or K exhausted); the
    caller must re-run the full kernel then.

    Returns (KernelOut, valid: bool scalar).
    """
    n = kin.cap_cpu.shape[0]
    f = features
    assert f.n_spreads == 0, "top-K path requires no spread stanzas"
    k_cand = n_candidates or min(n, max(2 * k_steps, k_steps + 8, TOPK))

    init = dict(
        used_cpu=kin.used_cpu,
        used_mem=kin.used_mem,
        used_disk=kin.used_disk,
        job_tg_count=kin.job_tg_count,
    )
    if f.with_cores:
        init["used_cores"] = kin.used_cores
    if f.with_network:
        init["used_mbits"] = kin.used_mbits
    if f.with_ports:
        init["free_dyn"] = kin.free_dyn
        init["port_conflict"] = kin.port_conflict
    if f.with_devices:
        init["dev_free"] = kin.dev_free
    if f.with_distinct:
        init["job_any_count"] = kin.job_any_count

    # ---- one full-width pass: metrics + initial scores ----
    feas0, ask_cpu_total0, dims0 = _feasible(kin, init, f)
    final0 = _score(kin, init, ask_cpu_total0, kin.penalty, f, None)
    masked0 = jnp.where(feas0, final0, NEG_INF)
    base_i = kin.base_mask
    exhausted = lambda fit: jnp.sum(base_i & ~fit).astype(jnp.int32)  # noqa: E731

    # approx_max_k is the TPU-fast selection (lax.top_k is orders
    # slower there); exactness is preserved by computing the rest
    # bound EXACTLY below — a recall miss that would have mattered
    # shows up as a bound breach and falls back to the full kernel
    _, cand_idx = jax.lax.approx_max_k(
        masked0, k_cand, recall_target=0.95)
    rest_max = jnp.max(masked0.at[cand_idx].set(NEG_INF))

    # preferred nodes must be selectable even when outside the top-K:
    # union them into the candidate set (duplicates are harmless --
    # duplicate rows share deductions via scatter-by-node below)
    if f.with_preferred:
        prefs = jnp.clip(kin.step_preferred[:k_steps], 0, n - 1)
        pref_valid = kin.step_preferred[:k_steps] >= 0
        cand_idx = jnp.concatenate([cand_idx, prefs])
        k_all = k_cand + k_steps
        cand_is_pref_pad = jnp.concatenate([
            jnp.zeros(k_cand, bool), ~pref_valid])
    else:
        k_all = k_cand
        cand_is_pref_pad = jnp.zeros(k_cand, bool)

    # tie-break decorrelation within the candidate set: the eval's
    # node permutation provides pseudo-random distinct keys per node,
    # so argsort of the gathered keys is a per-eval random candidate
    # order (shuffleNodes util.go:464, restricted to candidates)
    if f.with_shuffle:
        cand_perm = jnp.argsort(kin.node_perm[cand_idx]).astype(jnp.int32)
    else:
        cand_perm = jnp.arange(k_all, dtype=jnp.int32)

    # ---- gather candidate-width planes ----
    def g(x):
        return x[cand_idx]

    kin_c = KernelIn(
        cap_cpu=g(kin.cap_cpu), cap_mem=g(kin.cap_mem),
        cap_disk=g(kin.cap_disk), free_cores=g(kin.free_cores),
        shares_per_core=g(kin.shares_per_core), free_dyn=g(kin.free_dyn),
        base_mask=g(kin.base_mask) & ~cand_is_pref_pad,
        used_cpu=g(kin.used_cpu), used_mem=g(kin.used_mem),
        used_disk=g(kin.used_disk), used_cores=g(kin.used_cores),
        used_mbits=g(kin.used_mbits), avail_mbits=g(kin.avail_mbits),
        port_conflict=g(kin.port_conflict), dev_free=g(kin.dev_free),
        dev_aff_score=g(kin.dev_aff_score),
        has_dev_affinity=kin.has_dev_affinity,
        job_tg_count=g(kin.job_tg_count), penalty=g(kin.penalty),
        aff_score=g(kin.aff_score),
        node_perm=cand_perm,
        step_penalty=kin.step_penalty, step_preferred=kin.step_preferred,
        job_any_count=g(kin.job_any_count),
        distinct_hosts_job=kin.distinct_hosts_job,
        distinct_hosts_tg=kin.distinct_hosts_tg,
        spread_active=kin.spread_active, spread_even=kin.spread_even,
        spread_weight=kin.spread_weight,
        spread_bucket=kin.spread_bucket[:, :1],
        spread_counts=kin.spread_counts,
        spread_desired=kin.spread_desired,
        ask_cpu=kin.ask_cpu, ask_mem=kin.ask_mem, ask_disk=kin.ask_disk,
        ask_cores=kin.ask_cores, ask_dyn_ports=kin.ask_dyn_ports,
        ask_has_reserved_ports=kin.ask_has_reserved_ports,
        ask_dev=kin.ask_dev, ask_mbits=kin.ask_mbits,
        desired_count=kin.desired_count,
        algorithm_spread=kin.algorithm_spread,
        n_steps=kin.n_steps,
    )

    # duplicate candidate rows (a preferred node also in the top-K)
    # must share deductions: scatter per-step deltas by NODE id and
    # re-gather. same_node[i, j] = cand i and cand j are one node.
    same_node = cand_idx[:, None] == cand_idx[None, :]   # bool[K', K']
    share = same_node.astype(jnp.float32)
    sharei = same_node.astype(jnp.int32)

    init_c = dict(
        used_cpu=kin_c.used_cpu, used_mem=kin_c.used_mem,
        used_disk=kin_c.used_disk, job_tg_count=kin_c.job_tg_count,
    )
    if f.with_cores:
        init_c["used_cores"] = kin_c.used_cores
    if f.with_network:
        init_c["used_mbits"] = kin_c.used_mbits
    if f.with_ports:
        init_c["free_dyn"] = kin_c.free_dyn
        init_c["port_conflict"] = kin_c.port_conflict
    if f.with_devices:
        init_c["dev_free"] = kin_c.dev_free
    if f.with_distinct:
        init_c["job_any_count"] = kin_c.job_any_count

    iota_c = jnp.arange(k_all, dtype=jnp.int32)

    def step(carry, i):
        st, ok = carry
        feasible, ask_cpu_total, _ = _feasible(kin_c, st, f)
        penalty = kin_c.penalty
        if f.with_step_penalties:
            pen_ids = kin_c.step_penalty[i]
            node_ids = cand_idx
            step_pen = jnp.any(
                node_ids[:, None] == pen_ids[None, :], axis=1)
            penalty = penalty | step_pen
        final = _score(kin_c, st, ask_cpu_total, penalty, f, None)
        active = i < kin_c.n_steps
        masked = jnp.where(feasible & active, final, NEG_INF)
        if f.with_shuffle:
            best = kin_c.node_perm[jnp.argmax(masked[kin_c.node_perm])]
        else:
            best = jnp.argmax(masked)
        if f.with_preferred:
            pref = kin_c.step_preferred[i]
            # the preferred node's candidate row: k_cand + i by layout
            pref_row = k_cand + i
            pref_ok = (pref >= 0) & feasible[pref_row] & active
            idx = jnp.where(pref_ok, pref_row, best)
        else:
            pref_ok = jnp.asarray(False)
            idx = best
        found = masked[idx] > NEG_INF / 2
        # bound check: if the best candidate fell below what the rest
        # of the cluster could offer, the candidate set is invalid.
        # Preferred picks are exempt — they are taken regardless of
        # score in the full kernel too, so the bound is irrelevant
        ok = ok & (~active | ~found | pref_ok | (masked[idx] >= rest_max))

        if f.with_topk:
            topv, topi = jax.lax.top_k(masked, TOPK)
            topi = cand_idx[topi]
        else:
            topv = jnp.full(TOPK, NEG_INF)
            topi = jnp.zeros(TOPK, jnp.int32)

        upd = (found & active).astype(jnp.float32)
        updi = (found & active).astype(jnp.int32)
        one = share[idx] * upd          # all rows of the chosen NODE
        onei = sharei[idx] * updi
        st2 = dict(
            used_cpu=st["used_cpu"] + one * ask_cpu_total,
            used_mem=st["used_mem"] + one * kin_c.ask_mem,
            used_disk=st["used_disk"] + one * kin_c.ask_disk,
            job_tg_count=st["job_tg_count"] + onei,
        )
        if f.with_cores:
            st2["used_cores"] = st["used_cores"] + onei * kin_c.ask_cores
        if f.with_network:
            st2["used_mbits"] = st["used_mbits"] + onei * kin_c.ask_mbits
        if f.with_ports:
            st2["free_dyn"] = st["free_dyn"] - onei * kin_c.ask_dyn_ports
            st2["port_conflict"] = st["port_conflict"] | (
                (one > 0) & kin_c.ask_has_reserved_ports)
        if f.with_devices:
            st2["dev_free"] = st["dev_free"] - one[:, None] * kin_c.ask_dev[None, :]
        if f.with_distinct:
            st2["job_any_count"] = st["job_any_count"] + onei
        out = (
            jnp.where(found, cand_idx[idx], -1).astype(jnp.int32),
            jnp.where(found, masked[idx], 0.0),
            found & active,
            topi.astype(jnp.int32),
            topv,
        )
        return (st2, ok), out

    # candidate-width steps are tiny; full unroll removes the scan's
    # per-step sequencing overhead (the remaining cost driver)
    (_, ok), (chosen, scores, found, topk_idx, topk_scores) = jax.lax.scan(
        step, (init_c, jnp.asarray(True)), jnp.arange(k_steps),
        unroll=True,
    )

    out = KernelOut(
        chosen=chosen, scores=scores, found=found,
        topk_idx=topk_idx, topk_scores=topk_scores,
        nodes_evaluated=jnp.sum(base_i).astype(jnp.int32),
        nodes_feasible=jnp.sum(feas0).astype(jnp.int32),
        exhausted_cpu=exhausted(dims0["fit_cpu"]),
        exhausted_mem=exhausted(dims0["fit_mem"]),
        exhausted_disk=exhausted(dims0["fit_disk"]),
        exhausted_ports=exhausted(dims0["fit_ports"]),
        exhausted_devices=exhausted(dims0["fit_dev"]),
        exhausted_cores=exhausted(dims0["fit_cores"]),
    )
    # a run that failed placements while rest_max was still beatable is
    # also invalid (candidates exhausted but the wider cluster might
    # fit); detect: any inactive-step-before-n_steps with rest feasible
    missing = jnp.any(
        (jnp.arange(k_steps) < kin.n_steps) & ~found)
    ok = ok & (~missing | (rest_max <= NEG_INF / 2))
    return out, ok


place_taskgroup_topk_jit = jax.jit(
    place_taskgroup_topk, static_argnums=(1, 2, 3)
)



def _resident_kin(kin: KernelIn) -> KernelIn:
    """Swap shared-plane leaves for their device-resident twins
    (tensors/device_state.py) so the dispatch uploads only genuinely
    per-eval planes. Substitution is ALL-OR-NOTHING across every
    sharing group: the unprofiled path's jit-cache signature is then
    exactly one of TWO layouts — all-host, or all-shared-resident —
    both populated by the AOT warmup (ops/warmup._call_both_
    placements). A partially-resident eval (say, forked job planes)
    falls back to the all-host signature instead of compiling an
    unwarmed commitment combination on the steady hot path."""
    from nomad_tpu.parallel.coalesce import (
        _JOB_SHAREABLE_FIELDS,
        _NEUTRAL_SHAREABLE_FIELDS,
        _SHAREABLE_FIELDS,
    )
    from nomad_tpu.tensors.device_state import default_device_state

    subs = {}
    for group in (_SHAREABLE_FIELDS, _NEUTRAL_SHAREABLE_FIELDS,
                  _JOB_SHAREABLE_FIELDS):
        for f in group:
            dev = default_device_state.lookup(
                getattr(kin, f),
                frozen_ok=group is not _SHAREABLE_FIELDS)
            if dev is None:
                return kin
            subs[f] = dev
    return kin._replace(**subs)


def default_kernel_launch(kin: KernelIn, k_steps: int,
                          features: KernelFeatures) -> KernelOut:
    """The stack's direct (non-coalesced) dispatch: candidate-set fast
    path when its preconditions hold, full-width kernel otherwise or on
    a bound breach.

    Profiled like coalesced waves (telemetry/kernel_profile.py): the
    single-eval path compiles its own (node-pad, step-bucket, features)
    variants, and an un-instrumented fallback here would let recompiles
    hide outside the wave accounting."""
    from nomad_tpu.telemetry.kernel_profile import profiler

    features = canonical_features(features)
    n_pad = int(np.asarray(kin.cap_cpu).shape[0])
    kin = _resident_kin(kin)
    key = (n_pad, k_steps, features)
    if features.n_spreads == 0 and not bool(kin.algorithm_spread):
        out, ok = profiler.call(
            "single_topk", place_taskgroup_topk_jit, (kin,),
            (k_steps, features), key, jit_fn=place_taskgroup_topk_jit)
        if bool(ok):
            return out
    return profiler.call(
        "single_full", place_taskgroup_jit, (kin,),
        (k_steps, features), key, jit_fn=place_taskgroup_jit)


class JointOut(NamedTuple):
    """Outputs of a joint wave: per-step placements + per-member metrics."""

    chosen: jnp.ndarray          # i32[T]
    scores: jnp.ndarray          # f32[T]
    found: jnp.ndarray           # bool[T]
    topk_idx: jnp.ndarray        # i32[T, TOPK]
    topk_scores: jnp.ndarray     # f32[T, TOPK]
    nodes_evaluated: jnp.ndarray     # i32[B]
    nodes_feasible: jnp.ndarray      # i32[B]
    exhausted_cpu: jnp.ndarray       # i32[B]
    exhausted_mem: jnp.ndarray
    exhausted_disk: jnp.ndarray
    exhausted_ports: jnp.ndarray
    exhausted_devices: jnp.ndarray
    exhausted_cores: jnp.ndarray
    # final shared-capacity carry: total resources the wave consumed
    # per node (lets a caller commit the wave as one scatter)
    a_cpu: jnp.ndarray               # f32[N]
    a_mem: jnp.ndarray               # f32[N]
    a_disk: jnp.ndarray              # f32[N]


def place_taskgroups_joint(
    kin: KernelIn,
    step_member: jnp.ndarray,
    step_local: jnp.ndarray,
    t_steps: int,
    features: KernelFeatures = FULL_FEATURES,
) -> JointOut:
    """Place a WAVE of task-group asks with a shared capacity carry.

    ``kin`` is a stacked KernelIn (leading member axis B). The scan
    runs ``t_steps`` placement steps; step t belongs to wave member
    ``step_member[t]`` (-1 = padding) at member-local placement index
    ``step_local[t]``.

    This is the on-device form of the leader's serialized plan applier
    (nomad/plan_apply.go:71): every step's feasibility and score see
    the capacity consumed by ALL previous steps — including other
    members' — via shared accumulation planes (cpu/mem/disk, cores,
    bandwidth, dynamic-port counts, device counts). Job-local planes
    (anti-affinity counts, distinct-hosts counts, spread counts, the
    member's own reserved-port conflicts) stay per-member, because
    they only constrain the member's own job. Concurrently scheduled
    evaluations therefore cannot over-subscribe a node within a batch,
    which is what keeps the optimistic plan re-validation
    (plan_apply.go:644) from rejecting lockstep retries.

    Cross-member *identity* conflicts (the same reserved port number
    or the same reserved core id chosen by two members for one node)
    are not modeled on device — exact port/core assignment stays
    host-side and the applier's re-check catches the rare collision,
    exactly as it does between reference scheduler workers.
    """
    n = kin.cap_cpu.shape[-1]
    b = kin.n_steps.shape[0]       # n_steps is always member-stacked
    f = features

    def _bat(x, rank):
        """Ensure a leading member axis (carried leaves need one even
        when the wave shipped the leaf shared/unbatched — the broadcast
        happens ON DEVICE, costing HBM, not transport)."""
        if jnp.ndim(x) == rank + 1:
            return x
        return jnp.broadcast_to(x, (b,) + jnp.shape(x))

    zf = jnp.zeros(n, jnp.float32)
    zi = jnp.zeros(n, jnp.int32)
    init = dict(
        a_cpu=zf, a_mem=zf, a_disk=zf,
        job_tg_count=_bat(kin.job_tg_count, 1),     # [B, N]
    )
    if f.with_cores:
        init["a_cores"] = zi
    if f.with_network:
        init["a_mbits"] = zi
    if f.with_ports:
        init["a_dyn"] = zi
        init["port_conflict"] = _bat(kin.port_conflict, 1)   # [B, N]
    if f.with_devices:
        init["a_dev"] = jnp.zeros((n, kin.dev_free.shape[-1]), jnp.float32)
    if f.with_distinct:
        init["job_any_count"] = _bat(kin.job_any_count, 1)   # [B, N]
    if f.n_spreads > 0:
        init["spread_counts"] = _bat(kin.spread_counts, 2)   # [B, S, Bk]

    iota = jnp.arange(n, dtype=jnp.int32)

    def member_view(st, m):
        """The member's single-problem (kin, st) as place_taskgroup
        sees it. Leaves shipped unbatched (shared by every member) are
        used as-is; stacked leaves index the member axis."""
        kin_m = KernelIn(*[
            x[m] if jnp.ndim(x) == r + 1 else x
            for x, r in zip(kin, KIN_UNBATCHED_RANKS)
        ])
        st_m = dict(
            used_cpu=kin_m.used_cpu + st["a_cpu"],
            used_mem=kin_m.used_mem + st["a_mem"],
            used_disk=kin_m.used_disk + st["a_disk"],
            job_tg_count=st["job_tg_count"][m],
        )
        if f.with_cores:
            st_m["used_cores"] = kin_m.used_cores + st["a_cores"]
        if f.with_network:
            st_m["used_mbits"] = kin_m.used_mbits + st["a_mbits"]
        if f.with_ports:
            st_m["free_dyn"] = kin_m.free_dyn - st["a_dyn"]
            st_m["port_conflict"] = st["port_conflict"][m]
        if f.with_devices:
            st_m["dev_free"] = kin_m.dev_free - st["a_dev"]
        if f.with_distinct:
            st_m["job_any_count"] = st["job_any_count"][m]
        if f.n_spreads > 0:
            st_m["spread_counts"] = st["spread_counts"][m]
        return kin_m, st_m

    def step(st, t):
        member = step_member[t]
        active_step = member >= 0
        m = jnp.clip(member, 0, b - 1)
        j = step_local[t]
        kin_m, st_m = member_view(st, m)

        feasible, ask_cpu_total, _ = _feasible(kin_m, st_m, f)
        penalty = kin_m.penalty
        if f.with_step_penalties:
            pen_ids = kin_m.step_penalty[j]
            step_pen = jnp.any(iota[:, None] == pen_ids[None, :], axis=1)
            penalty = penalty | step_pen
        spread_onehot = None
        if f.n_spreads > 0:
            sb = kin_m.spread_bucket[:f.n_spreads]
            spread_onehot = (
                jax.nn.one_hot(jnp.clip(sb, 0, SPREAD_BUCKETS - 1),
                               SPREAD_BUCKETS, dtype=jnp.float32)
                * (sb >= 0)[..., None]
            )
        final = _score(kin_m, st_m, ask_cpu_total, penalty, f, spread_onehot)
        active = active_step & (j < kin_m.n_steps)
        masked = jnp.where(feasible & active, final, NEG_INF)
        if f.with_shuffle:
            best = kin_m.node_perm[jnp.argmax(masked[kin_m.node_perm])]
        else:
            best = jnp.argmax(masked)
        if f.with_preferred:
            pref = kin_m.step_preferred[j]
            pref_ok = (pref >= 0) & feasible[jnp.clip(pref, 0, n - 1)] & active
            idx = jnp.where(pref_ok, jnp.clip(pref, 0, n - 1), best)
        else:
            idx = best
        found = masked[idx] > NEG_INF / 2

        if f.with_topk:
            topv, topi = jax.lax.top_k(masked, TOPK)
        else:
            topv = jnp.full(TOPK, NEG_INF)
            topi = jnp.zeros(TOPK, jnp.int32)

        upd = (found & active).astype(jnp.float32)
        updi = (found & active).astype(jnp.int32)
        one = jax.nn.one_hot(idx, n, dtype=jnp.float32) * upd
        onei = jax.nn.one_hot(idx, n, dtype=jnp.int32) * updi
        st2 = dict(
            a_cpu=st["a_cpu"] + one * ask_cpu_total,
            a_mem=st["a_mem"] + one * kin_m.ask_mem,
            a_disk=st["a_disk"] + one * kin_m.ask_disk,
            job_tg_count=st["job_tg_count"].at[m].add(onei),
        )
        if f.with_cores:
            st2["a_cores"] = st["a_cores"] + onei * kin_m.ask_cores
        if f.with_network:
            st2["a_mbits"] = st["a_mbits"] + onei * kin_m.ask_mbits
        if f.with_ports:
            st2["a_dyn"] = st["a_dyn"] + onei * kin_m.ask_dyn_ports
            st2["port_conflict"] = st["port_conflict"].at[m].set(
                st["port_conflict"][m]
                | ((one > 0) & kin_m.ask_has_reserved_ports)
            )
        if f.with_devices:
            st2["a_dev"] = st["a_dev"] + one[:, None] * kin_m.ask_dev[None, :]
        if f.with_distinct:
            st2["job_any_count"] = st["job_any_count"].at[m].add(onei)
        if f.n_spreads > 0:
            st2["spread_counts"] = st["spread_counts"].at[m].set(
                _bump_spread(kin_m, st["spread_counts"][m], one,
                             spread_onehot, f.n_spreads)
            )
        out = (
            jnp.where(found, idx, -1).astype(jnp.int32),
            jnp.where(found, masked[idx], 0.0),
            found & active,
            topi.astype(jnp.int32),
            topv,
        )
        return st2, out

    st_final, (chosen, scores, found, topk_idx, topk_scores) = jax.lax.scan(
        step, init, jnp.arange(t_steps)
    )

    # per-member first-step metrics (AllocMetric inputs), from the
    # pre-wave state — identical to the single-problem kernel's
    def member_metrics(kin_m: KernelIn):
        st0 = dict(
            used_cpu=kin_m.used_cpu, used_mem=kin_m.used_mem,
            used_disk=kin_m.used_disk, job_tg_count=kin_m.job_tg_count,
            used_cores=kin_m.used_cores, used_mbits=kin_m.used_mbits,
            free_dyn=kin_m.free_dyn, port_conflict=kin_m.port_conflict,
            dev_free=kin_m.dev_free, job_any_count=kin_m.job_any_count,
            spread_counts=kin_m.spread_counts,
        )
        feas0, _, dims0 = _feasible(kin_m, st0, f)
        base_i = kin_m.base_mask
        ex = lambda fit: jnp.sum(base_i & ~fit).astype(jnp.int32)  # noqa: E731
        return (
            jnp.sum(base_i).astype(jnp.int32),
            jnp.sum(feas0).astype(jnp.int32),
            ex(dims0["fit_cpu"]), ex(dims0["fit_mem"]), ex(dims0["fit_disk"]),
            ex(dims0["fit_ports"]), ex(dims0["fit_dev"]), ex(dims0["fit_cores"]),
        )

    in_axes = KernelIn(*[
        0 if jnp.ndim(x) == r + 1 else None
        for x, r in zip(kin, KIN_UNBATCHED_RANKS)
    ])
    (m_eval, m_feas, m_cpu, m_mem, m_disk, m_ports, m_dev, m_cores) = jax.vmap(
        member_metrics, in_axes=(in_axes,))(kin)

    return JointOut(
        chosen=chosen, scores=scores, found=found,
        topk_idx=topk_idx, topk_scores=topk_scores,
        nodes_evaluated=m_eval, nodes_feasible=m_feas,
        exhausted_cpu=m_cpu, exhausted_mem=m_mem, exhausted_disk=m_disk,
        exhausted_ports=m_ports, exhausted_devices=m_dev,
        exhausted_cores=m_cores,
        a_cpu=st_final["a_cpu"], a_mem=st_final["a_mem"],
        a_disk=st_final["a_disk"],
    )


place_taskgroups_joint_jit = jax.jit(
    place_taskgroups_joint, static_argnums=(3, 4)
)


# ---------------------------------------------------------------------------
# Fused wave dispatch (ISSUE 19): ONE device program per wave.
#
# The composite path above costs two wave-critical device interactions
# per launch: the joint program execution, then an eager per-field
# fetch of eleven separate output buffers. The fused variant runs the
# same scan as a single Pallas program (ops/pallas_kernel.fused_wave
# _place — interpret mode off-TPU so CPU tier-1 exercises the exact
# program) and PACKS everything the launcher fetches eagerly into one
# flat f32 buffer, so steady state is one dispatch and one readback
# that rides the dispatch's own synchronization. The top-k planes stay
# separate device outputs — they are lazy (_WaveTopK) and drain in the
# plan window, off the wave-critical path.
# ---------------------------------------------------------------------------

#: JointOut metric fields in packed-segment order (8 x [B] after the
#: two [T] rows). Single source of truth for pack (device) and unpack
#: (host) — a drift here would hand members another member's metrics.
FUSED_METRIC_FIELDS = (
    "nodes_evaluated", "nodes_feasible",
    "exhausted_cpu", "exhausted_mem", "exhausted_disk",
    "exhausted_ports", "exhausted_devices", "exhausted_cores",
)


class FusedWaveOut(NamedTuple):
    """One fused wave's device outputs.

    ``packed`` is flat f32[2*T + 8*B]: ``[0:T)`` chosen (exact as f32
    — node ids are far below 2**24; ``found`` is NOT packed because
    it is definitionally ``chosen >= 0``), ``[T:2T)`` scores, then the
    eight B-wide metric segments in FUSED_METRIC_FIELDS order. 8T+32B
    bytes — strictly below the composite's eager fetch (9T+32B), so
    fusing never regresses d2h-per-wave."""

    packed: jnp.ndarray          # f32[2*T + 8*B]
    topk_idx: jnp.ndarray        # i32[T, TOPK]
    topk_scores: jnp.ndarray     # f32[T, TOPK]
    a_cpu: jnp.ndarray           # f32[N] final shared-capacity carry
    a_mem: jnp.ndarray           # f32[N]
    a_disk: jnp.ndarray          # f32[N]


def fused_wave_supported(f: KernelFeatures) -> bool:
    """Whether a wave's (canonical) feature union fits the fused
    mega-kernel's envelope. Ports, preemption penalties, preferred
    pins, distinct_hosts, shuffle, and top-k are all in (shuffle is
    ALWAYS on for live evals — scheduler/generic.py seeds it per
    eval, so excluding it would turn every live wave into a counted
    fallback). Spread stanzas and the device/core/bandwidth planes
    are out: rare in steady traffic and each would widen the fused
    signature lattice ~2x — those waves take the composite path,
    counted by ``fused_wave_stats``."""
    return (f.n_spreads == 0 and not f.with_devices
            and not f.with_cores and not f.with_network)


def fused_pack_len(t_steps: int, b: int) -> int:
    return 2 * t_steps + 8 * b


def pack_fused_wave(out: JointOut, t_steps: int, b: int) -> jnp.ndarray:
    """Pack a JointOut's eagerly-fetched planes into the flat f32
    buffer (device side; see FusedWaveOut.packed layout)."""
    parts = [out.chosen.astype(jnp.float32), out.scores]
    parts += [getattr(out, name).astype(jnp.float32)
              for name in FUSED_METRIC_FIELDS]
    return jnp.concatenate(parts)


def unpack_fused_wave(packed: np.ndarray, t_steps: int, b: int) -> dict:
    """Host-side inverse of ``pack_fused_wave``: the launcher's eager
    fetch dict (same keys as coalesce._JOINT_FETCH_FIELDS, same
    dtypes as the composite's per-field ``np.asarray`` fetch)."""
    flat = np.asarray(packed)
    chosen = flat[:t_steps].astype(np.int32)
    host = {
        "chosen": chosen,
        "scores": flat[t_steps:2 * t_steps].astype(np.float32),
        "found": chosen >= 0,
    }
    off = 2 * t_steps
    for name in FUSED_METRIC_FIELDS:
        host[name] = flat[off:off + b].astype(np.int32)
        off += b
    return host


def fused_wave_launch(kin: KernelIn, step_member, step_local,
                      t_steps: int, features: KernelFeatures,
                      key: tuple) -> FusedWaveOut:
    """Single-device fused dispatch: ONE profiled Pallas program per
    wave, selected per bucket key exactly like the composite (the
    profiler's miss counter and the AOT warmup manifest both see it
    as the "fused_wave" kernel)."""
    from nomad_tpu.ops.pallas_kernel import fused_wave_place_jit
    from nomad_tpu.telemetry.kernel_profile import profiler

    return profiler.call(
        "fused_wave", fused_wave_place_jit,
        (kin, jnp.asarray(step_member), jnp.asarray(step_local)),
        (t_steps, features), key, jit_fn=fused_wave_place_jit,
    )


def infer_features(ev, any_penalty: bool = True, any_preferred: bool = True,
                   with_topk: bool = True, with_shuffle: bool = False) -> KernelFeatures:
    """Derive the lean static variant for one EvalTensors' ask."""
    ask = ev.ask
    return KernelFeatures(
        n_spreads=len(ev.spreads),
        with_topk=with_topk,
        with_devices=bool(ask.n_dev_reqs > 0 or ev.has_dev_affinity),
        with_ports=bool(ask.n_dyn_ports > 0 or ask.reserved_ports),
        with_cores=bool(ask.cores > 0),
        with_network=bool(ask.total_mbits > 0),
        with_distinct=bool(ev.distinct_hosts_job or ev.distinct_hosts_tg),
        with_step_penalties=bool(any_penalty),
        with_preferred=bool(any_preferred),
        with_shuffle=bool(with_shuffle),
    )


def build_kernel_in(
    cluster: ClusterTensors,
    ev: EvalTensors,
    n_steps: int,
    step_penalty: Optional[np.ndarray] = None,
    step_preferred: Optional[np.ndarray] = None,
    node_perm: Optional[np.ndarray] = None,
) -> KernelIn:
    """Assemble device inputs from the host-side tensor schema.

    ``step_penalty``/``step_preferred`` are per-placement planes sized to
    the padded step count (``pad_steps(n_steps)``); None means no
    penalties/preferences. ``node_perm`` is the seeded tie-break
    permutation (identity when shuffling is off).
    """
    from nomad_tpu.tensors.schema import AskLimitError

    S, N = MAX_SPREADS, cluster.n_pad
    if len(ev.spreads) > S:
        raise AskLimitError(
            f"task group has {len(ev.spreads)} spread stanzas; kernel "
            f"supports {S}"
        )
    neutral = neutral_planes(N)
    if ev.spreads:
        sp_active = np.zeros(S, bool)
        sp_even = np.zeros(S, bool)
        sp_weight = np.zeros(S, np.float32)
        sp_bucket = np.full((S, N), -1, np.int32)
        sp_counts = np.zeros((S, SPREAD_BUCKETS), np.float32)
        sp_desired = np.full((S, SPREAD_BUCKETS), -1.0, np.float32)
        for s, sp in enumerate(ev.spreads[:S]):
            sp_active[s] = True
            sp_even[s] = sp.even
            sp_weight[s] = sp.weight_frac
            sp_bucket[s] = sp.bucket_id
            sp_counts[s] = sp.counts
            sp_desired[s] = sp.desired
    else:
        # frozen singletons: identity-shared across wave members
        sp_active = sp_even = neutral.zeros_spread_flags
        sp_weight = neutral.zeros_spread_weight
        sp_bucket = neutral.neg1_spread_bucket
        sp_counts = neutral.zeros_spread_counts
        sp_desired = neutral.neg1_spread_desired

    # reserved-port conflict: ask bits already set in node planes or the
    # in-plan conflict words
    if ev.ask.reserved_ports:
        words = cluster.port_words | ev.port_conflict_words
        conflict = np.any(words & ev.ask.port_mask[None, :], axis=1)
        if ev.port_live_conflict is not None:
            # live-alloc port occupancy (usage-index bitmaps): the
            # node plane only carries agent-reserved ports
            conflict = conflict | ev.port_live_conflict
        has_res = True
    else:
        conflict = neutral.zeros_bool
        has_res = False

    k_pad = pad_steps(n_steps)
    if step_penalty is None or step_preferred is None:
        np_pen, np_pref = neutral_step_planes(k_pad)
        if step_penalty is None:
            step_penalty = np_pen
        if step_preferred is None:
            step_preferred = np_pref
    if node_perm is None:
        node_perm = neutral.arange_i32

    # leaves stay NUMPY: jit uploads each argument once at call time.
    # Building device arrays here would mean one host->device transfer
    # per field per evaluation (and per wave member when coalescing) —
    # on a remote-device transport every transfer is a round trip.
    return KernelIn(
        cap_cpu=np.asarray(cluster.cap_cpu, np.float32),
        cap_mem=np.asarray(cluster.cap_mem, np.float32),
        cap_disk=np.asarray(cluster.cap_disk, np.float32),
        free_cores=np.asarray(cluster.free_cores, np.int32),
        shares_per_core=np.asarray(cluster.shares_per_core, np.float32),
        # identity-preserving when no in-plan dyn ports: wave members
        # then share the cluster's plane (shipped once per wave)
        free_dyn=(np.asarray(cluster.free_dyn, np.int32)
                  if not ev.free_dyn_delta.any()
                  else np.asarray(cluster.free_dyn - ev.free_dyn_delta,
                                  np.int32)),
        base_mask=np.asarray(ev.base_mask, bool),
        used_cpu=np.asarray(ev.used_cpu, np.float32),
        used_mem=np.asarray(ev.used_mem, np.float32),
        used_disk=np.asarray(ev.used_disk, np.float32),
        used_cores=np.asarray(ev.used_cores, np.int32),
        used_mbits=np.asarray(ev.used_mbits, np.int32),
        avail_mbits=np.asarray(ev.avail_mbits, np.int32),
        port_conflict=np.asarray(conflict, bool),
        dev_free=np.asarray(ev.dev_free, np.float32),
        dev_aff_score=np.asarray(ev.dev_aff_score, np.float32),
        has_dev_affinity=np.asarray(ev.has_dev_affinity, bool),
        job_tg_count=np.asarray(ev.job_tg_count, np.int32),
        penalty=np.asarray(ev.penalty, bool),
        aff_score=np.asarray(ev.aff_score, np.float32),
        node_perm=np.asarray(node_perm, np.int32),
        step_penalty=np.asarray(step_penalty, np.int32),
        step_preferred=np.asarray(step_preferred, np.int32),
        job_any_count=np.asarray(ev.job_any_count, np.int32),
        distinct_hosts_job=np.asarray(ev.distinct_hosts_job, bool),
        distinct_hosts_tg=np.asarray(ev.distinct_hosts_tg, bool),
        spread_active=np.asarray(sp_active, bool),
        spread_even=np.asarray(sp_even, bool),
        spread_weight=np.asarray(sp_weight, np.float32),
        spread_bucket=np.asarray(sp_bucket, np.int32),
        spread_counts=np.asarray(sp_counts, np.float32),
        spread_desired=np.asarray(sp_desired, np.float32),
        ask_cpu=np.asarray(ev.ask.cpu, np.float32),
        ask_mem=np.asarray(ev.ask.mem, np.float32),
        ask_disk=np.asarray(ev.ask.disk, np.float32),
        ask_cores=np.asarray(ev.ask.cores, np.int32),
        ask_dyn_ports=np.asarray(ev.ask.n_dyn_ports, np.int32),
        ask_has_reserved_ports=np.asarray(has_res, bool),
        ask_dev=np.asarray(ev.ask.dev_counts, np.float32),
        ask_mbits=np.asarray(ev.ask.total_mbits, np.int32),
        desired_count=np.asarray(ev.desired_count, np.int32),
        algorithm_spread=np.asarray(ev.algorithm == "spread", bool),
        n_steps=np.asarray(n_steps, np.int32),
    )
