"""AOT kernel warmup: precompile the live path's placement kernels.

PR 1's TRACE_DECOMP made the live-path gap a measurement: jit
``compile`` was 50% of per-eval wall, one miss per
(wave, nodes, steps, features) bucket key. The buckets exist precisely
so the variant set is small and enumerable — which means a server can
compile all of them BEFORE the first evaluation ever needs one,
instead of paying each cold compile inside a scheduling deadline.

The enumeration is driven by a **warmup manifest**: the bucket keys a
production server actually launched, persisted from the kernel
profiler's per-key stats (telemetry/kernel_profile.py). At startup
(Server.start, background thread) the manifest replays as ahead-of-time
compilations of the ``joint`` wave kernel and the ``single_topk`` /
``single_full`` direct kernels against neutral dummy planes of the
recorded shapes — populating the exact jit caches the live launches
hit (and, transitively, the persistent XLA compilation cache, so the
cost is once per machine, not once per process).

``expand_lattice`` widens a manifest downward over the wave-bucket
axis: tail waves (a partial batch, a deadline-fired wave) use smaller
buckets than the steady state, and those are exactly the variants a
steady-state-derived manifest would otherwise miss.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

LOG = logging.getLogger(__name__)

MANIFEST_VERSION = 1

#: default manifest location (overridable per server via
#: ServerConfig.warmup_manifest_path / agent config `warmup_manifest`)
DEFAULT_MANIFEST_PATH = os.path.join(
    os.path.expanduser("~"), ".cache", "nomad_tpu_warmup.json")


def _features_to_dict(f) -> Dict:
    return dict(f._asdict())


def _features_from_dict(d: Dict):
    from nomad_tpu.ops.kernel import KernelFeatures

    return KernelFeatures(**{k: v for k, v in d.items()
                             if k in KernelFeatures._fields})


def manifest_from_profiler(profiler=None) -> List[Dict]:
    """Flatten the kernel profiler's observed (kernel, bucket-key)
    launches into JSON-able manifest entries. Sharded-wave keys fold
    into the SAME mesh-agnostic joint entries (their trailing devices
    tuple dropped): the compiled program is mesh-specific, but the
    bucket lattice a mesh server observes is exactly what the next
    start must precompile — unsharded always, sharded again once its
    own mesh probe lands (warmup_entries' ``mesh``)."""
    if profiler is None:
        from nomad_tpu.telemetry.kernel_profile import profiler as _p

        profiler = _p
    entries: List[Dict] = []
    for kernel, key in profiler.keys():
        try:
            if kernel == "joint_sharded" and len(key) == 8:
                # (joint 7-key, devices-tuple): mesh-agnostic manifest
                kernel, key = "joint", key[:7]
            # fused program keys (ISSUE 19) fold into the SAME joint
            # entries: the fused launcher reuses the wave bucket key
            # verbatim, and warmup_entries re-derives "also compile
            # the fused variant" from the entry's feature envelope
            # (fused_wave_supported) — so one manifest line covers
            # composite, sharded, fused, and fused-sharded
            if kernel == "fused_wave_sharded" and len(key) == 8:
                kernel, key = "joint", key[:7]
            if kernel == "fused_wave" and len(key) == 7:
                kernel = "joint"
            if kernel == "joint" and len(key) in (6, 7):
                # len 6: pre-job-group keys from persisted manifests
                # (job_shared defaults True, the common layout)
                b_pad, t_pad, n_nodes, shared, neutral_shared = key[:5]
                job_shared = key[5] if len(key) == 7 else True
                feats = key[-1]
                entries.append({
                    "kernel": "joint",
                    "wave": int(b_pad), "steps": int(t_pad),
                    "nodes": int(n_nodes),
                    "shared": bool(shared),
                    "neutral_shared": bool(neutral_shared),
                    "job_shared": bool(job_shared),
                    "features": _features_to_dict(feats),
                })
            elif kernel in ("single_topk", "single_full") and len(key) == 3:
                n_pad, k_steps, feats = key
                entries.append({
                    "kernel": kernel,
                    "nodes": int(n_pad), "steps": int(k_steps),
                    "features": _features_to_dict(feats),
                })
        except Exception:                       # noqa: BLE001
            continue
    return _dedupe(entries)


def _entry_key(e: Dict) -> Tuple:
    return (e.get("kernel"), e.get("wave"), e.get("steps"),
            e.get("nodes"), e.get("shared"), e.get("neutral_shared"),
            e.get("job_shared", True),
            tuple(sorted((e.get("features") or {}).items())))


def _dedupe(entries: List[Dict]) -> List[Dict]:
    seen = set()
    out = []
    for e in entries:
        k = _entry_key(e)
        if k not in seen:
            seen.add(k)
            out.append(e)
    return out


def load_manifest(path: str) -> List[Dict]:
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        return list(data.get("entries", []))
    return list(data)


def save_manifest(entries: List[Dict], path: str,
                  merge: bool = True) -> int:
    """Persist ``entries`` (unioned with any existing manifest when
    ``merge``): the bucket lattice a deployment accumulates over
    restarts is the set worth precompiling. Returns the entry count
    written. Best-effort atomic (write + rename)."""
    if merge and os.path.exists(path):
        try:
            entries = list(load_manifest(path)) + list(entries)
        except Exception:                       # noqa: BLE001
            pass
    entries = _dedupe(entries)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"version": MANIFEST_VERSION, "entries": entries},
                  f, indent=2)
        f.write("\n")
    os.replace(tmp, path)
    return len(entries)


def expand_lattice(entries: List[Dict],
                   max_wave: Optional[int] = None) -> List[Dict]:
    """Widen joint-wave entries across the bucket lattice a steady
    state reaches from an observed variant:

    - wave axis: every smaller wave bucket (tail / deadline-fired
      partial waves), and — given ``max_wave``, e.g. the worker's
      padded batch size — larger buckets up to the full wave;
    - step axis: every step bucket from the live floor
      (MIN_STEP_BUCKET) up to the observed one — follow-up evals
      placing a job's leftovers launch with fewer steps;
    - layout axis: ALL four sharing layouts for multi-member waves
      (shared x neutral-shared; a retry member forks either group
      independently — a refreshed snapshot stacks the cluster planes,
      a follow-up eval's live-alloc counts stack the neutral group)
      and the fully-shared layout for 1-waves;
    - feature axis: the rescheduling variant (step penalties +
      preferred pins travel together post-canonicalization) —
      follow-up evals for failed allocs carry penalty nodes;
    - plus the direct-dispatch ``single_topk``/``single_full``
      programs a 1-eval batch launches."""
    from nomad_tpu.ops.kernel import MIN_STEP_BUCKET, pad_steps
    from nomad_tpu.parallel.coalesce import _WAVE_BUCKETS, pad_wave

    out = list(entries)
    for e in entries:
        if e.get("kernel") != "joint":
            continue
        b_pad = int(e["wave"])
        ceiling = max(b_pad, pad_wave(max_wave) if max_wave else 0)
        k_max = max(int(e["steps"]) // max(b_pad, 1), 1)
        k_buckets = sorted({pad_steps(k_max),
                            *(b for b in range(1, k_max + 1)
                              if b == pad_steps(b)
                              and b >= MIN_STEP_BUCKET)})
        feats_variants = [dict(e["features"])]
        aux = dict(e["features"])
        if not (aux.get("with_step_penalties")
                and aux.get("with_preferred")):
            aux["with_step_penalties"] = True
            aux["with_preferred"] = True
            feats_variants.append(aux)
        for feats in feats_variants:
            for k in k_buckets:
                for w in _WAVE_BUCKETS:
                    if w > ceiling:
                        continue
                    base = {**e, "features": feats, "wave": w,
                            "steps": pad_steps(w * k)}
                    if w == 1:
                        # a lone member shares every field with
                        # itself: 1-waves ALWAYS take the fully-shared
                        # layout
                        out.append({**base, "shared": True,
                                    "neutral_shared": True,
                                    "job_shared": True})
                    else:
                        # every sharing layout: a member with a
                        # refreshed snapshot stacks the cluster group,
                        # a follow-up eval's live-alloc counts stack
                        # the job group, a device/spread ask stacks
                        # the wide neutral group, a partial-commit
                        # retry stacks them all — each combination is
                        # its own compiled variant the steady state
                        # can hit
                        for sh in (True, False):
                            for ns in (True, False):
                                for js in (True, False):
                                    out.append({
                                        **base, "shared": sh,
                                        "neutral_shared": ns,
                                        "job_shared": js})
                # an eval in a 1-eval batch dispatches DIRECTLY
                # (ops/kernel.default_kernel_launch) with the same
                # shapes and features a wave member would ship
                out.append({"kernel": "single_topk",
                            "nodes": int(e["nodes"]), "steps": k,
                            "features": feats})
                out.append({"kernel": "single_full",
                            "nodes": int(e["nodes"]), "steps": k,
                            "features": feats})
    return _dedupe(out)


# --- dummy-plane construction ----------------------------------------


def _dummy_kin(n: int, k_pad: int):
    """A neutral KernelIn with build_kernel_in's exact dtypes/shapes —
    the jit cache keys on (shape, dtype), so fidelity here is what
    makes the warmup compile THE program the live launch reuses."""
    from nomad_tpu.ops.kernel import (
        KernelIn,
        neutral_planes,
        neutral_step_planes,
    )
    from nomad_tpu.tensors.schema import (
        MAX_DEV_REQS,
        MAX_SPREADS,
        SPREAD_BUCKETS,
    )

    neutral = neutral_planes(n)
    pen, pref = neutral_step_planes(k_pad)
    return KernelIn(
        cap_cpu=neutral.zeros_f32, cap_mem=neutral.zeros_f32,
        cap_disk=neutral.zeros_f32,
        free_cores=neutral.zeros_i32,
        shares_per_core=neutral.zeros_f32,
        free_dyn=neutral.zeros_i32,
        base_mask=neutral.zeros_bool,
        used_cpu=neutral.zeros_f32, used_mem=neutral.zeros_f32,
        used_disk=neutral.zeros_f32,
        used_cores=neutral.zeros_i32, used_mbits=neutral.zeros_i32,
        avail_mbits=neutral.zeros_i32,
        port_conflict=neutral.zeros_bool,
        dev_free=neutral.zeros_dev,
        dev_aff_score=neutral.zeros_f32,
        has_dev_affinity=np.asarray(False, bool),
        job_tg_count=neutral.zeros_i32,
        penalty=neutral.zeros_bool,
        aff_score=neutral.zeros_f32,
        node_perm=neutral.arange_i32,
        step_penalty=pen, step_preferred=pref,
        job_any_count=neutral.zeros_i32,
        distinct_hosts_job=np.asarray(False, bool),
        distinct_hosts_tg=np.asarray(False, bool),
        spread_active=neutral.zeros_spread_flags,
        spread_even=neutral.zeros_spread_flags,
        spread_weight=neutral.zeros_spread_weight,
        spread_bucket=neutral.neg1_spread_bucket,
        spread_counts=neutral.zeros_spread_counts,
        spread_desired=neutral.neg1_spread_desired,
        ask_cpu=np.asarray(0.0, np.float32),
        ask_mem=np.asarray(0.0, np.float32),
        ask_disk=np.asarray(0.0, np.float32),
        ask_cores=np.asarray(0, np.int32),
        ask_dyn_ports=np.asarray(0, np.int32),
        ask_has_reserved_ports=np.asarray(False, bool),
        ask_dev=np.zeros(MAX_DEV_REQS, np.float32),
        ask_mbits=np.asarray(0, np.int32),
        desired_count=np.asarray(1, np.int32),
        algorithm_spread=np.asarray(False, bool),
        n_steps=np.asarray(0, np.int32),
    )


def _call_both_placements(fn, arrays: tuple, statics: tuple,
                          mixed=None) -> None:
    """Populate EVERY jit-cache entry a live launch can hit: the
    kernel profiler device_puts its arguments (committed arrays) while
    the unprofiled path passes host numpy (uncommitted) — jax keys its
    jit cache on commitment, so these are distinct entries over one
    XLA program (the second trace re-hits the compilation cache).

    ``mixed`` (a KernelIn of bools, or None) warms a THIRD signature:
    the unprofiled path with the device-resident cluster state active
    (tensors/device_state.py) passes committed device arrays for the
    shared leaves and host numpy for the rest — commitment follows the
    wave layout flags exactly, so one extra variant per entry covers
    it."""
    import jax

    out = fn(*jax.device_put(arrays), *statics)
    jax.block_until_ready(out)
    out = fn(*arrays, *statics)
    jax.block_until_ready(out)
    if mixed is not None and any(mixed):
        kin = arrays[0]
        kin = kin._replace(**{
            f: jax.device_put(getattr(kin, f))
            for f, m in zip(kin._fields, mixed) if m
        })
        out = fn(kin, *arrays[1:], *statics)
        jax.block_until_ready(out)


def _warm_joint(e: Dict) -> bool:
    import jax.numpy as jnp

    from nomad_tpu.ops.kernel import KernelIn, place_taskgroups_joint_jit
    from nomad_tpu.parallel.coalesce import wave_field_is_shared

    b_pad = int(e["wave"])
    t_pad = int(e["steps"])
    n = int(e["nodes"])
    shared = bool(e.get("shared", True))
    neutral_shared = bool(e.get("neutral_shared", True))
    job_shared = bool(e.get("job_shared", True))
    feats = _features_from_dict(e["features"])
    k_max = max(t_pad // max(b_pad, 1), 1)
    kin = _dummy_kin(n, k_max)

    def stack_field(f, x):
        # the layout predicate is SHARED with launch_wave: the jit
        # cache keys on shapes, so warmup must reproduce the live
        # stacking exactly
        if wave_field_is_shared(f, shared, neutral_shared, job_shared):
            return np.asarray(x)
        return np.stack([np.asarray(x)] * b_pad)

    stacked = KernelIn(*[
        stack_field(f, getattr(kin, f)) for f in KernelIn._fields
    ])
    step_member = np.full(t_pad, -1, np.int32)
    step_local = np.zeros(t_pad, np.int32)
    pos = 0
    for i in range(b_pad):
        step_member[pos:pos + k_max] = i
        step_local[pos:pos + k_max] = np.arange(k_max)
        pos += k_max
    # the resident-state signature: shared leaves committed, the rest
    # host — exactly the leaves the live launcher swaps for device
    # twins when the cluster state is resident
    mixed = [wave_field_is_shared(f, shared, neutral_shared, job_shared)
             for f in KernelIn._fields]
    _call_both_placements(
        place_taskgroups_joint_jit,
        (stacked, jnp.asarray(step_member), jnp.asarray(step_local)),
        (t_pad, feats), mixed=mixed)
    return True


def _warm_joint_sharded(e: Dict, mesh) -> bool:
    """Populate the SHARDED joint program's jit cache for a manifest
    entry (parallel/sharded.make_joint_sharded) — the live signatures
    a mesh server's waves hit:

    1. every leaf host numpy (telemetry off, nothing resident — the
       jit itself uploads per its in_shardings);
    2. every leaf committed WITH the jit's shardings (the profiled
       path pre-places host leaves, and resident leaves arrive
       mesh-placed);
    3. mixed: the layout's shared leaves committed sharded (the
       resident cluster state + frozen singletons), the rest host.

    All three trace onto ONE XLA program; the extra traces are cache
    hits on the compilation cache. Entries whose node axis the mesh
    does not divide are skipped — the live launcher falls back to
    single-device dispatch for those (and counts it)."""
    import jax

    from nomad_tpu.ops.kernel import KernelIn
    from nomad_tpu.parallel.coalesce import wave_field_is_shared
    from nomad_tpu.parallel.sharded import (
        joint_in_shardings,
        make_joint_sharded,
    )

    n = int(e["nodes"])
    if mesh is None or mesh.size < 2 or n % mesh.size != 0:
        return False
    b_pad = int(e["wave"])
    t_pad = int(e["steps"])
    shared = bool(e.get("shared", True))
    neutral_shared = bool(e.get("neutral_shared", True))
    job_shared = bool(e.get("job_shared", True))
    feats = _features_from_dict(e["features"])
    k_max = max(t_pad // max(b_pad, 1), 1)
    kin = _dummy_kin(n, k_max)

    def stack_field(f, x):
        if wave_field_is_shared(f, shared, neutral_shared, job_shared):
            return np.asarray(x)
        return np.stack([np.asarray(x)] * b_pad)

    stacked = KernelIn(*[
        stack_field(f, getattr(kin, f)) for f in KernelIn._fields
    ])
    step_member = np.full(t_pad, -1, np.int32)
    step_local = np.zeros(t_pad, np.int32)
    pos = 0
    for i in range(b_pad):
        step_member[pos:pos + k_max] = i
        step_local[pos:pos + k_max] = np.arange(k_max)
        pos += k_max
    fn = make_joint_sharded(mesh, shared, neutral_shared, job_shared)
    kin_shardings, repl = joint_in_shardings(
        mesh, shared, neutral_shared, job_shared)
    arrays = (stacked, step_member, step_local)
    shardings = (kin_shardings, repl, repl)
    # all-host signature (jit uploads per in_shardings)
    out = fn(*arrays, t_pad, feats)
    jax.block_until_ready(out)
    # all-committed signature (the profiled path)
    placed = jax.device_put(arrays, shardings)
    out = fn(*placed, t_pad, feats)
    jax.block_until_ready(out)
    # mixed signature: shared leaves resident (mesh-placed), rest host
    # — only meaningful when the layout shares something (all-stacked
    # waves have no resident leaves, and the mixed call would just
    # repeat the all-host trace)
    subs = {
        f: jax.device_put(getattr(stacked, f),
                          getattr(kin_shardings, f))
        for f in KernelIn._fields
        if wave_field_is_shared(f, shared, neutral_shared, job_shared)
    }
    if subs:
        out = fn(stacked._replace(**subs), step_member, step_local,
                 t_pad, feats)
        jax.block_until_ready(out)
    return True


def _entry_wave(e: Dict):
    """Build one manifest entry's dummy wave exactly as launch_wave
    stacks it (shared predicate included) — the common prelude of the
    fused warm passes."""
    from nomad_tpu.ops.kernel import KernelIn
    from nomad_tpu.parallel.coalesce import wave_field_is_shared

    b_pad = int(e["wave"])
    t_pad = int(e["steps"])
    n = int(e["nodes"])
    shared = bool(e.get("shared", True))
    neutral_shared = bool(e.get("neutral_shared", True))
    job_shared = bool(e.get("job_shared", True))
    feats = _features_from_dict(e["features"])
    k_max = max(t_pad // max(b_pad, 1), 1)
    kin = _dummy_kin(n, k_max)

    def stack_field(f, x):
        if wave_field_is_shared(f, shared, neutral_shared, job_shared):
            return np.asarray(x)
        return np.stack([np.asarray(x)] * b_pad)

    stacked = KernelIn(*[
        stack_field(f, getattr(kin, f)) for f in KernelIn._fields
    ])
    step_member = np.full(t_pad, -1, np.int32)
    step_local = np.zeros(t_pad, np.int32)
    pos = 0
    for i in range(b_pad):
        step_member[pos:pos + k_max] = i
        step_local[pos:pos + k_max] = np.arange(k_max)
        pos += k_max
    return (stacked, step_member, step_local, t_pad, feats,
            (shared, neutral_shared, job_shared))


def _warm_fused(e: Dict) -> bool:
    """Compile the single-device FUSED program for a joint manifest
    entry — the same three commitment signatures _warm_joint covers
    (host / committed / resident-mixed), against the fused jit."""
    import jax.numpy as jnp

    from nomad_tpu.ops.kernel import KernelIn, fused_wave_supported
    from nomad_tpu.ops.pallas_kernel import fused_wave_place_jit
    from nomad_tpu.parallel.coalesce import wave_field_is_shared

    feats = _features_from_dict(e["features"])
    if not fused_wave_supported(feats):
        return False
    stacked, step_member, step_local, t_pad, feats, layout = \
        _entry_wave(e)
    mixed = [wave_field_is_shared(f, *layout)
             for f in KernelIn._fields]
    _call_both_placements(
        fused_wave_place_jit,
        (stacked, jnp.asarray(step_member), jnp.asarray(step_local)),
        (t_pad, feats), mixed=mixed)
    return True


def _warm_fused_sharded(e: Dict, mesh) -> bool:
    """Compile the FUSED sharded program for a joint manifest entry —
    the same three signatures as _warm_joint_sharded, against the
    shard_map entry. Skips entries the mesh cannot serve fused: a
    node axis it does not divide, or shards narrower than the local
    TOPK merge (the live launcher counts those as fused fallbacks)."""
    import jax

    from nomad_tpu.ops.kernel import (
        TOPK,
        KernelIn,
        fused_wave_supported,
    )
    from nomad_tpu.parallel.coalesce import wave_field_is_shared
    from nomad_tpu.parallel.sharded import fused_sharded_entry

    feats = _features_from_dict(e["features"])
    if not fused_wave_supported(feats):
        return False
    n = int(e["nodes"])
    if (mesh is None or mesh.size < 2 or n % mesh.size != 0
            or n // mesh.size < TOPK):
        return False
    stacked, step_member, step_local, t_pad, feats, layout = \
        _entry_wave(e)
    fn, kin_shardings, repl = fused_sharded_entry(mesh, *layout)
    arrays = (stacked, step_member, step_local)
    shardings = (kin_shardings, repl, repl)
    out = fn(*arrays, t_pad, feats)
    jax.block_until_ready(out)
    placed = jax.device_put(arrays, shardings)
    out = fn(*placed, t_pad, feats)
    jax.block_until_ready(out)
    subs = {
        f: jax.device_put(getattr(stacked, f),
                          getattr(kin_shardings, f))
        for f in KernelIn._fields
        if wave_field_is_shared(f, *layout)
    }
    if subs:
        out = fn(stacked._replace(**subs), step_member, step_local,
                 t_pad, feats)
        jax.block_until_ready(out)
    return True


def _warm_single(e: Dict) -> bool:
    from nomad_tpu.ops.kernel import (
        KernelIn,
        place_taskgroup_jit,
        place_taskgroup_topk_jit,
    )
    from nomad_tpu.parallel.coalesce import wave_field_is_shared

    n = int(e["nodes"])
    k_steps = int(e["steps"])
    feats = _features_from_dict(e["features"])
    kin = _dummy_kin(n, k_steps)
    # the direct dispatch substitutes BOTH sharing groups when the
    # cluster state is resident (ops/kernel._resident_kin)
    mixed = [wave_field_is_shared(f, True, True, True)
             for f in KernelIn._fields]
    if e["kernel"] == "single_topk":
        if feats.n_spreads != 0:
            return False                # topk path never compiles these
        _call_both_placements(place_taskgroup_topk_jit, (kin,),
                              (k_steps, feats), mixed=mixed)
    else:
        _call_both_placements(place_taskgroup_jit, (kin,),
                              (k_steps, feats), mixed=mixed)
    return True


def warmup_entries(entries: List[Dict], mesh=None,
                   mesh_only: bool = False) -> Tuple[int, int]:
    """Compile every manifest entry; returns (compiled, failed).
    Failures are logged and skipped — warmup is an optimization, never
    a liveness dependency.

    ``mesh``: ALSO warm the sharded joint signatures for this mesh
    (the default dispatch on a >=2-device server). ``mesh_only`` skips
    the single-device programs — the pass a server runs when its mesh
    probe adopts a mesh AFTER the main warmup already covered them."""
    compiled = failed = 0
    node_sizes = set()
    for e in _dedupe(entries):
        try:
            did = False
            if e.get("kernel") == "joint":
                # warm the one program the launcher will route this
                # entry's envelope to: the FUSED mega-kernel when the
                # envelope supports it (and the knob is on), the
                # composite otherwise — warming both would double
                # compile time on a program that never dispatches.
                # The composite still compiles lazily on the rare
                # fused-exception fallback; that path is off the
                # steady state by construction.
                from nomad_tpu.parallel.coalesce import (
                    fused_wave_enabled,
                )

                fused_on = fused_wave_enabled()
                if not mesh_only:
                    did = fused_on and _warm_fused(e)
                    if not did:
                        did = _warm_joint(e)
                if mesh is not None:
                    d2 = fused_on and _warm_fused_sharded(e, mesh)
                    if not d2:
                        # a mesh too narrow for the fused local
                        # top-k merge launches composite-sharded
                        d2 = _warm_joint_sharded(e, mesh)
                    did = d2 or did
            elif e.get("kernel") in ("single_topk", "single_full"):
                if not mesh_only:
                    did = _warm_single(e)
            else:
                continue
            if did:
                compiled += 1
                node_sizes.add(int(e["nodes"]))
        except Exception as err:                # noqa: BLE001
            failed += 1
            LOG.warning("kernel warmup entry failed (%s): %s", e, err)
    # the device-resident state's dirty-row scatter rides the same
    # node shapes: precompile its (row-bucket, dtype) programs so the
    # first burst whose dirty set crosses a fresh bucket doesn't pay a
    # cold compile inside an eval's snapshot phase
    for n in sorted(node_sizes):
        try:
            from nomad_tpu.tensors.device_state import default_device_state

            default_device_state.warm_scatter(n)
        except Exception as err:                # noqa: BLE001
            LOG.warning("scatter warmup failed (n=%d): %s", n, err)
    return compiled, failed


def warmup_from_manifest(path: str, expand: bool = True,
                         max_wave: Optional[int] = None,
                         mesh=None,
                         mesh_only: bool = False) -> Tuple[int, int]:
    """Load ``path`` and precompile its lattice (expanded across the
    wave-bucket axis unless ``expand=False``; see ``expand_lattice``
    for ``max_wave``, ``warmup_entries`` for ``mesh``/``mesh_only``).
    Missing/corrupt manifests are a no-op."""
    try:
        entries = load_manifest(path)
    except FileNotFoundError:
        return (0, 0)
    except Exception as err:                    # noqa: BLE001
        LOG.warning("warmup manifest %s unreadable: %s", path, err)
        return (0, 0)
    if expand:
        entries = expand_lattice(entries, max_wave=max_wave)
    return warmup_entries(entries, mesh=mesh, mesh_only=mesh_only)


def start_background_warmup(path: str, expand: bool = True,
                            max_wave: Optional[int] = None,
                            mesh=None,
                            on_done=None) -> threading.Thread:
    """Server-start entry point: warm the manifest on a daemon thread
    (compiles hold the XLA compile lock, not the GIL, so the server
    keeps serving; waves that race warmup simply compile first and the
    warmup call becomes a cache hit)."""
    def run() -> None:
        try:
            compiled, failed = warmup_from_manifest(
                path, expand=expand, max_wave=max_wave, mesh=mesh)
            if compiled or failed:
                LOG.info("kernel warmup: %d compiled, %d failed (%s)",
                         compiled, failed, path)
            if on_done is not None:
                on_done(compiled, failed)
        except Exception as err:                # noqa: BLE001
            LOG.warning("kernel warmup failed: %s", err)

    t = threading.Thread(target=run, daemon=True, name="kernel-warmup")
    t.start()
    return t
