"""JAX scheduling kernels: the TPU-native hot path.

Replaces the reference's per-node iterator chain
(scheduler/stack.go GenericStack.Select -> feasible.go -> rank.go ->
spread.go -> select.go) with one batched kernel over the node axis:
feasibility is boolean mask algebra, scoring is elementwise math over
score planes, selection is a global argmax, and sequential resource
deduction between placements of the same task group is a ``lax.scan``
(place -> update planes -> repeat).
"""

from nomad_tpu.ops.kernel import (  # noqa: F401
    KernelIn,
    KernelOut,
    TOPK,
    build_kernel_in,
    pad_steps,
    place_taskgroup,
    place_taskgroup_jit,
)
