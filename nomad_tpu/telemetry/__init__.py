"""Eval-lifecycle tracing + TPU kernel profiling.

The subsystem the BENCH_r05 gap analysis was missing: spans across the
full eval hot path (broker dequeue -> worker batch -> snapshot -> wave
assembly -> kernel launch -> plan submit -> plan apply -> FSM), a
JAX-level wave profiler (h2d / compile / dispatch / execute / d2h, jit
cache-miss accounting per bucket shape), and exposition through
``/v1/metrics`` + ``/v1/operator/traces``.

Disabled by default; ``telemetry.enable()`` (or
``NOMAD_TPU_TRACE=1`` in the environment) turns both the tracer and
the kernel profiler on. Disabled-mode cost on the hot path is one
attribute check per span site.
"""

from __future__ import annotations

import os

from nomad_tpu.telemetry.histogram import (  # noqa: F401
    HistogramRegistry,
    LatencyHistogram,
    histograms,
    percentile,
)
from nomad_tpu.telemetry.kernel_profile import (  # noqa: F401
    KernelProfiler,
    profiled_call,
    profiler,
)
from nomad_tpu.telemetry.trace import (  # noqa: F401
    ConsensusRecorder,
    FlightRecorder,
    Span,
    Tracer,
    consensus_recorder,
    flight_recorder,
    tracer,
)

__all__ = [
    "Span", "Tracer", "tracer",
    "KernelProfiler", "profiler", "profiled_call",
    "LatencyHistogram", "HistogramRegistry", "histograms", "percentile",
    "FlightRecorder", "flight_recorder",
    "ConsensusRecorder", "consensus_recorder",
    "enable", "disable", "enabled", "reset",
]


def enable() -> None:
    tracer.enable()
    profiler.enable()


def disable() -> None:
    tracer.disable()
    profiler.disable()


def enabled() -> bool:
    return tracer.enabled


def reset() -> None:
    tracer.reset()
    profiler.reset()
    # latency histograms + the slow-eval flight recorder cover the
    # same burst window as the tracer aggregates
    histograms.reset()
    flight_recorder.reset()
    # the consensus-plane recorder + per-server raft observer counters
    # follow the same burst window (live-node registrations survive)
    consensus_recorder.reset()
    try:
        from nomad_tpu.raft.observe import raft_observer

        raft_observer.reset_stats()
    except Exception:                           # noqa: BLE001
        pass
    try:
        # wave-shape stats (fill ratio, park latency) live with the
        # coalescer; reset them with the rest so burst decompositions
        # cover exactly their window. Import is lazy/guarded: telemetry
        # must stay importable without jax.
        from nomad_tpu.parallel.coalesce import (
            fused_wave_stats,
            sharded_wave_stats,
            wave_stats,
        )

        wave_stats.reset()
        sharded_wave_stats.reset()
        fused_wave_stats.reset()
    except Exception:                           # noqa: BLE001
        pass
    try:
        # device-residency counters (dirty-row upload ratio etc.)
        # follow the same window; the resident arrays themselves stay
        from nomad_tpu.tensors.device_state import default_device_state

        default_device_state.reset_stats()
    except Exception:                           # noqa: BLE001
        pass
    try:
        # feasibility mask-cache counters follow the same window; the
        # cached programs/masks themselves stay resident
        from nomad_tpu.feasibility import default_mask_cache

        default_mask_cache.reset_stats()
    except Exception:                           # noqa: BLE001
        pass
    try:
        # plan group-commit counters (vector vs fallback re-validation,
        # batched raft entries) cover the same burst window
        from nomad_tpu.server.plan_apply import plan_group_stats

        plan_group_stats.reset()
    except Exception:                           # noqa: BLE001
        pass
    try:
        # wave-cohort drain counters (plan-queue wave-boundary
        # batching) follow the burst window; the learned drain EWMA
        # survives like any other timing calibration
        from nomad_tpu.utils.wavecohort import wave_cohorts

        wave_cohorts.reset_stats()
    except Exception:                           # noqa: BLE001
        pass
    try:
        # blocking-query wakeup counters (state/store.py watch_stats)
        # cover the same burst window; the held-watcher gauge tracks
        # live waiters and is never reset
        from nomad_tpu.state.store import watch_stats

        watch_stats.reset_stats()
    except Exception:                           # noqa: BLE001
        pass
    try:
        # MVCC store rate counters (state/store.py store_stats) cover
        # the same burst window; the generation and live-root gauges
        # track durable store state and are never reset
        from nomad_tpu.state.store import store_stats

        store_stats.reset_stats()
    except Exception:                           # noqa: BLE001
        pass
    try:
        # heartbeat fan-in counters (server/server.py) follow the
        # burst window; event-broker stats are per-broker and are
        # windowed by the bench cells via broker.reset_stats()
        from nomad_tpu.server.server import client_update_stats

        client_update_stats.reset_stats()
    except Exception:                           # noqa: BLE001
        pass
    try:
        # read-plane routing counters (server/readplane.py) follow the
        # burst window; the staleness histogram rides the shared
        # registry reset above
        from nomad_tpu.server.readplane import read_stats

        read_stats.reset_stats()
    except Exception:                           # noqa: BLE001
        pass


if os.environ.get("NOMAD_TPU_TRACE", "") not in ("", "0"):
    enable()
