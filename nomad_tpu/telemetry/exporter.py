"""Exposition: Prometheus text + JSON trace dump.

The reference serves go-metrics through ``/v1/metrics`` with
``?format=prometheus`` rendering the Prometheus text exposition
(command/agent/http.go:383). This exporter extends that surface with
the telemetry subsystem's series:

- ``nomad_tpu_trace_span_seconds_total{span=...}`` /
  ``..._exclusive_seconds_total`` / ``..._count`` — per-span-name
  aggregates from the tracer (full-fidelity; survives ring wrap).
- ``nomad_tpu_kernel_stage_seconds_total{stage=...}`` — the wave
  pipeline decomposition (h2d / compile / dispatch / execute).
- ``nomad_tpu_kernel_jit_cache_misses_total{kernel=...,key=...}`` and
  ``..._launches_total`` — the recompile accounting per bucket shape.

``traces_json`` is the ``/v1/operator/traces`` body: the raw span ring
(newest spans, bounded) plus the aggregates, so an operator can pull a
decomposition from a live server without restarting it.
"""

from __future__ import annotations

from typing import Dict, List

from nomad_tpu.telemetry.histogram import histograms
from nomad_tpu.telemetry.kernel_profile import profiler
from nomad_tpu.telemetry.trace import (
    consensus_recorder,
    flight_recorder,
    tracer,
)
from nomad_tpu.utils import metrics as _metrics


def _esc(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, AND newline
    (the text exposition is line-framed — an unescaped newline in a
    label value corrupts every series after it). ISSUE 15 routes every
    labeled series through this one helper (via :func:`_lbl`); server
    ids and trace ids now flow into labels, so hygiene is load-bearing
    rather than cosmetic."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _lbl(**kv) -> str:
    """Render ``k="v"`` label pairs, every value escaped. The single
    seam all labeled series go through."""
    return ",".join(f'{k}="{_esc(v)}"' for k, v in kv.items())


def prometheus_text(registry=None, event_broker=None) -> str:
    """The full exposition: metrics registry + telemetry series.
    ``event_broker`` is the serving server's broker (per-server state,
    unlike every other source here); the HTTP layer passes it so the
    ``nomad_tpu_stream_*`` gauges ride the same scrape."""
    reg = registry if registry is not None else _metrics.global_registry
    base = reg.prometheus_text().strip("\n")
    lines: List[str] = [base] if base else []

    stages = tracer.stage_totals()
    if stages:
        lines.append("# TYPE nomad_tpu_trace_span_seconds_total counter")
        for name, agg in stages.items():
            lines.append(
                f'nomad_tpu_trace_span_seconds_total{{{_lbl(span=name)}}} '
                f"{agg['total_s']:.6f}")
        lines.append(
            "# TYPE nomad_tpu_trace_span_exclusive_seconds_total counter")
        for name, agg in stages.items():
            lines.append(
                f'nomad_tpu_trace_span_exclusive_seconds_total'
                f'{{{_lbl(span=name)}}} '
                f"{agg['exclusive_s']:.6f}")
        lines.append("# TYPE nomad_tpu_trace_span_count counter")
        for name, agg in stages.items():
            lines.append(
                f'nomad_tpu_trace_span_count{{{_lbl(span=name)}}} '
                f"{agg['count']}")

    prof = profiler.summary()
    lines.append("# TYPE nomad_tpu_kernel_stage_seconds_total counter")
    for stage, secs in sorted(prof["StageSeconds"].items()):
        lines.append(
            f'nomad_tpu_kernel_stage_seconds_total{{{_lbl(stage=stage)}}} '
            f"{secs}")
    # transfer BYTES per direction (ISSUE 3): seconds say how long the
    # PCIe stages took, bytes say whether the payload shrank — the
    # device-resident cluster state's success metric
    lines.append("# TYPE nomad_tpu_kernel_transfer_bytes_total counter")
    for direction, n in sorted(prof.get("TransferBytes", {}).items()):
        lines.append(
            f'nomad_tpu_kernel_transfer_bytes_total'
            f'{{{_lbl(direction=direction)}}} {n}')
    # per-wave device-dispatch counts (ISSUE 19): program executions
    # plus the composite's eager result fetch ("wave_fetch") and the
    # deferred top-k drain ("topk_drain") — a fused steady wave is
    # exactly ONE dispatch, which TRACE_DECOMP's dispatches_per_wave
    # key gates
    if prof.get("Dispatches"):
        lines.append("# TYPE nomad_tpu_kernel_dispatches_total counter")
        for program, n in sorted(prof["Dispatches"].items()):
            lines.append(
                f'nomad_tpu_kernel_dispatches_total'
                f'{{{_lbl(program=program)}}} {n}')
    if prof["PerKey"]:
        lines.append(
            "# TYPE nomad_tpu_kernel_jit_cache_misses_total counter")
        lines.append("# TYPE nomad_tpu_kernel_launches_total counter")
        for row in prof["PerKey"]:
            labels = _lbl(kernel=row["Kernel"], key=row["Key"])
            lines.append(
                f"nomad_tpu_kernel_jit_cache_misses_total{{{labels}}} "
                f"{row['Misses']}")
            lines.append(
                f"nomad_tpu_kernel_launches_total{{{labels}}} "
                f"{row['Launches']}")
    # wave-shape series (parallel/coalesce.wave_stats): fill ratio says
    # whether the adaptive coalescer fires full or starved waves; park
    # latency percentiles are the rendezvous cost its deadline bounds
    try:
        from nomad_tpu.parallel.coalesce import wave_stats

        w = wave_stats.snapshot()
        lines.append("# TYPE nomad_tpu_wave_fill_ratio gauge")
        lines.append(f"nomad_tpu_wave_fill_ratio {w['fill_ratio']:.4f}")
        lines.append("# TYPE nomad_tpu_wave_park_latency_seconds gauge")
        lines.append(
            'nomad_tpu_wave_park_latency_seconds{quantile="0.5"} '
            f"{w['park_latency_p50_ms'] / 1e3:.6f}")
        lines.append(
            'nomad_tpu_wave_park_latency_seconds{quantile="0.99"} '
            f"{w['park_latency_p99_ms'] / 1e3:.6f}")
        lines.append("# TYPE nomad_tpu_wave_launches_total counter")
        lines.append(
            'nomad_tpu_wave_launches_total{fired="full"} '
            f"{w['full_launches']}")
        lines.append(
            'nomad_tpu_wave_launches_total{fired="deadline"} '
            f"{w['deadline_launches']}")
        # sharded dispatch (ISSUE 14): waves that ran the joint program
        # over a device mesh vs mesh-present single-device fallbacks
        # (a node axis the device count does not divide) — fallbacks
        # must sit at 0 on a healthy mesh server, and the mesh-device
        # gauge says how wide the slice is
        from nomad_tpu.parallel.coalesce import sharded_wave_stats

        s = sharded_wave_stats.snapshot()
        lines.append(
            "# TYPE nomad_tpu_wave_sharded_launches_total counter")
        lines.append(
            f"nomad_tpu_wave_sharded_launches_total {s['launches']}")
        lines.append(
            "# TYPE nomad_tpu_wave_sharded_fallbacks_total counter")
        lines.append(
            f"nomad_tpu_wave_sharded_fallbacks_total {s['fallbacks']}")
        lines.append(
            "# TYPE nomad_tpu_wave_sharded_mesh_devices gauge")
        lines.append(
            f"nomad_tpu_wave_sharded_mesh_devices {s['mesh_devices']}")
        # fused dispatch (ISSUE 19): waves that ran the one-dispatch
        # mega-kernel vs fusion-wanted composite fallbacks (an
        # unsupported feature union, a narrow shard, or a fused
        # error) — fallbacks must sit at 0 on steady traffic
        from nomad_tpu.parallel.coalesce import fused_wave_stats

        fu = fused_wave_stats.snapshot()
        lines.append(
            "# TYPE nomad_tpu_wave_fused_launches_total counter")
        lines.append(
            f"nomad_tpu_wave_fused_launches_total {fu['launches']}")
        lines.append(
            "# TYPE nomad_tpu_wave_fused_fallbacks_total counter")
        lines.append(
            f"nomad_tpu_wave_fused_fallbacks_total {fu['fallbacks']}")
    except Exception:                           # noqa: BLE001
        pass                # coalescer (jax) unavailable: skip series
    # device-resident cluster state (tensors/device_state.py): how the
    # shared wave planes advanced — row-scatter deltas vs full uploads,
    # and the dirty-row upload ratio (delta bytes / full-re-upload
    # bytes; low = the h2d tax is gone)
    try:
        from nomad_tpu.tensors.device_state import default_device_state

        d = default_device_state.snapshot()
        lines.append(
            "# TYPE nomad_tpu_device_state_advances_total counter")
        for kind, key in (("hit", "hits"),
                          ("delta", "delta_advances"),
                          ("fork_delta", "fork_deltas"),
                          ("full", "full_uploads"),
                          ("usage_full", "usage_full_uploads")):
            lines.append(
                f'nomad_tpu_device_state_advances_total'
                f'{{kind="{kind}"}} {d[key]}')
        lines.append(
            "# TYPE nomad_tpu_device_state_rows_uploaded_total counter")
        lines.append(
            f"nomad_tpu_device_state_rows_uploaded_total "
            f"{d['rows_uploaded']}")
        lines.append(
            "# TYPE nomad_tpu_device_state_upload_bytes_total counter")
        lines.append(
            f"nomad_tpu_device_state_upload_bytes_total "
            f"{d['bytes_uploaded']}")
        lines.append(
            "# TYPE nomad_tpu_device_state_dirty_row_upload_ratio gauge")
        lines.append(
            f"nomad_tpu_device_state_dirty_row_upload_ratio "
            f"{d['dirty_row_upload_ratio']}")
        lines.append(
            "# TYPE nomad_tpu_device_state_resident_generations gauge")
        lines.append(
            f"nomad_tpu_device_state_resident_generations "
            f"{d['resident_generations']}")
    except Exception:                           # noqa: BLE001
        pass                # device state (jax) unavailable: skip
    # feasibility compiler (nomad_tpu/feasibility/): mask-program cache
    # effectiveness — a steady cluster should sit near hit_ratio 1.0,
    # with misses only on node-structure forks and novel job specs
    try:
        from nomad_tpu.feasibility import default_mask_cache

        f = default_mask_cache.snapshot()
        lines.append(
            "# TYPE nomad_tpu_feasibility_mask_lookups_total counter")
        for kind, key in (("hit", "hits"), ("miss", "misses"),
                          ("fallback", "fallbacks")):
            lines.append(
                f'nomad_tpu_feasibility_mask_lookups_total'
                f'{{kind="{kind}"}} {f[key]}')
        lines.append(
            "# TYPE nomad_tpu_feasibility_program_compiles_total counter")
        lines.append(
            f"nomad_tpu_feasibility_program_compiles_total "
            f"{f['program_compiles']}")
        lines.append(
            "# TYPE nomad_tpu_feasibility_dynamic_applies_total counter")
        lines.append(
            f"nomad_tpu_feasibility_dynamic_applies_total "
            f"{f['dynamic_applies']}")
        lines.append(
            "# TYPE nomad_tpu_feasibility_mask_hit_ratio gauge")
        lines.append(
            f"nomad_tpu_feasibility_mask_hit_ratio {f['hit_ratio']}")
        lines.append(
            "# TYPE nomad_tpu_feasibility_cached_masks gauge")
        lines.append(
            f"nomad_tpu_feasibility_cached_masks {f['cached_masks']}")
    except Exception:                           # noqa: BLE001
        pass                # feasibility subsystem unavailable: skip
    # plan group commit (server/plan_apply.py): wave-window plan
    # re-validation — vector-proven vs exact-walk fallback plans,
    # rejected node plans, and the batched raft entries' plan counts
    # and payload bytes. fallback > 0 on a lean burst is a regression
    # (the steady-state gate requires 0).
    try:
        from nomad_tpu.server.plan_apply import plan_group_stats

        g = plan_group_stats.snapshot()
        lines.append("# TYPE nomad_tpu_plan_group_plans_total counter")
        for kind, key in (("vector", "vector_plans"),
                          ("fallback", "fallback_plans")):
            lines.append(
                f'nomad_tpu_plan_group_plans_total{{kind="{kind}"}} '
                f'{g[key]}')
        lines.append(
            "# TYPE nomad_tpu_plan_group_port_plans_total counter")
        for kind, key in (("vector", "port_vector_plans"),
                          ("fallback", "port_fallback_plans")):
            lines.append(
                f'nomad_tpu_plan_group_port_plans_total{{kind="{kind}"}} '
                f'{g[key]}')
        lines.append("# TYPE nomad_tpu_plan_group_rejects_total counter")
        lines.append(
            f"nomad_tpu_plan_group_rejects_total "
            f"{g['rejected_node_plans']}")
        lines.append("# TYPE nomad_tpu_plan_group_commits_total counter")
        lines.append(
            f"nomad_tpu_plan_group_commits_total {g['commit_batches']}")
        lines.append(
            "# TYPE nomad_tpu_plan_group_committed_plans_total counter")
        lines.append(
            f"nomad_tpu_plan_group_committed_plans_total "
            f"{g['committed_plans']}")
        lines.append("# TYPE nomad_tpu_plan_group_bytes_total counter")
        lines.append(
            f"nomad_tpu_plan_group_bytes_total {g['batch_bytes']}")
        lines.append("# TYPE nomad_tpu_plan_group_size_avg gauge")
        lines.append(
            f"nomad_tpu_plan_group_size_avg "
            f"{round(g['group_size_avg'], 4)}")
    except Exception:                           # noqa: BLE001
        pass                # plan applier unavailable: skip
    # plan rejection tracker (server/plan_rejection.py; Nomad 1.3's
    # plan_rejection_tracker): per-node applier-rejection pressure and
    # the eligibility flips it drove — a node "eating the cluster"
    # shows up here before it shows up as a throughput mystery
    try:
        from nomad_tpu.server.plan_rejection import plan_rejections

        pr = plan_rejections.snapshot()
        lines.append(
            "# TYPE nomad_tpu_plan_rejection_node_rejections_total "
            "counter")
        lines.append(
            f"nomad_tpu_plan_rejection_node_rejections_total "
            f"{pr['rejections']}")
        lines.append(
            "# TYPE nomad_tpu_plan_rejection_marked_ineligible_total "
            "counter")
        lines.append(
            f"nomad_tpu_plan_rejection_marked_ineligible_total "
            f"{pr['nodes_marked']}")
        lines.append(
            "# TYPE nomad_tpu_plan_rejection_tracked_nodes gauge")
        lines.append(
            f"nomad_tpu_plan_rejection_tracked_nodes "
            f"{pr['tracked_nodes']}")
    except Exception:                           # noqa: BLE001
        pass                # tracker unavailable: skip series
    # fault-injection plane (utils/faultpoints.py, ISSUE 12): per-point
    # hit/fire counters plus the armed gauge. Disarmed processes show
    # armed=0 and no per-point series — exactly the no-op promise.
    try:
        from nomad_tpu.utils import faultpoints

        fp = faultpoints.stats()
        lines.append("# TYPE nomad_tpu_fault_armed gauge")
        lines.append(
            f"nomad_tpu_fault_armed {1 if faultpoints.armed() else 0}")
        if fp:
            lines.append("# TYPE nomad_tpu_fault_hits_total counter")
            for point, row in fp.items():
                lines.append(
                    f'nomad_tpu_fault_hits_total'
                    f'{{{_lbl(point=point)}}} {row["hits"]}')
            lines.append("# TYPE nomad_tpu_fault_fires_total counter")
            for point, row in fp.items():
                kind = row["kind"] or "none"
                lines.append(
                    f'nomad_tpu_fault_fires_total'
                    f'{{{_lbl(point=point, kind=kind)}}} '
                    f'{row["fires"]}')
    except Exception:                           # noqa: BLE001
        pass                # fault plane unavailable: skip series
    # raft durability plane (raft/wal.py, ISSUE 13): WAL frame/fsync
    # volume, recovery accounting (replayed entries, torn-tail
    # truncations), and the snapshot byte meters (in-memory cache vs
    # on-disk files). In-memory raft shows zeros — the disarmed-cost
    # promise, like the fault plane's.
    try:
        from nomad_tpu.raft.wal import wal_stats

        d = wal_stats.snapshot()
        lines.append(
            "# TYPE nomad_tpu_raft_durability_fsyncs_total counter")
        lines.append(
            f"nomad_tpu_raft_durability_fsyncs_total {d['fsyncs']}")
        lines.append(
            "# TYPE nomad_tpu_raft_durability_frames_total counter")
        lines.append(
            f"nomad_tpu_raft_durability_frames_total {d['frames']}")
        lines.append(
            "# TYPE nomad_tpu_raft_durability_bytes_total counter")
        lines.append(
            f"nomad_tpu_raft_durability_bytes_total {d['bytes_written']}")
        lines.append(
            "# TYPE nomad_tpu_raft_durability_replayed_entries_total "
            "counter")
        lines.append(
            f"nomad_tpu_raft_durability_replayed_entries_total "
            f"{d['replayed_entries']}")
        lines.append(
            "# TYPE nomad_tpu_raft_durability_torn_truncations_total "
            "counter")
        lines.append(
            f"nomad_tpu_raft_durability_torn_truncations_total "
            f"{d['torn_truncations']}")
        lines.append(
            "# TYPE nomad_tpu_raft_durability_recoveries_total counter")
        lines.append(
            f"nomad_tpu_raft_durability_recoveries_total "
            f"{d['recoveries']}")
        lines.append("# TYPE nomad_tpu_raft_snapshots_total counter")
        for kind, key in (("written", "snapshots_written"),
                          ("pruned", "snapshots_pruned"),
                          ("invalid", "snapshots_invalid")):
            lines.append(
                f'nomad_tpu_raft_snapshots_total{{kind="{kind}"}} '
                f'{d[key]}')
        lines.append("# TYPE nomad_tpu_raft_snapshot_bytes gauge")
        for kind, key in (("cache", "snapshot_cache_bytes"),
                          ("disk", "snapshot_disk_bytes")):
            lines.append(
                f'nomad_tpu_raft_snapshot_bytes{{kind="{kind}"}} '
                f'{d[key]}')
    except Exception:                           # noqa: BLE001
        pass                # durability plane unavailable: skip series
    # per-replica consensus plane (ISSUE 15): raft state/term/lag and
    # WAL counters with a server_id label, so co-resident
    # make_cluster servers report three distinguishable truths
    # instead of one blended process-global one. Aggregate series
    # above stay for single-server scrapes; these are the per-replica
    # view the cluster-health endpoint renders.
    try:
        from nomad_tpu.raft.observe import raft_observer
        from nomad_tpu.raft.wal import wal_stats as _wal_stats

        per = raft_observer.snapshot()
        live = {sid: row for sid, row in sorted(per.items())
                if row.get("live")}
        if live:
            for series, key in (("nomad_tpu_raft_term", "term"),
                                ("nomad_tpu_raft_is_leader",
                                 "is_leader"),
                                ("nomad_tpu_raft_commit_index",
                                 "commit_index"),
                                ("nomad_tpu_raft_last_applied",
                                 "last_applied")):
                lines.append(f"# TYPE {series} gauge")
                for sid, row in live.items():
                    lines.append(
                        f'{series}{{{_lbl(server_id=sid)}}} {row[key]}')
            lines.append(
                "# TYPE nomad_tpu_raft_peer_lag_entries gauge")
            lines.append(
                "# TYPE nomad_tpu_raft_peer_last_contact_seconds gauge")
            for sid, row in live.items():
                for peer, lag in sorted(
                        row.get("peer_lag_entries", {}).items()):
                    lines.append(
                        f'nomad_tpu_raft_peer_lag_entries'
                        f'{{{_lbl(server_id=sid, peer=peer)}}} {lag}')
                for peer, age in sorted(
                        row.get("peer_last_contact_s", {}).items()):
                    lines.append(
                        f'nomad_tpu_raft_peer_last_contact_seconds'
                        f'{{{_lbl(server_id=sid, peer=peer)}}} {age}')
            # replication pipeline + leader lease (ISSUE 18): window
            # occupancy per peer, arm/drain counters, and the lease
            # fast-path/barrier read split
            for series, key, mtype in (
                    ("nomad_tpu_raft_pipeline_armed_peers",
                     "pipeline_armed", "gauge"),
                    ("nomad_tpu_raft_pipeline_batches_total",
                     "pipeline_batches", "counter"),
                    ("nomad_tpu_raft_pipeline_drains_total",
                     "pipeline_drains", "counter"),
                    ("nomad_tpu_raft_lease_valid", "lease_valid",
                     "gauge"),
                    ("nomad_tpu_raft_lease_age_seconds", "lease_age_s",
                     "gauge")):
                lines.append(f"# TYPE {series} {mtype}")
                for sid, row in live.items():
                    val = row.get(key)
                    if val is None:
                        continue
                    lines.append(
                        f'{series}{{{_lbl(server_id=sid)}}} {val}')
            lines.append(
                "# TYPE nomad_tpu_raft_pipeline_inflight_batches gauge")
            lines.append(
                "# TYPE nomad_tpu_raft_lease_reads_total counter")
            for sid, row in live.items():
                for peer, n in sorted(
                        (row.get("pipeline_inflight") or {}).items()):
                    lines.append(
                        f'nomad_tpu_raft_pipeline_inflight_batches'
                        f'{{{_lbl(server_id=sid, peer=peer)}}} {n}')
                for path, key in (("fast", "lease_reads_fast"),
                                  ("barrier", "lease_reads_barrier")):
                    val = row.get(key)
                    if val is None:
                        continue
                    lines.append(
                        f'nomad_tpu_raft_lease_reads_total'
                        f'{{{_lbl(server_id=sid, path=path)}}} {val}')
        if any(row.get("transitions") or row.get("replicated_entries")
               or row.get("snapshot_xfer_bytes")
               for row in per.values()):
            lines.append(
                "# TYPE nomad_tpu_raft_transitions_total counter")
            lines.append(
                "# TYPE nomad_tpu_raft_replicated_entries_total counter")
            lines.append("# TYPE nomad_tpu_raft_peer_lag_seconds gauge")
            lines.append(
                "# TYPE nomad_tpu_raft_snapshot_transfer_bytes_total "
                "counter")
            for sid, row in sorted(per.items()):
                for kind, n in sorted(row["transitions"].items()):
                    lines.append(
                        f'nomad_tpu_raft_transitions_total'
                        f'{{{_lbl(server_id=sid, kind=kind)}}} {n}')
                for peer, n in sorted(
                        row["replicated_entries"].items()):
                    lines.append(
                        f'nomad_tpu_raft_replicated_entries_total'
                        f'{{{_lbl(server_id=sid, peer=peer)}}} {n}')
                for peer, ms in sorted(row["peer_lag_ms"].items()):
                    lines.append(
                        f'nomad_tpu_raft_peer_lag_seconds'
                        f'{{{_lbl(server_id=sid, peer=peer)}}} '
                        f'{ms / 1e3:.6f}')
                for direction, n in sorted(
                        row["snapshot_xfer_bytes"].items()):
                    lines.append(
                        f'nomad_tpu_raft_snapshot_transfer_bytes_total'
                        f'{{{_lbl(server_id=sid, direction=direction)}}} '
                        f'{n}')
        walper = _wal_stats.per_server()
        if walper:
            for series, key, mtype in (
                    ("nomad_tpu_raft_wal_frames_total", "frames",
                     "counter"),
                    ("nomad_tpu_raft_wal_fsyncs_total", "fsyncs",
                     "counter"),
                    ("nomad_tpu_raft_wal_bytes_total", "bytes_written",
                     "counter"),
                    ("nomad_tpu_raft_wal_replayed_entries_total",
                     "replayed_entries", "counter"),
                    ("nomad_tpu_raft_wal_torn_truncations_total",
                     "torn_truncations", "counter"),
                    ("nomad_tpu_raft_wal_segments", "segments",
                     "gauge"),
                    ("nomad_tpu_raft_wal_pending_frames",
                     "pending_frames", "gauge"),
                    ("nomad_tpu_raft_wal_fsync_batch_avg",
                     "fsync_batch_avg", "gauge"),
                    ("nomad_tpu_raft_wal_failed", "wal_failed",
                     "gauge")):
                lines.append(f"# TYPE {series} {mtype}")
                for sid, row in sorted(walper.items()):
                    lines.append(
                        f'{series}{{{_lbl(server_id=sid)}}} '
                        f'{row.get(key, 0)}')
    except Exception:                           # noqa: BLE001
        pass                # consensus plane unavailable: skip series
    # wave-cohort drain accounting (utils/wavecohort.py): the plan
    # queue's wave-boundary batching — armed waves, landed plans,
    # whole-cohort drains vs expirations vs hard-cap clamps, and the
    # learned drain-window EWMA (ISSUE 11 satellite: the tracker
    # landed in ISSUE 10 without metrics)
    try:
        from nomad_tpu.utils.wavecohort import wave_cohorts

        c = wave_cohorts.snapshot()
        lines.append("# TYPE nomad_tpu_wave_cohort_waves_total counter")
        lines.append(f"nomad_tpu_wave_cohort_waves_total {c['waves']}")
        lines.append("# TYPE nomad_tpu_wave_cohort_plans_total counter")
        lines.append(
            f"nomad_tpu_wave_cohort_plans_total {c['cohort_plans']}")
        lines.append(
            "# TYPE nomad_tpu_wave_cohort_outcomes_total counter")
        for kind, key in (("drained", "drained_cohorts"),
                          ("expired", "expired_cohorts"),
                          ("hard_cap", "hard_cap_hits")):
            lines.append(
                f'nomad_tpu_wave_cohort_outcomes_total'
                f'{{kind="{kind}"}} {c[key]}')
        lines.append(
            "# TYPE nomad_tpu_wave_cohort_drain_ewma_seconds gauge")
        lines.append(
            f"nomad_tpu_wave_cohort_drain_ewma_seconds "
            f"{c['drain_ewma_ms'] / 1e3:.6f}")
    except Exception:                           # noqa: BLE001
        pass                # tracker unavailable: skip series
    # blocking-query wakeups (state/store.py watch_stats): the watch
    # side of the serving plane — parked watchers, real vs spurious
    # wakeups, expired waits
    try:
        from nomad_tpu.state.store import watch_stats

        w = watch_stats.snapshot()
        lines.append("# TYPE nomad_tpu_watch_held_watchers gauge")
        lines.append(
            f"nomad_tpu_watch_held_watchers {w['held_watchers']}")
        lines.append("# TYPE nomad_tpu_watch_wakeups_total counter")
        for kind, key in (("real", "wakeups"),
                          ("spurious", "spurious_wakeups"),
                          ("timeout", "timeouts")):
            lines.append(
                f'nomad_tpu_watch_wakeups_total{{kind="{kind}"}} '
                f'{w[key]}')
    except Exception:                           # noqa: BLE001
        pass                # store unavailable: skip series
    # MVCC store plane (state/store.py store_stats): write-transaction
    # and snapshot volume, the last committed generation, and how many
    # generation roots are still alive (pinned by snapshots or the
    # registry) — the retention gauge that catches a generation leak
    try:
        from nomad_tpu.state.store import store_stats

        st = store_stats.snapshot()
        lines.append("# TYPE nomad_tpu_store_write_txns_total counter")
        lines.append(
            f"nomad_tpu_store_write_txns_total {st['write_txns']}")
        lines.append("# TYPE nomad_tpu_store_snapshots_total counter")
        lines.append(
            f"nomad_tpu_store_snapshots_total {st['snapshots']}")
        lines.append("# TYPE nomad_tpu_store_restores_total counter")
        lines.append(
            f"nomad_tpu_store_restores_total {st['restores']}")
        lines.append("# TYPE nomad_tpu_store_generation gauge")
        lines.append(
            f"nomad_tpu_store_generation {st['last_generation']}")
        lines.append("# TYPE nomad_tpu_store_live_roots gauge")
        lines.append(
            f"nomad_tpu_store_live_roots {st['live_roots']}")
        # retention split (ISSUE 17): roots held by in-process snapshot
        # refs vs pinned by worker-process generation leases — a stuck
        # lease shows up as `holder="leased"` climbing while
        # `holder="in_process"` stays flat
        for holder, key in (("in_process", "live_roots_in_process"),
                            ("leased", "live_roots_leased")):
            lines.append(
                f'nomad_tpu_store_live_roots{{holder="{holder}"}} '
                f'{st[key]}')
    except Exception:                           # noqa: BLE001
        pass                # store unavailable: skip series
    # heartbeat fan-in (server/server.py client_update_stats): raw
    # heartbeat rate plus the Node.UpdateAlloc group-commit's
    # coalescing (callers vs batched raft entries)
    try:
        from nomad_tpu.server.server import client_update_stats

        u = client_update_stats.snapshot()
        lines.append("# TYPE nomad_tpu_heartbeats_total counter")
        lines.append(f"nomad_tpu_heartbeats_total {u['heartbeats']}")
        lines.append(
            "# TYPE nomad_tpu_client_update_fanin_total counter")
        for kind, key in (("callers", "callers"),
                          ("batches", "batches"),
                          ("allocs", "allocs")):
            lines.append(
                f'nomad_tpu_client_update_fanin_total'
                f'{{kind="{kind}"}} {u[key]}')
    except Exception:                           # noqa: BLE001
        pass                # server module unavailable: skip series
    # read plane (server/readplane.py, ISSUE 20): who served reads
    # (role), per-mode volume, follower fence forwards + retries +
    # failures, linearizable lease->barrier demotions, and max_stale
    # rejections. The staleness distribution itself rides the shared
    # histogram registry (op="read_staleness" below).
    try:
        from nomad_tpu.server.readplane import read_stats

        r = read_stats.snapshot()
        lines.append("# TYPE nomad_tpu_read_served_total counter")
        for role, n in sorted(r["served"].items()):
            lines.append(
                f'nomad_tpu_read_served_total{{role="{role}"}} {n}')
        lines.append("# TYPE nomad_tpu_read_requests_total counter")
        for mode, n in sorted(r["modes"].items()):
            lines.append(
                f'nomad_tpu_read_requests_total{{mode="{mode}"}} {n}')
        lines.append("# TYPE nomad_tpu_read_forwards_total counter")
        lines.append(f"nomad_tpu_read_forwards_total {r['forwards']}")
        lines.append(
            "# TYPE nomad_tpu_read_forward_retries_total counter")
        lines.append(
            f"nomad_tpu_read_forward_retries_total "
            f"{r['forward_retries']}")
        lines.append(
            "# TYPE nomad_tpu_read_forward_failures_total counter")
        lines.append(
            f"nomad_tpu_read_forward_failures_total "
            f"{r['forward_failures']}")
        lines.append("# TYPE nomad_tpu_read_demotions_total counter")
        lines.append(f"nomad_tpu_read_demotions_total {r['demotions']}")
        lines.append(
            "# TYPE nomad_tpu_read_lease_fast_total counter")
        lines.append(
            f"nomad_tpu_read_lease_fast_total {r['lease_fast']}")
        lines.append(
            "# TYPE nomad_tpu_read_stale_rejects_total counter")
        lines.append(
            f"nomad_tpu_read_stale_rejects_total {r['stale_rejects']}")
    except Exception:                           # noqa: BLE001
        pass                # server module unavailable: skip series
    # event-stream ring health (server/stream.py): publish/deliver
    # volume, slow-consumer losses, the widest subscriber lag, and the
    # wire bytes the NDJSON endpoint shipped — per-broker state, so
    # only present when the HTTP layer passes its server's broker
    if event_broker is not None:
        s = event_broker.snapshot()
        lines.append("# TYPE nomad_tpu_stream_subscribers gauge")
        lines.append(f"nomad_tpu_stream_subscribers {s['subscribers']}")
        lines.append("# TYPE nomad_tpu_stream_events_total counter")
        for kind, key in (("published", "published_events"),
                          ("delivered", "delivered_events"),
                          ("lost", "lost_events")):
            lines.append(
                f'nomad_tpu_stream_events_total{{kind="{kind}"}} '
                f'{s[key]}')
        lines.append("# TYPE nomad_tpu_stream_delivered_bytes_total counter")
        lines.append(
            f"nomad_tpu_stream_delivered_bytes_total "
            f"{s['delivered_bytes']}")
        lines.append("# TYPE nomad_tpu_stream_max_lag_events gauge")
        lines.append(
            f"nomad_tpu_stream_max_lag_events {s['max_lag_events']}")
        lines.append("# TYPE nomad_tpu_stream_retained_events gauge")
        lines.append(
            f"nomad_tpu_stream_retained_events {s['retained_events']}")
    # streaming latency histograms (telemetry/histogram.py): the real
    # Prometheus histogram type — log-bucketed cumulative _bucket
    # series per op (e2e eval latency, plan queue/evaluate/commit,
    # wave park, snapshot wait), the distribution substrate behind the
    # TRACE_DECOMP tail table and the flight recorder's threshold
    hist_items = [(name, h) for name, h in histograms.items()
                  if h.count > 0]
    if hist_items:
        lines.append("# TYPE nomad_tpu_latency_seconds histogram")
        for name, h in hist_items:
            lines.extend(h.prometheus_lines(
                "nomad_tpu_latency_seconds", _lbl(op=name)))
    # slow-eval flight recorder health: captures say the tail is being
    # recorded, threshold says where the adaptive p99 bar sits
    fr = flight_recorder.snapshot()
    lines.append(
        "# TYPE nomad_tpu_slow_evals_captured_total counter")
    lines.append(
        f"nomad_tpu_slow_evals_captured_total {fr['captured']}")
    lines.append("# TYPE nomad_tpu_slow_eval_threshold_seconds gauge")
    lines.append(
        f"nomad_tpu_slow_eval_threshold_seconds "
        f"{fr['threshold_ms'] / 1e3:.6f}")
    # consensus flight recorder health (ISSUE 15): slow raft appends /
    # fsync batches / elections captured past the adaptive bar
    cr = consensus_recorder.snapshot()
    lines.append("# TYPE nomad_tpu_slow_raft_captured_total counter")
    lines.append(
        f"nomad_tpu_slow_raft_captured_total {cr['captured']}")
    lines.append(
        "# TYPE nomad_tpu_telemetry_enabled gauge")
    lines.append(
        f"nomad_tpu_telemetry_enabled {1 if tracer.enabled else 0}")
    return "\n".join(lines) + "\n"


def traces_json(limit: int = 2000, trace_id: str = "") -> Dict:
    """The /v1/operator/traces body. ``trace_id`` narrows the span dump
    to one eval's tree (the ``?trace_id=`` query param — the operator's
    "show me THIS slow eval" handle; aggregates stay global)."""
    spans = tracer.spans(trace_id=trace_id or None)
    if limit and len(spans) > limit:
        spans = spans[-limit:]
    return {
        "Enabled": tracer.enabled,
        "TraceID": trace_id,
        "Spans": [s.to_api() for s in spans],
        "Stages": {
            name: {
                "Count": agg["count"],
                "TotalMs": round(agg["total_s"] * 1e3, 4),
                "ExclusiveMs": round(agg["exclusive_s"] * 1e3, 4),
            }
            for name, agg in tracer.stage_totals().items()
        },
        "Kernel": profiler.summary(),
    }


def stream_health_json(event_broker) -> Dict:
    """The /v1/operator/stream-health body: the serving plane's state
    in one pull — event-ring health, blocking-query wakeup accounting,
    heartbeat fan-in coalescing, and the delivery-lag distribution
    (the same ``stream_deliver`` series /v1/metrics exposes)."""
    from nomad_tpu.server.server import client_update_stats
    from nomad_tpu.state.store import watch_stats
    from nomad_tpu.telemetry.histogram import STREAM_DELIVER

    deliver = histograms.peek(STREAM_DELIVER)
    return {
        "Stream": event_broker.snapshot() if event_broker is not None
        else {},
        "Watch": watch_stats.snapshot(),
        "Heartbeat": client_update_stats.snapshot(),
        "DeliverLatency": deliver.snapshot() if deliver is not None
        else {},
    }


def cluster_health_json(server) -> Dict:
    """The ``GET /v1/operator/cluster-health`` body (ISSUE 15): the
    autopilot-style per-peer consensus picture from THIS server's
    vantage — raft identity/term/state + per-peer match/lag/contact
    (leader-side), its WAL occupancy + durability counters, the
    consensus latency distributions, election/term transition
    counters, the fault plane's arm state, and the consensus flight
    recorder's health."""
    from nomad_tpu.raft.observe import raft_observer
    from nomad_tpu.raft.wal import wal_stats
    from nomad_tpu.telemetry.histogram import (
        RAFT_APPEND,
        RAFT_ELECTION,
        RAFT_QUORUM,
        RAFT_REPLICATION,
        WAL_FSYNC,
    )
    from nomad_tpu.utils import faultpoints

    raft = server.raft
    if raft is not None:
        body = raft.cluster_health()
    else:
        body = {
            "ServerId": server.config.name,
            "State": "leader" if server.is_leader() else "follower",
            "Term": 0,
            "Leader": server.config.name if server.is_leader() else None,
            "CommitIndex": server.state.latest_index(),
            "LastApplied": server.state.latest_index(),
            "LastLogIndex": server.state.latest_index(),
            "Peers": [],
        }
    sid = body["ServerId"]
    obs = raft_observer.snapshot().get(sid, {})
    body["Transitions"] = obs.get("transitions", {})
    body["ReplicatedEntries"] = obs.get("replicated_entries", {})
    body["PeerLagMs"] = obs.get("peer_lag_ms", {})
    body["SnapshotTransferBytes"] = obs.get("snapshot_xfer_bytes", {})
    body["Wal"] = wal_stats.per_server().get(sid, {})
    body["Faults"] = {
        "Armed": faultpoints.armed(),
        "Points": faultpoints.stats(),
    }
    lat = {}
    for op in (RAFT_REPLICATION, RAFT_QUORUM, RAFT_APPEND,
               RAFT_ELECTION, WAL_FSYNC):
        h = histograms.peek(op)
        if h is not None and h.count > 0:
            lat[op] = h.snapshot()
    body["Latency"] = lat
    body["SlowRaft"] = consensus_recorder.snapshot()
    return body


def slow_raft_json(limit: int = 0) -> Dict:
    """The ``GET /v1/operator/slow-raft`` body: the consensus flight
    recorder's captured slow-op records (appends, fsync batches,
    elections past their adaptive thresholds), newest last, plus its
    health counters — the eval recorder's sibling (ISSUE 15)."""
    cr = consensus_recorder.snapshot()
    trees = consensus_recorder.trees()
    if limit and len(trees) > limit:
        trees = trees[-limit:]
    return {
        "Enabled": tracer.enabled,
        "Captured": cr["captured"],
        "Retained": cr["retained"],
        "ThresholdsMs": cr["thresholds_ms"],
        "Observed": cr["observed"],
        "Trees": trees,
    }


def slow_evals_json(limit: int = 0) -> Dict:
    """The /v1/operator/slow-evals body: the flight recorder's ring of
    captured slow-eval span trees, newest last, plus its health
    counters and the adaptive threshold."""
    fr = flight_recorder.snapshot()
    trees = flight_recorder.trees()
    if limit and len(trees) > limit:
        trees = trees[-limit:]
    return {
        "Enabled": tracer.enabled,
        "Observed": fr["observed"],
        "Captured": fr["captured"],
        "Retained": fr["retained"],
        "ThresholdMs": fr["threshold_ms"],
        "Histogram": {
            name: h.snapshot()
            for name, h in histograms.items() if h.count > 0
        },
        "Trees": trees,
    }
