"""The failover timeline: CHAOS_TIMELINE.json (ISSUE 15).

The chaos/restart cells (bench/trace_report.py) assert that a cluster
CONVERGES through injected failures; this module makes the run
EXPLAIN itself. Every server's consensus events (raft/observe.py:
elections, term adoptions, step-downs, kills, recoveries, snapshot
installs, leadership establishment), the fault plane's firings
(utils/faultpoints.fire_log), and a bounded summary of the consensus
span stream merge into one causally-ordered timeline artifact:

- Ordering: events that pin a raft index are ordered BY INDEX (raft
  indexes are the cluster's causal spine — an apply of index i on any
  server happened-after the leader's append of i, whatever the local
  clocks say). Everything else orders by monotonic clock, per-server
  skew-corrected: a per-server offset is estimated so that no
  index-pinned event precedes the earliest same-index event of a
  lower-or-equal index (in-process cells share one clock and the
  offsets resolve to 0; the hook exists for multi-process cells).
- Failover phase attribution: each leadership loss opens a failover
  window that the named phases partition — ``detect`` (loss → first
  election round), ``elect`` (first round → leader won, failed rounds
  included), ``replay`` (leader won → server-side leadership
  established: broker flush/restore, barrier apply), and ``converge``
  (last establishment → the cell's quiesce stamp). The attribution
  share (named-phase wall over total failover wall) is the CI-gated
  quantity, the way TRACE_DECOMP's coverage is gated.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["build_timeline", "validate_timeline",
           "merge_into_artifact", "PHASES"]

#: failover phase names, lifecycle order
PHASES = ("detect", "elect", "replay", "converge")

#: events that mean "the cluster lost its leader" when the server was
#: leading (each opens a failover window)
_LOSS_KINDS = ("stepdown", "killed", "wal_failed")


#: index-pinned event kinds stamped by the index's CREATOR (the
#: leader) — every other server's same-index event is causally AFTER
#: these, which is what makes them usable as skew anchors. Observer
#: kinds (snapshot_install) may legally lag the anchor by transfer
#: time, so they can never anchor.
_CREATOR_KINDS = ("snapshot_sent",)


def _estimate_offsets(events: Sequence[Dict]) -> Dict[str, float]:
    """Per-server monotonic-clock offsets from index-pinned causality:
    a raft index's CREATOR event (the leader's stamp) anchors it; a
    DIFFERENT server whose same-index event sits EARLIER than the
    anchor after correction has a clock behind by at least the
    difference and gets shifted forward. Indexes with no creator event
    contribute no anchor (an early observer stamp proves nothing —
    observers legally lag the creation by transfer time), so
    shared-clock (in-process) cells resolve to all-zero offsets."""
    anchors: Dict[int, Tuple[float, str]] = {}
    for ev in events:
        idx = ev.get("index")
        if idx is None or ev["kind"] not in _CREATOR_KINDS:
            continue
        t = ev["t"]
        # a per-peer re-send repeats the creator stamp; the EARLIEST
        # is the true creation lower bound
        if idx not in anchors or t < anchors[idx][0]:
            anchors[idx] = (t, ev["server"])
    offsets: Dict[str, float] = {}
    for ev in events:
        idx = ev.get("index")
        if idx is None or idx not in anchors:
            continue
        anchor_t, anchor_server = anchors[idx]
        if ev["server"] == anchor_server:
            continue
        # causality: an index-pinned event cannot precede the index's
        # creation; if this server's clock says it did, its clock is
        # behind by at least the difference
        lag = anchor_t - (ev["t"] + offsets.get(ev["server"], 0.0))
        if lag > 0.0:
            offsets[ev["server"]] = offsets.get(ev["server"], 0.0) + lag
    return offsets


def _order_events(events: Sequence[Dict],
                  offsets: Optional[Dict[str, float]] = None) -> List[Dict]:
    """Causal order: skew-corrected monotonic sort, then the
    index-pinned subsequence is re-ordered by raft index in place
    (positions stay where the clocks put them; VALUES obey the index
    spine — the standard pinned-subsequence discipline)."""
    if offsets is None:
        offsets = _estimate_offsets(events)
    rows = [dict(ev) for ev in events]
    for ev in rows:
        ev["t_corrected"] = ev["t"] + offsets.get(ev["server"], 0.0)
    rows.sort(key=lambda e: e["t_corrected"])
    pinned_pos = [i for i, e in enumerate(rows) if e.get("index")]
    pinned = sorted((rows[i] for i in pinned_pos),
                    key=lambda e: (e["index"], e["t_corrected"]))
    for pos, ev in zip(pinned_pos, pinned):
        rows[pos] = ev
    return rows


def _failovers(ordered: List[Dict],
               converged_mono: Optional[float]) -> List[Dict]:
    """Scan the ordered events into failover windows with per-phase
    attribution. Phases partition loss→established by construction;
    anything un-spanned (a missing event) stays unattributed and
    lowers the share — honest, never hidden.

    A PARTITIONED leader never emits a loss event (it still thinks it
    leads until the heal), so its failover is detected from the other
    side: a server winning leadership away from a tracked leader that
    never reported loss opens a ``partition`` window, backdated to
    that server's election start. The mirror case — the stale
    leader's stepdown when the heal delivers it the higher term — is
    NOT a leadership loss (the cluster already moved on), so loss
    events from a superseded leader are dropped rather than opening a
    window that no election will ever close."""
    out: List[Dict] = []
    open_fo: Optional[Dict] = None
    cur_leader: Optional[str] = None
    last_elect: Dict[str, float] = {}
    for ev in ordered:
        kind, t = ev["kind"], ev["t_corrected"]
        was_leader = bool((ev.get("detail") or {}).get("was_leader"))
        if kind == "election_start":
            last_elect[ev["server"]] = t
        # only the LEADER's loss opens a failover — a killed or
        # fail-stopped follower is an event, not a leadership loss
        # (every loss-kind emitter stamps detail.was_leader)
        if kind in _LOSS_KINDS and was_leader:
            if cur_leader is not None and ev["server"] != cur_leader:
                # stale-leader correction after a heal: leadership
                # already moved (tracked from the winner's side)
                continue
            if open_fo is None:
                open_fo = {"loss_t": t, "loss_kind": kind,
                           "leader_from": ev["server"],
                           "term_from": ev.get("term")}
            continue
        if kind == "leader_won" and open_fo is None \
                and cur_leader is not None and ev["server"] != cur_leader:
            # leadership moved without a loss event: the old leader is
            # partitioned, not dead. Backdate to the winner's election
            # start so detect/elect stay honestly attributed.
            loss_t = last_elect.get(ev["server"], t)
            open_fo = {"loss_t": loss_t, "loss_kind": "partition",
                       "leader_from": cur_leader,
                       "term_from": None,
                       "elect_t": loss_t}
        if kind == "leader_won":
            cur_leader = ev["server"]
        if open_fo is None:
            continue
        if kind == "election_start" and "elect_t" not in open_fo:
            open_fo["elect_t"] = t
        elif kind == "leader_won" and "won_t" not in open_fo:
            open_fo["won_t"] = t
            open_fo["leader_to"] = ev["server"]
            open_fo["term_to"] = ev.get("term")
        elif kind == "established" and "won_t" in open_fo:
            open_fo["established_t"] = t
            out.append(open_fo)
            open_fo = None
    if open_fo is not None:
        if "won_t" not in open_fo:
            # leadership lost and never re-won before the cell ended:
            # the worst failover must not vanish from the timeline —
            # keep the window (closed at the cell's end stamp below)
            # with the un-elected tail left unattributed, so the
            # share drops instead of reading 1.0
            open_fo["unresolved"] = True
        # else: leadership won but establishment never observed (e.g.
        # the cell stopped first) — keep the partial window,
        # unattributed tail included
        out.append(open_fo)

    rendered = []
    for k, fo in enumerate(out):
        loss = fo["loss_t"]
        elect_t = fo.get("elect_t")
        won_t = fo.get("won_t")
        est_t = fo.get("established_t")
        end = est_t if est_t is not None else (won_t or loss)
        if fo.get("unresolved"):
            last_t = ordered[-1]["t_corrected"] if ordered else loss
            end = max(converged_mono if converged_mono is not None
                      else last_t, loss)
        phases = {
            "detect": max(elect_t - loss, 0.0)
            if elect_t is not None else 0.0,
            "elect": max(won_t - elect_t, 0.0)
            if elect_t is not None and won_t is not None else 0.0,
            "replay": max(est_t - won_t, 0.0)
            if est_t is not None and won_t is not None else 0.0,
            "converge": 0.0,
        }
        if k == len(out) - 1 and converged_mono is not None \
                and est_t is not None and converged_mono > est_t:
            phases["converge"] = converged_mono - est_t
            end = converged_mono
        total = max(end - loss, 0.0)
        attributed = sum(phases.values())
        rendered.append({
            "loss_kind": fo["loss_kind"],
            "resolved": not fo.get("unresolved", False),
            "leader_from": fo.get("leader_from"),
            "leader_to": fo.get("leader_to"),
            "term_from": fo.get("term_from"),
            "term_to": fo.get("term_to"),
            "start_t": loss,
            "total_ms": round(total * 1e3, 3),
            "phases_ms": {p: round(phases[p] * 1e3, 3) for p in PHASES},
            "attributed_ms": round(attributed * 1e3, 3),
            "attributed_share": round(attributed / total, 4)
            if total > 0 else 1.0,
        })
    return rendered


def build_timeline(events: Sequence[Dict],
                   fault_fires: Sequence[Dict] = (),
                   span_summary: Optional[Dict[str, int]] = None,
                   converged_mono: Optional[float] = None,
                   offsets: Optional[Dict[str, float]] = None,
                   cell: str = "") -> Dict:
    """Merge one cell's consensus events + fault firings (+ a span
    summary) into the CHAOS_TIMELINE shape. ``converged_mono`` is the
    cell's quiesce stamp (monotonic) closing the last failover's
    converge phase."""
    if offsets is None:
        offsets = _estimate_offsets(events)
    ordered = _order_events(events, offsets)
    failovers = _failovers(ordered, converged_mono)
    stamps = [e["t_corrected"] for e in ordered]
    stamps += [f["t"] for f in fault_fires]
    t0 = min(stamps) if stamps else 0.0

    total_ms = sum(f["total_ms"] for f in failovers)
    attributed_ms = sum(f["attributed_ms"] for f in failovers)
    per_server: Dict[str, int] = {}
    for ev in ordered:
        per_server[ev["server"]] = per_server.get(ev["server"], 0) + 1
    return {
        "cell": cell,
        "events": [
            {
                "t_ms": round((ev["t_corrected"] - t0) * 1e3, 3),
                "server": ev["server"],
                "kind": ev["kind"],
                **{k: ev[k] for k in ("term", "index", "detail")
                   if k in ev},
            }
            for ev in ordered
        ],
        "fault_fires": [
            {"t_ms": round((f["t"] - t0) * 1e3, 3),
             "point": f["point"], "kind": f["kind"]}
            for f in fault_fires
        ],
        "servers": per_server,
        "clock_offsets_ms": {s: round(o * 1e3, 3)
                             for s, o in offsets.items() if o},
        "span_summary": span_summary or {},
        "failovers": failovers,
        "attribution": {
            "failover_wall_ms": round(total_ms, 3),
            "attributed_ms": round(attributed_ms, 3),
            "share": round(attributed_ms / total_ms, 4)
            if total_ms > 0 else 1.0,
        },
    }


def validate_timeline(tl: Dict) -> List[str]:
    """Shape check for the CI gates (the TRACE_DECOMP discipline):
    returns the list of problems, empty when the artifact is valid."""
    problems: List[str] = []
    for key in ("cell", "events", "fault_fires", "servers",
                "failovers", "attribution"):
        if key not in tl:
            problems.append(f"missing key {key!r}")
    if problems:
        return problems
    last = -1.0
    for i, ev in enumerate(tl["events"]):
        for key in ("t_ms", "server", "kind"):
            if key not in ev:
                problems.append(f"event {i} missing {key!r}")
        if "index" in ev:
            # index-pinned events sit where causality puts them; their
            # clock stamps may legally back-step vs neighbors
            continue
        t = ev.get("t_ms", 0.0)
        if t < last - 1e-6:
            problems.append(
                f"event {i} out of order ({t} after {last})")
        last = t
    # index-pinned events must be monotone in index
    pinned = [ev["index"] for ev in tl["events"] if "index" in ev]
    if pinned != sorted(pinned):
        problems.append("index-pinned events violate raft-index order")
    for i, fo in enumerate(tl["failovers"]):
        phases = fo.get("phases_ms", {})
        if set(phases) != set(PHASES):
            problems.append(f"failover {i} phases {sorted(phases)}")
            continue
        if fo["attributed_ms"] > fo["total_ms"] + 1e-6:
            problems.append(f"failover {i} over-attributed")
    att = tl["attribution"]
    if not (0.0 <= att.get("share", -1) <= 1.0):
        problems.append(f"attribution share {att.get('share')}")
    return problems


def merge_into_artifact(path: str, section: str, tl: Dict,
                        summary_extra: Optional[Dict] = None) -> Dict:
    """Write ``tl`` under ``section`` of the CHAOS_TIMELINE.json
    artifact, merging with whatever other cells already wrote, and
    refresh the top-level attribution summary across sections."""
    doc: Dict = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
    sections = doc.get("cells", {})
    sections[section] = tl
    total = sum(c["attribution"]["failover_wall_ms"]
                for c in sections.values())
    attributed = sum(c["attribution"]["attributed_ms"]
                     for c in sections.values())
    # earlier cells' summary_extra keys survive later merges: start
    # from the existing doc and overwrite only the recomputed keys
    doc.pop("cells", None)
    doc.update({
        "cells": sections,
        "failovers": sum(len(c["failovers"]) for c in sections.values()),
        "events": sum(len(c["events"]) for c in sections.values()),
        "fault_fires": sum(len(c["fault_fires"])
                           for c in sections.values()),
        "attribution": {
            "failover_wall_ms": round(total, 3),
            "attributed_ms": round(attributed, 3),
            "share": round(attributed / total, 4) if total > 0 else 1.0,
        },
    })
    if summary_extra:
        doc.update(summary_extra)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    return doc
