"""Per-eval critical-path waterfalls and tail aggregation.

TRACE_DECOMP's stage table answers "where does the *mean* eval
millisecond go"; the tail question — why is p99 4.6x p50 (BENCH_r05:
plan p99 59ms vs p50 26ms) — needs the decomposition *per eval*, then
compared between the median cohort and the slowest cohort. This module
reduces one eval's span tree (everything sharing its ``trace_id``,
which IS the eval id on the instrumented hot path) to an ordered,
non-overlapping segment waterfall over the eval's e2e window
(broker-enqueue → commit, carried by the ``eval.e2e`` marker span the
worker records at ack time), then aggregates waterfalls into the
``tail`` table: per-segment latency share at p50 vs at p99.

Reduction rules (Dapper-style critical path, adapted to this repo's
concurrency shape):

- Per-trace spans claim their own wall intervals, most-specific first
  (``plan.queue_wait`` beats ``plan.wait`` beats ``eval.schedule``) —
  a child's time never double-counts against its envelope.
- The applier/FSM spans are *batch* envelopes on other threads and
  carry no per-eval trace id; for each eval they claim, by time
  overlap, the part of that eval's ``plan.wait`` window they cover.
  That is exactly the critical-path semantics: while the worker blocks
  in submit, whatever the applier is doing IS this eval's latency.
- ``dequeue-wait`` is the gap from broker enqueue to the eval's
  schedule span — ready-queue time plus the batch's shared
  snapshot/fan-out (those spans carry the batch leader's trace id, so
  for the other members they are honest queue-shaped waiting).
- Whatever no rule claims is reported as ``other``, never hidden —
  coverage (claimed / e2e) is a CI gate, not an assumption.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from nomad_tpu.telemetry.histogram import percentile

__all__ = ["build_waterfall", "build_waterfalls", "aggregate_tail",
           "SEGMENT_ORDER"]

#: waterfall display order (≈ lifecycle order). The raft-* segments
#: (ISSUE 15) live INSIDE the commit window: replicate (AppendEntries
#: on the wire), fsync (the leader's group fsync), quorum (append →
#: majority commit residue), apply (raft apply-loop dispatch around
#: the FSM).
SEGMENT_ORDER = [
    "dequeue-wait", "snapshot", "schedule", "park", "launch",
    "plan-queue", "evaluate", "commit", "raft-replicate", "raft-fsync",
    "raft-quorum", "raft-apply", "fsm", "plan-wait", "other",
]

#: per-trace span name -> (segment, claim priority). Higher priority
#: claims wall first; lower-priority intervals keep only what is left.
_PER_TRACE = {
    "plan.queue_wait": ("plan-queue", 90),
    "wave.launch": ("launch", 80),
    "wave.park": ("park", 70),
    "worker.snapshot": ("snapshot", 60),
    "plan.wait": ("plan-wait", 20),
    "eval.schedule": ("schedule", 10),
}

#: batch-envelope span names (no per-eval trace id): claimed by
#: overlap with the eval's plan.wait window. fsm nests inside commit
#: and per-plan evaluation inside the evaluate envelope, so priority
#: runs leaf-out. The raft segments (ISSUE 15) follow the same
#: greedy-interval discipline inside the commit envelope: fsync and
#: replicate are disjoint leaf windows on the disk/network threads
#: (claimed first), quorum is the append→commit window residue those
#: two leave behind, raft-apply wraps the FSM dispatch so fsm (110)
#: claims first and raft-apply keeps the dispatch residue — together
#: they PARTITION the commit window exactly (property-tested in
#: tests/test_consensus_observability.py).
_GLOBAL = {
    "raft.fsync": ("raft-fsync", 130),
    "raft.replicate": ("raft-replicate", 125),
    "raft.quorum": ("raft-quorum", 112),
    "fsm.apply": ("fsm", 110),
    "raft.apply": ("raft-apply", 108),
    "plan.commit": ("commit", 105),
    "plan.evaluate": ("evaluate", 100),
}

_E2E_SPAN = "eval.e2e"

_Interval = Tuple[float, float]


def _clip(iv: _Interval, lo: float, hi: float) -> Optional[_Interval]:
    s, e = max(iv[0], lo), min(iv[1], hi)
    return (s, e) if e > s else None


def _subtract(iv: _Interval,
              claimed: Sequence[_Interval]) -> List[_Interval]:
    """``iv`` minus the (sorted, disjoint) claimed intervals."""
    out: List[_Interval] = []
    s, e = iv
    for cs, ce in claimed:
        if ce <= s:
            continue
        if cs >= e:
            break
        if cs > s:
            out.append((s, cs))
        s = max(s, ce)
        if s >= e:
            break
    if s < e:
        out.append((s, e))
    return out


def _claim(claimed: List[_Interval], iv: _Interval) -> float:
    """Claim ``iv``'s unclaimed part; returns the seconds claimed and
    keeps ``claimed`` sorted + disjoint."""
    got = _subtract(iv, claimed)
    if not got:
        return 0.0
    claimed.extend(got)
    claimed.sort()
    # merge adjacency so the list stays small
    merged: List[_Interval] = []
    for s, e in claimed:
        if merged and s <= merged[-1][1]:
            if e > merged[-1][1]:
                merged[-1] = (merged[-1][0], e)
        else:
            merged.append((s, e))
    claimed[:] = merged
    return sum(e - s for s, e in got)


def build_waterfall(trace_spans: Sequence,
                    global_spans: Sequence = ()) -> Optional[Dict]:
    """Reduce one eval's spans to its critical-path waterfall.

    ``trace_spans``: every span with the eval's trace id (must include
    the ``eval.e2e`` marker). ``global_spans``: applier/FSM batch
    envelopes (any trace id); only their overlap with this eval's
    ``plan.wait`` windows is attributed. Returns None when no e2e
    marker exists (the eval never committed, or the ring wrapped past
    it).
    """
    e2e = None
    for s in trace_spans:
        if s.name == _E2E_SPAN:
            e2e = s
    if e2e is None:
        return None
    w0, w1 = e2e.start_s, e2e.start_s + e2e.dur_s
    if w1 <= w0:
        return None

    # candidate claims: (priority, order, segment, interval)
    cands: List[Tuple[int, int, str, _Interval]] = []
    sched_start = None
    wait_windows: List[_Interval] = []
    for s in trace_spans:
        tgt = _PER_TRACE.get(s.name)
        if tgt is None:
            continue
        iv = _clip((s.start_s, s.start_s + s.dur_s), w0, w1)
        if iv is None:
            continue
        seg, prio = tgt
        cands.append((prio, len(cands), seg, iv))
        if s.name == "eval.schedule":
            sched_start = iv[0] if sched_start is None \
                else min(sched_start, iv[0])
        elif s.name == "plan.wait":
            wait_windows.append(iv)
    for s in global_spans:
        tgt = _GLOBAL.get(s.name)
        if tgt is None:
            continue
        seg, prio = tgt
        for win in wait_windows:
            iv = _clip((s.start_s, s.start_s + s.dur_s), win[0], win[1])
            if iv is not None:
                cands.append((prio, len(cands), seg, iv))
    if sched_start is not None and sched_start > w0:
        cands.append((15, len(cands), "dequeue-wait", (w0, sched_start)))

    claimed: List[_Interval] = []
    segments: Dict[str, float] = {}
    for prio, _, seg, iv in sorted(cands, key=lambda c: -c[0]):
        got = _claim(claimed, iv)
        if got > 0.0:
            segments[seg] = segments.get(seg, 0.0) + got
    covered = sum(e - s for s, e in claimed)
    e2e_s = w1 - w0
    other = max(e2e_s - covered, 0.0)
    if other > 0.0:
        segments["other"] = other
    return {
        "trace_id": e2e.trace_id,
        "e2e_s": e2e_s,
        "segments": segments,
        "covered_s": covered,
        "coverage": covered / e2e_s,
    }


def build_waterfalls(spans: Iterable) -> List[Dict]:
    """Group a span dump by trace id and reduce every eval that has an
    ``eval.e2e`` marker."""
    by_trace: Dict[str, List] = {}
    global_spans: List = []
    for s in spans:
        if s.name in _GLOBAL:
            global_spans.append(s)
        elif s.trace_id:
            by_trace.setdefault(s.trace_id, []).append(s)
    out = []
    for trace_spans in by_trace.values():
        wf = build_waterfall(trace_spans, global_spans)
        if wf is not None:
            out.append(wf)
    return out


def aggregate_tail(waterfalls: List[Dict],
                   p50_band: Tuple[float, float] = (0.25, 0.75),
                   tail_q: float = 0.99) -> Dict:
    """Fold per-eval waterfalls into the TRACE_DECOMP ``tail`` table:
    per-segment latency share for the median cohort (evals between the
    p50 band's quantiles) vs the tail cohort (evals at/above the
    ``tail_q`` latency). Shares are cohort-sum over cohort-sum — the
    "of a p99 eval's milliseconds, how many went to segment X"
    quantity.
    """
    if not waterfalls:
        return {"e2e_count": 0, "segments": {}, "p50_coverage": 0.0,
                "p99_coverage": 0.0, "p50_cohort": 0, "p99_cohort": 0}
    lats = [w["e2e_s"] for w in waterfalls]
    lo = percentile(lats, p50_band[0])
    hi = percentile(lats, p50_band[1])
    tail_cut = percentile(lats, tail_q)
    # both cohorts are non-empty by construction: nearest-rank
    # percentile returns an actual sample, so the waterfall carrying
    # ``lo`` is in the band and the max is always >= tail_cut
    mid = [w for w in waterfalls if lo <= w["e2e_s"] <= hi]
    tail = [w for w in waterfalls if w["e2e_s"] >= tail_cut]

    def cohort(rows: List[Dict]) -> Tuple[Dict[str, float], float, float]:
        tot = sum(w["e2e_s"] for w in rows)
        segs: Dict[str, float] = {}
        for w in rows:
            for seg, secs in w["segments"].items():
                segs[seg] = segs.get(seg, 0.0) + secs
        cov = sum(w["covered_s"] for w in rows)
        return segs, tot, cov

    mid_segs, mid_tot, mid_cov = cohort(mid)
    tail_segs, tail_tot, tail_cov = cohort(tail)
    table: Dict[str, Dict] = {}
    for seg in SEGMENT_ORDER:
        m, t = mid_segs.get(seg, 0.0), tail_segs.get(seg, 0.0)
        if m == 0.0 and t == 0.0:
            continue
        table[seg] = {
            "p50_ms": round(m / len(mid) * 1e3, 4),
            "p50_share": round(m / mid_tot, 4) if mid_tot else 0.0,
            "p99_ms": round(t / len(tail) * 1e3, 4),
            "p99_share": round(t / tail_tot, 4) if tail_tot else 0.0,
        }
    slowest = sorted(waterfalls, key=lambda w: -w["e2e_s"])[:3]
    return {
        "e2e_count": len(waterfalls),
        "e2e_p50_ms": round(percentile(lats, 0.5) * 1e3, 3),
        "e2e_p90_ms": round(percentile(lats, 0.9) * 1e3, 3),
        "e2e_p99_ms": round(percentile(lats, 0.99) * 1e3, 3),
        "segments": table,
        # the coverage gates: "other" is excluded from covered_s by
        # construction, so this is the fraction of cohort latency the
        # NAMED segments explain
        "p50_coverage": round(mid_cov / mid_tot, 4) if mid_tot else 0.0,
        "p99_coverage": round(tail_cov / tail_tot, 4)
        if tail_tot else 0.0,
        "p50_cohort": len(mid),
        "p99_cohort": len(tail),
        "slowest": [
            {"trace_id": w["trace_id"],
             "e2e_ms": round(w["e2e_s"] * 1e3, 3),
             "segments_ms": {k: round(v * 1e3, 3)
                             for k, v in sorted(
                                 w["segments"].items(),
                                 key=lambda kv: -kv[1])}}
            for w in slowest
        ],
    }
