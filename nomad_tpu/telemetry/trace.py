"""Span tracing for the eval lifecycle.

The reference instruments every hot component with go-metrics timers
(eval_broker.go, plan_apply.go, worker.go all carry
``defer metrics.MeasureSince(...)``); this subsystem goes one step
further and records *spans* — named, nested, per-thread intervals on a
monotonic clock — so the live path's per-eval wall time can be
decomposed stage by stage (BENCH_r05's unexplained 25x TPU/CPU gap is
exactly a missing decomposition).

Design constraints, in order:

- **~zero cost when disabled.** ``span()`` is one attribute check and
  returns a shared no-op context manager; no allocation, no lock, no
  clock read. The live path stays within noise of the uninstrumented
  build when tracing is off.
- **Thread-safe.** Spans nest per-thread via ``threading.local`` stacks
  (no cross-thread mutation); completed spans land in a bounded ring
  buffer plus per-name aggregates under one short lock.
- **Bounded.** The ring holds the newest ``capacity`` spans; aggregates
  (count / total / exclusive seconds per name) never lose data, so a
  long burst still decomposes exactly even after the ring wraps.
- **Exclusive time is first-class.** A span's *exclusive* duration is
  its wall duration minus its same-thread children — the quantity a
  stage decomposition can sum without double counting (a scheduler span
  that parks inside a kernel wave must not claim the wave's time).

Cross-thread propagation: a thread that fans work out captures
``tracer.context()`` and workers re-parent under it with
``tracer.attach(ctx)`` — the worker's spans then carry the originating
trace id (threads do not inherit ``threading.local`` state).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

__all__ = ["Span", "Tracer", "tracer", "FlightRecorder", "flight_recorder",
           "ConsensusRecorder", "consensus_recorder"]

_ids = itertools.count(1)


class Span:
    """One completed interval. Attributes are kept flat and small —
    spans are recorded on the hot path.

    Each span carries TWO clocks: wall (monotonic) and the owning
    thread's CPU time (``time.thread_time``). Wall answers "how long
    did this stage hold the critical path"; CPU answers "how much work
    did this stage execute". The distinction matters under the GIL: B
    concurrently-scheduled eval threads each see ~the whole phase as
    wall time, but their CPU times sum to the work actually done — the
    stage decomposition sums CPU for host stages and wall for
    device-blocking stages, so neither is double counted."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_s",
                 "dur_s", "child_s", "cpu_s", "child_cpu_s", "thread")

    def __init__(self, name: str, trace_id: str, span_id: int,
                 parent_id: int, start_s: float, dur_s: float,
                 child_s: float, cpu_s: float, child_cpu_s: float,
                 thread: str) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_s = start_s
        self.dur_s = dur_s
        self.child_s = child_s
        self.cpu_s = cpu_s
        self.child_cpu_s = child_cpu_s
        self.thread = thread

    @property
    def exclusive_s(self) -> float:
        return max(self.dur_s - self.child_s, 0.0)

    @property
    def exclusive_cpu_s(self) -> float:
        return max(self.cpu_s - self.child_cpu_s, 0.0)

    def to_api(self) -> Dict:
        """The wire shape /v1/operator/traces serves."""
        return {
            "Name": self.name,
            "TraceID": self.trace_id,
            "SpanID": self.span_id,
            "ParentID": self.parent_id,
            "Start": round(self.start_s, 6),
            "DurationMs": round(self.dur_s * 1e3, 4),
            "ExclusiveMs": round(self.exclusive_s * 1e3, 4),
            "CpuMs": round(self.cpu_s * 1e3, 4),
            "ExclusiveCpuMs": round(self.exclusive_cpu_s * 1e3, 4),
            "Thread": self.thread,
        }


class _NoopSpan:
    """Shared disabled-mode context manager: no state, no clock."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NOOP = _NoopSpan()


class _LiveSpan:
    """An open span on one thread's stack."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "t0", "c0", "child_s", "child_cpu_s", "sampled")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: int, sampled: bool) -> None:
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = next(_ids)
        self.parent_id = parent_id
        self.child_s = 0.0
        self.child_cpu_s = 0.0
        self.t0 = 0.0
        self.c0 = 0.0
        self.sampled = sampled

    def __enter__(self) -> "_LiveSpan":
        self.tracer._tls_stack().append(self)
        self.t0 = time.monotonic()
        # the CPU clock is read AFTER the wall clock and only on
        # sampled trees: on kernels where CLOCK_THREAD_CPUTIME_ID is a
        # real syscall (no vDSO) each read costs tens of µs — see
        # Tracer._calibrate
        self.c0 = time.thread_time() if self.sampled else 0.0
        return self

    def __exit__(self, *exc) -> None:
        # clock geometry on a sampled span: t0 is captured BEFORE the
        # enter CPU read and dur after the exit CPU read, so both
        # expensive reads' WALL lands inside this span's own window —
        # while their CPU is excluded from this span's cpu (c0 is
        # captured at the END of the enter read, the exit value before
        # its cost) and lands in the PARENT's CPU window instead
        cpu = (time.thread_time() - self.c0) if self.sampled else 0.0
        dur = time.monotonic() - self.t0
        stack = self.tracer._tls_stack()
        # unwind to self: an exception may have skipped children's exits
        while stack and stack[-1] is not self:
            stack.pop()
        if stack:
            stack.pop()
        comp = self.tracer._cpu_read_cost * 2.0 if self.sampled else 0.0
        if comp:
            # shed the two reads' wall from this span's own duration —
            # the recorded span measures the system, not the tracer
            dur = max(dur - comp, 0.0)
        if stack:
            parent = stack[-1]
            if self.sampled:
                # the parent still lost the FULL window (adjusted dur
                # + the reads' wall) and the reads' syscall CPU; credit
                # both to child time so the parent's EXCLUSIVE stage —
                # the quantity the decomposition gates on — stays
                # unbiased
                parent.child_s += dur + comp
                parent.child_cpu_s += cpu + comp
            else:
                parent.child_s += dur
        self.tracer._record(self, dur, cpu)


class _Attach:
    __slots__ = ("tracer", "ctx", "prev")

    def __init__(self, tracer: "Tracer", ctx) -> None:
        self.tracer = tracer
        self.ctx = ctx

    def __enter__(self) -> "_Attach":
        tls = self.tracer._tls
        self.prev = getattr(tls, "inherit", None)
        tls.inherit = self.ctx
        return self

    def __exit__(self, *exc) -> None:
        self.tracer._tls.inherit = self.prev


class Tracer:
    def __init__(self, capacity: int = 16384) -> None:
        self._enabled = False
        self._lock = threading.Lock()
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        # name -> [count, total_s, exclusive_s, cpu_s, exclusive_cpu_s]
        self._agg: Dict[str, List[float]] = {}
        self._tls = threading.local()
        self.enabled_at: Optional[float] = None
        #: CPU-clock sampling: 1 = read thread_time on every span
        #: (exact; the normal case). On kernels where the clock is an
        #: un-vDSO'd syscall, whole span TREES are sampled 1-in-K and
        #: their CPU contributions scaled by K — unbiased aggregates
        #: at a bounded instrumentation cost (see _calibrate).
        self.cpu_sample_every = 1
        self._cpu_read_cost = 0.0
        self._root_seq = itertools.count()

    # --- control --------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._calibrate()
        self.enabled_at = time.monotonic()
        self._enabled = True

    def _calibrate(self) -> None:
        """Measure the CPU clock's read cost and pick the tree-sampling
        rate. ``time.thread_time`` is ~0.1µs through the vDSO on
        production kernels (every span reads it: exact attribution),
        but tens of µs as a real syscall under sandboxed/older kernels
        — at 4 reads per span site an instrumented eval would owe more
        CPU to the tracer than to scheduling, and the decomposition
        would gate on the instrument instead of the system. Sampling
        1-in-K span trees (scaled by K) keeps aggregates unbiased and
        the overhead bounded; per-span compensation (_LiveSpan.__exit__)
        removes the residual bias from the sampled trees themselves."""
        reads = 64
        t0 = time.perf_counter()
        for _ in range(reads):
            time.thread_time()
        cost = (time.perf_counter() - t0) / reads
        self._cpu_read_cost = cost
        if cost < 2e-6:
            self.cpu_sample_every = 1
        else:
            # cap at 4: the variance of the scaled estimate grows with
            # K, and host stages gate CI — a 4x overhead cut already
            # brings the syscall tax under the stage costs it measures
            self.cpu_sample_every = min(4, max(2, int(cost / 5e-6)))

    def disable(self) -> None:
        self._enabled = False

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._agg.clear()
        if self._enabled:
            self.enabled_at = time.monotonic()

    # --- recording ------------------------------------------------------

    def _tls_stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def span(self, name: str, trace_id: str = ""):
        """Open a span. The ONLY hot-path entry point: when disabled it
        returns a shared no-op without reading the clock."""
        if not self._enabled:
            return _NOOP
        stack = self._tls_stack()
        if stack:
            # children inherit the root's CPU-sampling decision so the
            # parent/child exclusive arithmetic stays consistent
            # within one tree
            parent = stack[-1]
            return _LiveSpan(self, name, trace_id or parent.trace_id,
                             parent.span_id, parent.sampled)
        sampled = self.cpu_sample_every == 1 or (
            next(self._root_seq) % self.cpu_sample_every == 0)
        inherit = getattr(self._tls, "inherit", None)
        if inherit is not None:
            return _LiveSpan(self, name, trace_id or inherit[0],
                             inherit[1], sampled)
        return _LiveSpan(self, name, trace_id, 0, sampled)

    def record(self, name: str, dur_s: float, trace_id: str = "") -> None:
        """Record an already-measured interval as a leaf span (for
        sites that must decide retroactively, e.g. a blocking dequeue
        that only counts when it returned work)."""
        if not self._enabled:
            return
        stack = self._tls_stack()
        parent_id = stack[-1].span_id if stack else 0
        if stack:
            stack[-1].child_s += dur_s
            trace_id = trace_id or stack[-1].trace_id
        # after-the-fact records carry no CPU reading (they are mostly
        # blocking waits); cpu_s=0 keeps them out of CPU attributions
        sp = Span(name, trace_id, next(_ids), parent_id,
                  time.monotonic() - dur_s, dur_s, 0.0, 0.0, 0.0,
                  threading.current_thread().name)
        self._append(sp, 0)

    def _record(self, live: _LiveSpan, dur_s: float, cpu_s: float) -> None:
        sp = Span(live.name, live.trace_id, live.span_id, live.parent_id,
                  live.t0, dur_s, live.child_s, cpu_s, live.child_cpu_s,
                  threading.current_thread().name)
        self._append(sp, self.cpu_sample_every if live.sampled else 0)

    def _append(self, sp: Span, cpu_scale: int = 1) -> None:
        # ring entries keep the raw per-span reading (0 on unsampled
        # trees); AGGREGATES scale sampled CPU by the sampling rate so
        # stage_totals stays an unbiased estimate of work executed
        with self._lock:
            self._ring.append(sp)
            agg = self._agg.get(sp.name)
            if agg is None:
                self._agg[sp.name] = [1, sp.dur_s, sp.exclusive_s,
                                      sp.cpu_s * cpu_scale,
                                      sp.exclusive_cpu_s * cpu_scale]
            else:
                agg[0] += 1
                agg[1] += sp.dur_s
                agg[2] += sp.exclusive_s
                agg[3] += sp.cpu_s * cpu_scale
                agg[4] += sp.exclusive_cpu_s * cpu_scale

    # --- propagation ----------------------------------------------------

    def context(self) -> Optional[Tuple[str, int]]:
        """(trace_id, span_id) of the calling thread's open span, for
        hand-off to worker threads via ``attach``."""
        if not self._enabled:
            return None
        stack = getattr(self._tls, "stack", None)
        if stack:
            return (stack[-1].trace_id, stack[-1].span_id)
        return None

    def attach(self, ctx: Optional[Tuple[str, int]]):
        """Adopt ``ctx`` as the parent for this thread's root spans."""
        if ctx is None:
            return _NOOP
        return _Attach(self, ctx)

    # --- cross-process shipping (ISSUE 17) ------------------------------

    def drain_rows(self) -> List[Tuple]:
        """Pop every ring entry as a plain tuple row — the wire shape a
        worker process ships its spans to the consensus process in
        (server/workerproc.py). Aggregates stay: they are this
        process's own stage_totals. Rows are positional Span fields, so
        ``Span(*row)`` reconstructs on the other side."""
        with self._lock:
            rows = [(s.name, s.trace_id, s.span_id, s.parent_id,
                     s.start_s, s.dur_s, s.child_s, s.cpu_s,
                     s.child_cpu_s, s.thread) for s in self._ring]
            self._ring.clear()
        return rows

    def ingest(self, rows: List[Tuple]) -> None:
        """Adopt span rows recorded in ANOTHER process into this ring +
        aggregates, so worker-process spans land in the same e2e
        waterfall as the owner's (trace ids are eval ids on both sides;
        worker span ids are offset per process, so they never collide
        with local ones). Monotonic clocks are system-wide on Linux —
        the shipped start stamps order correctly against local spans."""
        if not self._enabled:
            return
        for row in rows:
            self._append(Span(*row), 1)

    # --- introspection --------------------------------------------------

    def spans(self, name: Optional[str] = None,
              trace_id: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._ring)
        if name is not None:
            out = [s for s in out if s.name == name]
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def recent_spans(self, trace_id: str, scan: int = 2048) -> List[Span]:
        """Spans of one trace among the newest ``scan`` ring entries,
        oldest first. Hot-path-safe companion to ``spans``: the copy
        under the lock is bounded by ``scan`` (reversed-deque steps are
        O(1)), so a caller on an eval thread — the flight recorder
        capturing a just-finished slow eval, whose spans are by
        definition the newest — never stalls concurrent recording
        behind a full 16k-entry ring copy."""
        with self._lock:
            newest = list(itertools.islice(reversed(self._ring), scan))
        newest.reverse()
        return [s for s in newest if s.trace_id == trace_id]

    def stage_totals(self) -> Dict[str, Dict[str, float]]:
        """Per-name aggregates since enable/reset: full-fidelity even
        after the ring wraps."""
        with self._lock:
            return {
                name: {"count": int(c), "total_s": t, "exclusive_s": e,
                       "cpu_s": cp, "exclusive_cpu_s": ecp}
                for name, (c, t, e, cp, ecp) in sorted(self._agg.items())
            }


#: process-wide tracer, analogous to utils.metrics.global_registry
tracer = Tracer()


class _CaptureRing:
    """Shared bounded-capture machinery for the flight recorders: the
    capture ring, the double-checked rate-limited append, serve-time
    span rendering, and the adaptive-threshold constants. Subclasses
    own their threshold POLICY (:class:`FlightRecorder`: one scalar
    e2e threshold; :class:`ConsensusRecorder`: per-op rows) — the
    capture-cost discipline lives here once so a fix to it cannot
    drift between the two recorders."""

    #: records retained (newest win)
    CAPACITY = 32
    #: observations before a threshold arms
    MIN_SAMPLES = 32
    #: EWMA smoothing for the p99 estimate
    ALPHA = 0.25
    #: p99 re-estimation cadence (bucket walks are cheap but not free)
    REFRESH_EVERY = 16
    #: capture rate limit (seconds between captures)
    MIN_CAPTURE_INTERVAL_S = 0.05

    def __init__(self, capacity: int) -> None:
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._last_capture_mono = 0.0
        self.min_capture_interval_s = self.MIN_CAPTURE_INTERVAL_S
        self.captured = 0

    def _capture_due(self) -> bool:
        """Pre-scan rate-limit check (cheap bail before the span-ring
        scan)."""
        with self._lock:
            return (time.monotonic() - self._last_capture_mono
                    >= self.min_capture_interval_s)

    def _try_append(self, record: Dict) -> bool:
        """Double-checked rate-limited append: a racing capture may
        have landed while the caller scanned the span ring (both are
        valid records; the limit is a cost bound, not a semantic
        one)."""
        with self._lock:
            if time.monotonic() - self._last_capture_mono \
                    < self.min_capture_interval_s:
                return False
            self._last_capture_mono = time.monotonic()
            self._ring.append(record)
            self.captured += 1
        return True

    def trees(self) -> List[Dict]:
        """Captured records in API shape (span dicts rendered here, at
        serve time — never on the thread that captured)."""
        with self._lock:
            raw = list(self._ring)
        return [
            {**{k: v for k, v in t.items() if k != "_spans"},
             "Spans": [s.to_api() for s in t["_spans"]]}
            for t in raw
        ]

    def _reset_ring_locked(self) -> None:
        self._ring.clear()
        self._last_capture_mono = 0.0
        self.captured = 0


class FlightRecorder(_CaptureRing):
    """Slow-eval flight recorder: a bounded ring of COMPLETE span trees
    for evals whose e2e latency crossed an adaptive threshold.

    Aggregates (TRACE_DECOMP, histograms) say *how much* tail there is;
    a tail investigation needs the span tree of an actual slow eval —
    which, at p99, has usually already fallen off the span ring by the
    time anyone looks. The recorder captures trees at completion time
    (the Canopy pattern: always-on, sampled by slowness), so
    ``GET /v1/operator/slow-evals`` can serve "the last N slow evals,
    fully decomposed" from a live server.

    Threshold adaptation: an EWMA of the e2e histogram's p99. Tracking
    p99 (rather than a fixed cutoff) keeps the capture rate near the
    top ~1% whatever the workload's absolute speed — a fixed cutoff
    either floods the ring on a slow box or never fires on a fast one.
    The EWMA smooths the estimate so one captured outlier doesn't
    instantly raise the bar past its successors. Disarmed until
    ``MIN_SAMPLES`` observations exist (an empty distribution has no
    tail to speak of).

    Memory is doubly bounded: at most ``capacity`` trees, each at most
    ``MAX_SPANS_PER_TREE`` spans. Capture cost is bounded too — the
    recorder runs ON the eval threads it measures, so it must not
    become the tail it records: captures are rate-limited to one per
    ``min_capture_interval_s`` (the ring only keeps the newest trees
    anyway — capturing every tail eval of a burst would overwrite
    itself while charging the burst for the serialization), the ring
    scan is bounded (``Tracer.recent_spans``), and captured trees hold
    raw Span references — the API-dict conversion happens at serve
    time, not on the hot path.
    """

    #: per-tree span cap (a runaway instrumented loop must not make
    #: one tree unbounded)
    MAX_SPANS_PER_TREE = 256

    def __init__(self, capacity: int = _CaptureRing.CAPACITY) -> None:
        super().__init__(capacity)
        self._threshold_s: Optional[float] = None
        self._observed = 0

    def observe(self, trace_id: str, e2e_s: float) -> bool:
        """Called once per committed eval with its e2e latency; captures
        the eval's span tree when it lands beyond the adaptive
        threshold. Returns True when a tree was captured."""
        from nomad_tpu.telemetry.histogram import histograms

        with self._lock:
            self._observed += 1
            refresh = (self._threshold_s is None
                       or self._observed % self.REFRESH_EVERY == 0)
            armed = self._observed >= self.MIN_SAMPLES
        if refresh:
            p99 = histograms.get("e2e").quantile(0.99)
            if p99 > 0.0:
                with self._lock:
                    if self._threshold_s is None:
                        self._threshold_s = p99
                    else:
                        self._threshold_s += self.ALPHA * (
                            p99 - self._threshold_s)
        with self._lock:
            thr = self._threshold_s
        if not armed or thr is None or e2e_s < thr:
            return False
        if not tracer.enabled or not trace_id:
            return False
        if not self._capture_due():
            return False
        # bounded scan of the NEWEST ring entries: the slow eval just
        # finished, so its tree is at the ring's tail — a full-ring
        # copy under the tracer lock would stall every concurrent
        # span-recording thread (an observer effect in the very
        # instrument that measures tail latency)
        spans = tracer.recent_spans(trace_id)
        if not spans:
            return False
        tree = {
            "TraceID": trace_id,
            "E2eMs": round(e2e_s * 1e3, 3),
            "ThresholdMs": round(thr * 1e3, 3),
            "CapturedAtS": round(time.time(), 3),
            # raw Span refs; to_api conversion deferred to trees()
            "_spans": spans[:self.MAX_SPANS_PER_TREE],
        }
        return self._try_append(tree)

    def threshold_s(self) -> Optional[float]:
        with self._lock:
            return self._threshold_s

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "observed": self._observed,
                "captured": self.captured,
                "retained": len(self._ring),
                "threshold_ms": round((self._threshold_s or 0.0) * 1e3,
                                      3),
            }

    def reset(self) -> None:
        with self._lock:
            self._reset_ring_locked()
            self._threshold_s = None
            self._observed = 0


#: process-wide slow-eval recorder; reset via telemetry.reset()
flight_recorder = FlightRecorder()


class ConsensusRecorder(_CaptureRing):
    """Consensus-plane flight recorder (ISSUE 15): the PR 8 slow-eval
    discipline extended to raft — slow follower appends, slow WAL
    group-fsync batches, and slow elections past a per-op adaptive
    EWMA threshold, served at ``GET /v1/operator/slow-raft`` alongside
    the eval recorder.

    Same bounded-cost rules as :class:`FlightRecorder` (the recorder
    runs on the raft/WAL threads it measures): per-op thresholds adapt
    as an EWMA of that op's histogram p99 (log-bucketed, always-on),
    disarmed until ``MIN_SAMPLES`` observations, captures rate-limited
    to one per ``MIN_CAPTURE_INTERVAL_S``, a bounded newest-first ring
    scan when a trace id exists, and span->JSON conversion deferred to
    serve time. Each captured record keeps the op, the owning
    ``server_id``, the duration vs the threshold at capture time, and
    (when tracing was on and the op carried a trace id) the span tree.
    """

    MAX_SPANS_PER_TREE = 128

    def __init__(self, capacity: int = _CaptureRing.CAPACITY) -> None:
        super().__init__(capacity)
        #: op -> [threshold_s or None, observed]
        self._ops: Dict[str, List] = {}

    def observe(self, op: str, dur_s: float, server_id: str = "",
                trace_id: str = "") -> bool:
        """Called per consensus op with its duration (the histogram
        record has already happened at the call site); captures when
        the duration lands beyond the op's adaptive threshold."""
        from nomad_tpu.telemetry.histogram import histograms

        with self._lock:
            row = self._ops.get(op)
            if row is None:
                row = self._ops[op] = [None, 0]
            row[1] += 1
            observed = row[1]
            refresh = row[0] is None or observed % self.REFRESH_EVERY == 0
            armed = observed >= self.MIN_SAMPLES
        if refresh:
            h = histograms.peek(op)
            p99 = h.quantile(0.99) if h is not None else 0.0
            if p99 > 0.0:
                with self._lock:
                    # re-fetch with a default: a concurrent reset()
                    # may have cleared _ops between the locked
                    # sections — this runs on the WAL-fsync/append
                    # path, where a KeyError would fail a raft ack,
                    # not just drop a telemetry sample
                    row = self._ops.setdefault(op, [None, 0])
                    if row[0] is None:
                        row[0] = p99
                    else:
                        row[0] += self.ALPHA * (p99 - row[0])
        with self._lock:
            row = self._ops.get(op)
            thr = row[0] if row is not None else None
        if not armed or thr is None or dur_s < thr:
            return False
        if not self._capture_due():
            return False
        spans = []
        if tracer.enabled and trace_id:
            spans = tracer.recent_spans(trace_id, scan=512)
        record = {
            "Op": op,
            "ServerId": server_id,
            "TraceID": trace_id,
            "DurMs": round(dur_s * 1e3, 3),
            "ThresholdMs": round(thr * 1e3, 3),
            "CapturedAtS": round(time.time(), 3),
            "_spans": spans[:self.MAX_SPANS_PER_TREE],
        }
        return self._try_append(record)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "captured": self.captured,
                "retained": len(self._ring),
                "thresholds_ms": {
                    op: round((row[0] or 0.0) * 1e3, 3)
                    for op, row in sorted(self._ops.items())
                },
                "observed": {op: row[1]
                             for op, row in sorted(self._ops.items())},
            }

    def reset(self) -> None:
        with self._lock:
            self._reset_ring_locked()
            self._ops.clear()


#: process-wide consensus-plane recorder; reset via telemetry.reset()
consensus_recorder = ConsensusRecorder()
