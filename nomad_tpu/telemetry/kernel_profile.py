"""JAX-level instrumentation of placement kernel waves.

Decomposes every coalesced wave launch into the stages that actually
cost wall time on an accelerator backend:

- ``kernel.h2d``     host->device upload of the stacked wave planes
- ``kernel.compile`` jit trace + XLA compile (first call per
                     (kernel, bucket-shape) key — a cold TPU compile is
                     tens of seconds and MUST be visible, not smeared)
- ``kernel.dispatch``the async dispatch of an already-compiled program
- ``kernel.execute`` device execution (``block_until_ready``)

(``kernel.d2h`` — the device->host fetch of the wave result — is
recorded by the caller around its result unpacking.)

The profiler also counts jit cache misses per (kernel, key): the live
path is bucketed precisely so that repeated waves REUSE compiled
programs, and a miss counter per bucket shape is the direct test of
that claim (BENCH_r05's open question: is the TPU live-path gap
recompilation?). A miss is classified first by the profiler's own seen
set and cross-checked against the jit function's cache size when the
runtime exposes it (``_cache_size``), so bucket-key bugs (two keys
mapping to one program, or one key recompiling) show up as
``misses != cache_growth``.

When disabled, ``profiled_call`` runs the plain call — same arguments,
same upload behavior (jit uploads host numpy leaves once at call time),
zero added device synchronization.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

from nomad_tpu.telemetry.trace import tracer

__all__ = ["KernelProfiler", "profiler", "profiled_call"]


class KernelProfiler:
    def __init__(self) -> None:
        self._enabled = False
        self._lock = threading.Lock()
        #: (kernel, key) ever launched -> launch count
        self._launches: Dict[Tuple[str, tuple], int] = {}
        #: (kernel, key) -> compile (cache-miss) count
        self._misses: Dict[Tuple[str, tuple], int] = {}
        #: per-stage cumulative seconds
        self.stage_s: Dict[str, float] = {
            "h2d": 0.0, "compile": 0.0, "dispatch": 0.0, "execute": 0.0,
        }
        #: cumulative transfer BYTES per direction — seconds say how
        #: long the PCIe stages took, bytes say whether the payload
        #: shrank (the device-resident cluster state's whole point).
        #: h2d counts host numpy leaves actually uploaded (resident
        #: device arrays cost nothing and are not counted) plus the
        #: dirty-row uploads device_state performs; d2h counts the
        #: result planes the wave launcher fetches.
        self.transfer_bytes: Dict[str, int] = {"h2d": 0, "d2h": 0}
        #: per-wave device-dispatch accounting (ISSUE 19): device
        #: interactions on the wave path, keyed by program. Every
        #: ``call`` counts one under its kernel name; the wave
        #: launcher adds "wave_fetch" for the composite's eager
        #: per-field result fetch and "topk_drain" for the deferred
        #: top-k materialization. The fused mega-kernel's single
        #: packed readback rides its own dispatch's synchronization,
        #: so a fused steady wave counts exactly ONE.
        self.dispatches: Dict[str, int] = {}
        #: cross-check: observed jit cache growth (when introspectable)
        self.cache_growth = 0

    # --- control --------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def reset(self) -> None:
        with self._lock:
            self._launches.clear()
            self._misses.clear()
            for k in self.stage_s:
                self.stage_s[k] = 0.0
            for k in self.transfer_bytes:
                self.transfer_bytes[k] = 0
            self.dispatches.clear()
            self.cache_growth = 0

    # --- accounting -----------------------------------------------------

    def summary(self) -> Dict:
        with self._lock:
            per_key = [
                {
                    "Kernel": kernel,
                    "Key": "/".join(str(p) for p in key),
                    "Launches": n,
                    "Misses": self._misses.get((kernel, key), 0),
                }
                for (kernel, key), n in sorted(self._launches.items())
            ]
            return {
                "Launches": sum(self._launches.values()),
                "JitCacheMisses": sum(self._misses.values()),
                "JitCacheGrowth": self.cache_growth,
                "StageSeconds": {k: round(v, 6)
                                 for k, v in self.stage_s.items()},
                "TransferBytes": dict(self.transfer_bytes),
                "Dispatches": dict(self.dispatches),
                "PerKey": per_key,
            }

    def misses_for(self, kernel: str) -> int:
        with self._lock:
            return sum(n for (k, _), n in self._misses.items()
                       if k == kernel)

    def add_bytes(self, direction: str, n: int) -> None:
        """Account ``n`` transfer bytes under ``direction`` ("h2d" or
        "d2h"). No-op when disabled — callers outside ``call`` (the
        wave launcher's d2h fetch, device_state's dirty-row uploads)
        report through this."""
        if not self._enabled or n <= 0:
            return
        with self._lock:
            self.transfer_bytes[direction] = \
                self.transfer_bytes.get(direction, 0) + int(n)

    def count_dispatch(self, program: str, n: int = 1) -> None:
        """Account ``n`` wave-path device dispatches under
        ``program`` (exported as
        ``nomad_tpu_kernel_dispatches_total{program=...}``). No-op
        when disabled, like ``add_bytes`` — callers outside ``call``
        (the composite eager fetch, the deferred top-k drain) report
        through this."""
        if not self._enabled or n <= 0:
            return
        with self._lock:
            self.dispatches[program] = \
                self.dispatches.get(program, 0) + int(n)

    def keys(self) -> list:
        """Every (kernel, bucket-key) ever launched since reset — the
        raw material of the AOT warmup manifest (ops/warmup.py)."""
        with self._lock:
            return list(self._launches)

    # --- the profiled launch -------------------------------------------

    def call(self, kernel: str, fn: Callable, dev_args: tuple,
             static_args: tuple, key: tuple, jit_fn=None,
             shardings=None):
        """Run ``fn(*dev_args, *static_args)`` decomposed into h2d /
        compile-or-dispatch / execute stages. ``dev_args`` is the array
        pytree uploaded to the device; ``static_args`` (jit static
        argnums — step bucket, feature set) pass through untouched.
        ``key`` is the bucket-shape identity the compile cache SHOULD
        be keyed by; ``jit_fn`` (when it differs from ``fn``, e.g. a
        sharded wrapper) is the object whose ``_cache_size`` is
        consulted for the cross-check. ``shardings`` (a pytree
        matching ``dev_args``) places host leaves at upload time — a
        sharded wave's explicit h2d must land each leaf with the jit's
        in_shardings, or the call would pay a hidden reshard."""
        if not self._enabled:
            return fn(*dev_args, *static_args)
        import time

        import jax

        probe = jit_fn if jit_fn is not None else fn
        size_fn = getattr(probe, "_cache_size", None)
        size0 = None
        if callable(size_fn):
            try:
                size0 = size_fn()
            except Exception:           # noqa: BLE001 - introspection only
                size0 = None

        # explicit upload: jit would upload the host numpy leaves
        # transparently inside the call; splitting it out is what makes
        # "is it transfer?" answerable. Leaves that are already device
        # arrays (the resident cluster state) skip device_put entirely
        # — only host leaves pay PCIe, so only they are uploaded,
        # blocked on, and byte-metered. One flatten + ONE batched
        # device_put: on a firing thread racing B eval threads for the
        # GIL, every extra per-leaf python round trip is a potential
        # 5ms switch-interval stall inside this span.
        leaves, treedef = jax.tree_util.tree_flatten(dev_args)
        host_idx = [i for i, x in enumerate(leaves)
                    if not isinstance(x, jax.Array)]
        host_leaves = [leaves[i] for i in host_idx]
        shard_leaves = None
        if shardings is not None and host_leaves:
            flat_shards = jax.tree_util.tree_flatten(
                shardings, is_leaf=lambda x: x is None)[0]
            if len(flat_shards) == len(leaves):
                shard_leaves = [flat_shards[i] for i in host_idx]
        up_bytes = sum(getattr(x, "nbytes", 0) for x in host_leaves)
        with tracer.span("kernel.h2d"):
            t0 = time.perf_counter()
            if host_leaves:
                # ONE batched device_put + ONE block: handing the jit
                # call arrays with in-flight transfers makes the
                # dispatch itself stall holding the GIL, which
                # serializes every eval thread behind this launch
                put = jax.device_put(host_leaves, shard_leaves)
                jax.block_until_ready(put)
                for i, v in zip(host_idx, put):
                    leaves[i] = v
            self._bump_stage("h2d", time.perf_counter() - t0)
        dev_args = jax.tree_util.tree_unflatten(treedef, leaves)
        self.add_bytes("h2d", up_bytes)

        full_key = (kernel, key)
        with self._lock:
            seen = full_key in self._launches
            self._launches[full_key] = self._launches.get(full_key, 0) + 1
            self.dispatches[kernel] = self.dispatches.get(kernel, 0) + 1
        t0 = time.perf_counter()
        out = fn(*dev_args, *static_args)
        call_s = time.perf_counter() - t0

        grew = 0
        if size0 is not None:
            try:
                grew = max(size_fn() - size0, 0)
            except Exception:           # noqa: BLE001
                grew = 0
        # a miss is OBSERVED cache growth when the runtime exposes it
        # (survives profiler resets against a warm jit cache); the seen
        # set is the fallback. A key we bucketed as "seen" that grows
        # the cache anyway is the exact bug class this counter exists
        # to expose (two shapes under one bucket key).
        miss = bool(grew) if size0 is not None else not seen
        stage = "compile" if miss else "dispatch"
        tracer.record(f"kernel.{stage}", call_s)
        self._bump_stage(stage, call_s)
        with self._lock:
            if miss:
                self._misses[full_key] = self._misses.get(full_key, 0) + 1
            self.cache_growth += grew

        with tracer.span("kernel.execute"):
            t0 = time.perf_counter()
            jax.block_until_ready(out)
            self._bump_stage("execute", time.perf_counter() - t0)
        return out

    def _bump_stage(self, stage: str, dur_s: float) -> None:
        with self._lock:
            self.stage_s[stage] += dur_s


#: process-wide profiler; enabled together with the tracer by
#: telemetry.enable()
profiler = KernelProfiler()


def profiled_call(kernel: str, fn: Callable, dev_args: tuple,
                  static_args: tuple, key: tuple, jit_fn=None):
    return profiler.call(kernel, fn, dev_args, static_args, key,
                         jit_fn=jit_fn)
