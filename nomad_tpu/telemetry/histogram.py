"""Streaming log-bucketed latency histograms.

TRACE_DECOMP attributes *mean* per-eval milliseconds; the open-item-4
contention gate ("e2e p99 plan latency holds") is a *distribution*
question the per-stage aggregates structurally cannot answer. This
module is the distribution substrate: bounded-memory streaming
histograms in the Prometheus classic-histogram shape, cheap enough to
record on the eval hot path, mergeable across workers, with quantile
estimation whose error is bounded by the bucket geometry.

Design constraints, in order:

- **Thread-cheap.** ``record`` is one ``math.log``, one short lock,
  three adds — no allocation, no sort, no deque growth. Safe to call
  per eval / per wave / per plan whether or not tracing is enabled.
- **Bounded.** Fixed bucket table (geometric, ``GROWTH`` = 2^0.25 per
  bucket, 1µs … ~54min + overflow). Memory never grows with traffic.
- **Mergeable.** All histograms share one static bound table, so merge
  is element-wise addition — associative and commutative, the property
  that lets per-worker histograms fold into one exposition.
- **Bounded-error quantiles.** ``quantile`` returns the geometric
  midpoint of the bucket holding the nearest-rank order statistic:
  relative error ≤ sqrt(GROWTH) − 1 ≈ 9.1% against the exact value
  (property-tested against ``numpy.percentile`` in
  tests/test_tail_latency.py).

``percentile()`` is the shared *exact* quantile helper for call sites
that already hold a small sample list — it replaces the two
independently-grown ``int(len*0.99)`` sorted-list hacks that used to
live in parallel/coalesce.py and bench.py (both off by one at the
tail: ``int(100*0.99) == 99`` indexes the MAX, not the 99th
percentile, of a 100-sample list).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "GROWTH", "LatencyHistogram", "HistogramRegistry", "histograms",
    "percentile",
]

#: per-bucket growth factor. 2^0.25 keeps midpoint-estimate relative
#: error under ~9.1% while 128 buckets still span 1µs → ~54 minutes —
#: wide enough for any eval latency this system can produce.
GROWTH = 2.0 ** 0.25
#: lower edge of bucket 0 (everything at or below lands there)
MIN_S = 1e-6
#: finite buckets; index N_BUCKETS is the +Inf overflow bucket
N_BUCKETS = 128

_LOG_GROWTH = math.log(GROWTH)
#: upper bounds of the finite buckets: bucket i covers
#: (BOUNDS[i-1], BOUNDS[i]], bucket 0 covers (0, MIN_S].
BOUNDS: Tuple[float, ...] = tuple(
    MIN_S * GROWTH ** i for i in range(N_BUCKETS)
)


def percentile(values: Sequence[float], q: float) -> float:
    """Exact nearest-rank percentile of a small sample.

    ``q`` in [0, 1]. Sorts a copy, so the input may be any sequence in
    any order (callers holding an already-sorted list pay one O(n)
    verification pass inside sort). Nearest-rank: the smallest value
    with at least ``ceil(q*n)`` samples at or below it — the standard
    definition, which for q=0.99 over 100 samples is element 98
    (0-indexed), NOT element 99 (the max) that ``int(n*0.99)``
    indexing returns.
    """
    if not values:
        return 0.0
    vs = sorted(values)
    if q <= 0.0:
        return vs[0]
    rank = min(math.ceil(q * len(vs)), len(vs))
    return vs[max(rank, 1) - 1]


def bucket_index(seconds: float) -> int:
    """Index of the bucket covering ``seconds`` (shared static table)."""
    if seconds <= MIN_S:
        return 0
    # ceil with a tiny epsilon so exact bound values stay in their
    # bucket instead of spilling up on float noise
    idx = int(math.ceil(math.log(seconds / MIN_S) / _LOG_GROWTH - 1e-9))
    return idx if idx <= N_BUCKETS else N_BUCKETS


class LatencyHistogram:
    """One named latency distribution. All instances share BOUNDS."""

    __slots__ = ("name", "_lock", "_counts", "_sum", "_count", "_max")

    def __init__(self, name: str = "") -> None:
        self.name = name
        self._lock = threading.Lock()
        self._counts = [0] * (N_BUCKETS + 1)
        self._sum = 0.0
        self._count = 0
        self._max = 0.0

    # --- recording ------------------------------------------------------

    def record(self, seconds: float) -> None:
        if seconds < 0.0:
            seconds = 0.0
        idx = bucket_index(seconds)
        with self._lock:
            self._counts[idx] += 1
            self._sum += seconds
            self._count += 1
            if seconds > self._max:
                self._max = seconds

    def merge(self, other: "LatencyHistogram") -> None:
        """Fold ``other`` into this histogram (element-wise adds over
        the shared bound table: associative, commutative)."""
        with other._lock:
            counts = list(other._counts)
            o_sum, o_count, o_max = other._sum, other._count, other._max
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum += o_sum
            self._count += o_count
            if o_max > self._max:
                self._max = o_max

    def reset(self) -> None:
        with self._lock:
            for i in range(len(self._counts)):
                self._counts[i] = 0
            self._sum = 0.0
            self._count = 0
            self._max = 0.0

    # --- introspection --------------------------------------------------

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum_s(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        return self.quantiles((q,))[0]

    def quantiles(self, qs: Iterable[float]) -> List[float]:
        """Nearest-rank quantile estimates: geometric midpoint of the
        bucket holding each target rank (one lock, one bucket walk for
        all requested quantiles)."""
        qs = list(qs)
        with self._lock:
            if self._count == 0:
                return [0.0 for _ in qs]
            counts = list(self._counts)
            total = self._count
            hist_max = self._max
        out: List[float] = []
        for q in qs:
            rank = min(max(int(math.ceil(q * total)), 1), total)
            cum = 0
            est = hist_max
            for i, c in enumerate(counts):
                cum += c
                if cum >= rank:
                    if i == 0:
                        # (0, MIN_S]: everything here is "instant"
                        est = min(MIN_S, hist_max)
                    elif i >= N_BUCKETS:
                        # overflow: the max is the only honest bound
                        est = hist_max
                    else:
                        est = min(BOUNDS[i] / math.sqrt(GROWTH), hist_max)
                    break
            out.append(est)
        return out

    def snapshot(self) -> Dict:
        """Summary dict (bench artifacts / JSON endpoints)."""
        p50, p90, p99 = self.quantiles((0.5, 0.9, 0.99))
        with self._lock:
            count, total, mx = self._count, self._sum, self._max
        return {
            "count": count,
            "sum_s": round(total, 6),
            "mean_ms": round(total / count * 1e3, 4) if count else 0.0,
            "p50_ms": round(p50 * 1e3, 4),
            "p90_ms": round(p90 * 1e3, 4),
            "p99_ms": round(p99 * 1e3, 4),
            "max_ms": round(mx * 1e3, 4),
        }

    def prometheus_lines(self, metric: str, labels: str = "") -> List[str]:
        """Classic-histogram exposition: cumulative ``_bucket`` lines
        (non-empty buckets plus the mandatory ``+Inf``), ``_sum``,
        ``_count``. ``labels`` is a pre-rendered ``k="v"`` list without
        braces; ``le`` is appended to it."""
        with self._lock:
            counts = list(self._counts)
            total_sum, total_count = self._sum, self._count
        sep = "," if labels else ""
        lines: List[str] = []
        cum = 0
        for i, c in enumerate(counts[:N_BUCKETS]):
            cum += c
            if c:
                lines.append(
                    f'{metric}_bucket{{{labels}{sep}le="{BOUNDS[i]:.9g}"}}'
                    f" {cum}")
        lines.append(
            f'{metric}_bucket{{{labels}{sep}le="+Inf"}} {total_count}')
        lines.append(f"{metric}_sum{{{labels}}} {total_sum:.6f}")
        lines.append(f"{metric}_count{{{labels}}} {total_count}")
        return lines


#: the latency series the hot path feeds (histogram `op` label values).
#: e2e = broker-enqueue → eval committed (ack after final plan commit);
#: the rest are the stage waits the tail decomposition names.
E2E = "e2e"
PLAN_QUEUE = "plan_queue"
PLAN_EVALUATE = "plan_evaluate"
PLAN_COMMIT = "plan_commit"
WAVE_PARK = "wave_park"
SNAPSHOT_WAIT = "snapshot_wait"
#: event-stream delivery lag: FSM-apply stamp -> consumer hand-off
#: (server/stream.py; the serving plane's headline distribution)
STREAM_DELIVER = "stream_deliver"
#: raft WAL group-fsync latency (raft/wal.py, ISSUE 13): the disk
#: cost every durable ack amortizes across the batched-commit windows
WAL_FSYNC = "wal_fsync"
#: consensus-plane latency ops (raft/node.py, ISSUE 15) — always-on
#: like e2e. raft_replication = leader append -> peer ack (per-peer
#: lag in ms); raft_quorum = leader append -> commit-index advance;
#: raft_append = follower AppendEntries handling incl. its group
#: fsync; raft_snapshot_xfer = one InstallSnapshot send
RAFT_REPLICATION = "raft_replication"
RAFT_QUORUM = "raft_quorum"
RAFT_APPEND = "raft_append"
RAFT_SNAPSHOT_XFER = "raft_snapshot_xfer"
#: full election duration (first round -> leadership won)
RAFT_ELECTION = "raft_election"
#: read-plane staleness (server/readplane.py, ISSUE 20): how far
#: behind the leader the data each served read was — 0 on the leader,
#: the last-contact / attributed-lag age on followers. The serving
#: plane's consistency distribution, exported per-op like the rest.
READ_STALENESS = "read_staleness"


class HistogramRegistry:
    """Process-wide named histograms (analogous to the tracer /
    metrics global_registry). ``get`` creates on first use so record
    sites need no setup ordering."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hists: Dict[str, LatencyHistogram] = {}

    def get(self, name: str) -> LatencyHistogram:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.get(name)
                if h is None:
                    h = LatencyHistogram(name)
                    self._hists[name] = h
        return h

    def peek(self, name: str) -> Optional[LatencyHistogram]:
        """Like ``get`` but never creates (exposition must not mint
        empty series)."""
        return self._hists.get(name)

    def items(self) -> List[Tuple[str, LatencyHistogram]]:
        with self._lock:
            return sorted(self._hists.items())

    def snapshot(self) -> Dict[str, Dict]:
        return {name: h.snapshot() for name, h in self.items()}

    def reset(self) -> None:
        for _, h in self.items():
            h.reset()


#: process-wide latency histograms; reset via telemetry.reset()
histograms = HistogramRegistry()
