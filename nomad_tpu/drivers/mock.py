"""Mock driver: fully scriptable fake workloads for tests.

Reference behavior: drivers/mock/driver.go -- tasks controlled by their
config stanza: ``run_for`` (seconds before clean exit), ``exit_code``,
``start_error`` / ``start_error_recoverable``, ``kill_after``; plus
recoverability toggles. The client/e2e test suites are built on it
(SURVEY.md section 4 "key fakes").
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from nomad_tpu.plugins.base import PLUGIN_TYPE_DRIVER, PluginInfo
from nomad_tpu.plugins.drivers import (
    TASK_STATE_EXITED,
    TASK_STATE_RUNNING,
    DriverCapabilities,
    DriverPlugin,
    ExitResult,
    Fingerprint,
    HEALTH_HEALTHY,
    TaskConfig,
    TaskHandle,
    TaskStatus,
)


class _MockTask:
    def __init__(self, config: TaskConfig) -> None:
        self.config = config
        self.state = TASK_STATE_RUNNING
        self.started_at = time.time()
        self.completed_at = 0.0
        self.exit_result: Optional[ExitResult] = None
        self.done = threading.Event()
        self.kill = threading.Event()
        # run_for accepts Go-style durations ("10s", "1m") like the
        # reference mock driver's time.ParseDuration config fields
        from nomad_tpu.jobspec.hcl import duration_s

        run_for = duration_s(config.driver_config.get("run_for", 0))
        exit_code = int(config.driver_config.get("exit_code", 0))
        self.thread = threading.Thread(
            target=self._run, args=(run_for, exit_code), daemon=True
        )
        self.thread.start()

    def _run(self, run_for: float, exit_code: int) -> None:
        if run_for <= 0:
            # run until killed
            self.kill.wait()
            result = ExitResult(exit_code=0, signal=15)
        elif self.kill.wait(run_for):
            result = ExitResult(exit_code=0, signal=15)
        else:
            result = ExitResult(exit_code=exit_code)
        self.state = TASK_STATE_EXITED
        self.completed_at = time.time()
        self.exit_result = result
        self.done.set()


class MockDriver(DriverPlugin):
    def __init__(self) -> None:
        self._tasks: Dict[str, _MockTask] = {}
        self._lock = threading.Lock()

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name="mock_driver", type=PLUGIN_TYPE_DRIVER)

    def capabilities(self) -> DriverCapabilities:
        return DriverCapabilities(send_signals=True, exec_=True)

    def fingerprint(self) -> Fingerprint:
        return Fingerprint(
            attributes={"driver.mock_driver": "1"},
            health=HEALTH_HEALTHY,
            health_description="Healthy",
        )

    def start_task(self, config: TaskConfig) -> TaskHandle:
        err = config.driver_config.get("start_error")
        if err:
            raise RuntimeError(str(err))
        with self._lock:
            if config.id in self._tasks:
                raise ValueError(f"task {config.id} already started")
            task = _MockTask(config)
            self._tasks[config.id] = task
        return TaskHandle(
            driver="mock_driver",
            config=config,
            state=TASK_STATE_RUNNING,
            driver_state={"started_at": task.started_at},
        )

    def recover_task(self, handle: TaskHandle) -> None:
        with self._lock:
            if handle.config.id in self._tasks:
                return
            if not bool(handle.config.driver_config.get("recoverable", True)):
                raise RuntimeError("mock task is not recoverable")
            # fresh in-memory task standing in for the "live" one
            self._tasks[handle.config.id] = _MockTask(handle.config)

    def wait_task(self, task_id: str, timeout: Optional[float] = None) -> Optional[ExitResult]:
        with self._lock:
            task = self._tasks.get(task_id)
        if task is None:
            raise KeyError(f"unknown task {task_id}")
        if not task.done.wait(timeout):
            return None
        return task.exit_result

    def stop_task(self, task_id: str, timeout: float = 5.0, signal: str = "SIGTERM") -> None:
        with self._lock:
            task = self._tasks.get(task_id)
        if task is not None:
            task.kill.set()
            task.done.wait(timeout)

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        with self._lock:
            task = self._tasks.pop(task_id, None)
        if task is not None and not task.done.is_set():
            if not force:
                raise RuntimeError("task still running; use force")
            task.kill.set()

    def inspect_task(self, task_id: str) -> TaskStatus:
        with self._lock:
            task = self._tasks.get(task_id)
        if task is None:
            raise KeyError(f"unknown task {task_id}")
        return TaskStatus(
            id=task_id,
            name=task.config.name,
            state=task.state,
            started_at=task.started_at,
            completed_at=task.completed_at,
            exit_result=task.exit_result,
        )

    def signal_task(self, task_id: str, signal: str) -> None:
        if signal in ("SIGKILL", "SIGTERM", "SIGINT"):
            self.stop_task(task_id)

    def exec_task(self, task_id: str, cmd: List[str], timeout: float = 30.0) -> Dict:
        return {"stdout": b"mock exec: " + " ".join(cmd).encode(), "exit_code": 0}
