"""docklog: detached engine-log follower for docker tasks.

Reference behavior: drivers/docker/docklog/docklog.go — a separate
process follows the container's log stream from the ENGINE and writes
it into the task's log files, so task output keeps flowing across
agent restarts and does not depend on the `docker run` CLI attachment
staying alive. The agent records the docklog pid in the task handle
and reaps/respawns it on recover.

Run standalone:
  python -S docklog.py <socket> <container> <stdout_file> <stderr_file> [since]

``since`` (unix seconds) bounds the follow so a respawned follower
does not re-append history.

Appends to the files (rotation is the logmon collector's job when the
files are its FIFOs; plain files otherwise). Exits when the engine
closes the stream (container gone).
"""

import sys


def follow(socket_path: str, container: str,
           stdout_path: str, stderr_path: str, since: str = "0") -> int:
    # import here so the module is importable without the package when
    # run with -S from an arbitrary cwd
    sys.path.insert(0, __file__.rsplit("/", 3)[0])
    from nomad_tpu.drivers.docker_api import DockerEngine, EngineError

    engine = DockerEngine(socket_path)
    try:
        with open(stdout_path, "ab", buffering=0) as out, \
                open(stderr_path, "ab", buffering=0) as err:
            for stream, data in engine.logs(container, follow=True,
                                            since=int(since or 0)):
                (err if stream == 2 else out).write(data)
    except (OSError, EngineError):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(follow(*sys.argv[1:6]))
