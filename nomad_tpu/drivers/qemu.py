"""qemu driver: virtual machine workloads.

Reference behavior: drivers/qemu/driver.go -- fingerprints the
`qemu-system-x86_64` binary (driver.qemu.version), then launches the VM
with `-m <memory>`, the image as the boot drive, `-nographic`, optional
KVM acceleration, and user-net port forwards from ``port_map``. The VM
process rides the shared executor for supervision/reattach.
"""

from __future__ import annotations

import re
import shutil
import subprocess
from typing import Dict, List

from nomad_tpu.drivers.rawexec import RawExecDriver
from nomad_tpu.plugins.base import PLUGIN_TYPE_DRIVER, PluginInfo
from nomad_tpu.plugins.drivers import (
    HEALTH_HEALTHY,
    HEALTH_UNDETECTED,
    Fingerprint,
    TaskConfig,
)

QEMU_BIN = "qemu-system-x86_64"


class QemuDriver(RawExecDriver):
    name = "qemu"

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name=self.name, type=PLUGIN_TYPE_DRIVER)

    def fingerprint(self) -> Fingerprint:
        qemu = shutil.which(QEMU_BIN)
        if qemu is None:
            return Fingerprint(health=HEALTH_UNDETECTED,
                               health_description=f"{QEMU_BIN} not found")
        attrs = {f"driver.{self.name}": "1"}
        try:
            out = subprocess.run(
                [qemu, "--version"], capture_output=True, text=True,
                timeout=10,
            ).stdout
            m = re.search(r"version ([\d.]+)", out)
            if m:
                attrs["driver.qemu.version"] = m.group(1)
        except Exception:                       # noqa: BLE001
            pass
        return Fingerprint(attributes=attrs, health=HEALTH_HEALTHY,
                           health_description="Healthy")

    def task_config_schema(self) -> Dict:
        return {
            "image_path": {"type": "string", "required": True},
            "accelerator": {"type": "string"},
            "memory": {"type": "string"},     # e.g. "512M"
            "port_map": {"type": "map"},      # {label: guest_port}
            "args": {"type": "list"},
        }

    def _command(self, config: TaskConfig) -> List[str]:
        cfg = config.driver_config
        image = cfg.get("image_path")
        if not image:
            raise ValueError("qemu driver requires image_path")
        argv: List[str] = [
            QEMU_BIN,
            "-machine", f"type=pc,accel={cfg.get('accelerator', 'tcg')}",
            "-m", str(cfg.get("memory")
                       or f"{config.resources.memory_mb or 512}M"),
            "-drive", f"file={image}",
            "-nographic",
        ]
        # user-net port forwards: hostfwd per mapped label
        port_map = cfg.get("port_map") or {}
        if port_map:
            fwds = []
            for label, guest_port in port_map.items():
                host_port = 0
                for net in config.resources.networks:
                    assigned = net.port_for_label(label)
                    if assigned:
                        host_port = assigned
                        break
                if host_port:
                    fwds.append(
                        f"hostfwd=tcp::{host_port}-:{guest_port}"
                    )
            argv += ["-netdev", "user,id=user.0" +
                     "".join("," + f for f in fwds),
                     "-device", "virtio-net,netdev=user.0"]
        argv.extend(cfg.get("args") or [])
        return argv
