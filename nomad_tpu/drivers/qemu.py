"""qemu driver: virtual machine workloads.

Reference behavior: drivers/qemu/driver.go -- fingerprints the
`qemu-system-x86_64` binary (driver.qemu.version), launches the VM
with `-m <memory>`, the image as the boot drive, `-nographic`, optional
KVM acceleration, user-net port forwards from ``port_map``, and a
MONITOR SOCKET (driver.go:52 qemuGracefulShutdownMsg area): when
``graceful_shutdown`` is set, the driver sends ``system_powerdown``
over the QMP socket so the guest OS shuts down cleanly before the
process is signalled. The VM process rides the shared executor for
supervision/reattach.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import socket
import subprocess
from typing import Dict, List, Optional

from nomad_tpu.drivers.rawexec import RawExecDriver
from nomad_tpu.plugins.base import PLUGIN_TYPE_DRIVER, PluginInfo
from nomad_tpu.plugins.drivers import (
    HEALTH_HEALTHY,
    HEALTH_UNDETECTED,
    Fingerprint,
    TaskConfig,
)

QEMU_BIN = "qemu-system-x86_64"

#: longest unix socket path (driver.go qemuLegacyMaxMonitorPathLen
#: concern); sockets land in the task dir which can be deep
_SUN_PATH_MAX = 100


class QemuDriver(RawExecDriver):
    name = "qemu"

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name=self.name, type=PLUGIN_TYPE_DRIVER)

    def fingerprint(self) -> Fingerprint:
        qemu = shutil.which(QEMU_BIN)
        if qemu is None:
            return Fingerprint(health=HEALTH_UNDETECTED,
                               health_description=f"{QEMU_BIN} not found")
        attrs = {f"driver.{self.name}": "1"}
        try:
            out = subprocess.run(
                [qemu, "--version"], capture_output=True, text=True,
                timeout=10,
            ).stdout
            m = re.search(r"version ([\d.]+)", out)
            if m:
                attrs["driver.qemu.version"] = m.group(1)
        except Exception:                       # noqa: BLE001
            pass
        return Fingerprint(attributes=attrs, health=HEALTH_HEALTHY,
                           health_description="Healthy")

    def task_config_schema(self) -> Dict:
        return {
            "image_path": {"type": "string", "required": True},
            "accelerator": {"type": "string"},
            "memory": {"type": "string"},     # e.g. "512M"
            "port_map": {"type": "map"},      # {label: guest_port}
            "graceful_shutdown": {"type": "bool"},
            "args": {"type": "list"},
        }

    # -- monitor socket (driver.go getMonitorPath) -----------------------

    def monitor_path(self, config: TaskConfig) -> str:
        base = config.alloc_dir or "/tmp"
        path = os.path.join(base, f".qmp-{config.name}.sock")
        if len(path) > _SUN_PATH_MAX:
            # fall back to a short path (the reference errors on
            # over-long monitor paths for legacy qemu; modern qemu
            # still caps sun_path)
            path = f"/tmp/nomad-qmp-{config.id[:24]}.sock"
        return path

    def _command(self, config: TaskConfig) -> List[str]:
        cfg = config.driver_config
        image = cfg.get("image_path")
        if not image:
            raise ValueError("qemu driver requires image_path")
        argv: List[str] = [
            QEMU_BIN,
            "-machine", f"type=pc,accel={cfg.get('accelerator', 'tcg')}",
            "-m", str(cfg.get("memory")
                       or f"{config.resources.memory_mb or 512}M"),
            "-drive", f"file={image}",
            "-nographic",
        ]
        if cfg.get("graceful_shutdown", True):
            argv += ["-qmp",
                     f"unix:{self.monitor_path(config)},server,nowait"]
        # user-net port forwards: hostfwd per mapped label
        port_map = cfg.get("port_map") or {}
        if port_map:
            fwds = []
            for label, guest_port in port_map.items():
                host_port = 0
                for net in config.resources.networks:
                    assigned = net.port_for_label(label)
                    if assigned:
                        host_port = assigned
                        break
                if host_port:
                    fwds.append(
                        f"hostfwd=tcp::{host_port}-:{guest_port}"
                    )
            argv += ["-netdev", "user,id=user.0" +
                     "".join("," + f for f in fwds),
                     "-device", "virtio-net,netdev=user.0"]
        argv.extend(cfg.get("args") or [])
        return argv

    # -- graceful shutdown (driver.go StopTask monitor path) -------------

    @staticmethod
    def qmp_system_powerdown(path: str, timeout: float = 5.0) -> bool:
        """Ask the guest to power down over the QMP socket. Returns
        True when the command was accepted (the guest will ACPI-off;
        the VM process then exits on its own)."""
        try:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(timeout)
            s.connect(path)
            f = s.makefile("rwb")
            json.loads(f.readline())            # greeting
            for cmd in ({"execute": "qmp_capabilities"},
                        {"execute": "system_powerdown"}):
                f.write(json.dumps(cmd).encode() + b"\n")
                f.flush()
                resp = json.loads(f.readline())
                while "return" not in resp and "error" not in resp:
                    resp = json.loads(f.readline())   # skip async events
                if "error" in resp:
                    return False
            s.close()
            return True
        except (OSError, ValueError):
            return False

    def stop_task(self, task_id: str, timeout: float = 5.0,
                  signal: str = "SIGTERM") -> None:
        task = self._get(task_id)
        cfg = task.config.driver_config or {}
        if not task.done.is_set() and cfg.get("graceful_shutdown", True):
            path = self.monitor_path(task.config)
            if os.path.exists(path) and self.qmp_system_powerdown(path):
                # clean guest shutdown: give the VM the full timeout
                # before falling back to signals
                if task.done.wait(max(timeout, 1.0)):
                    return
        super().stop_task(task_id, timeout=timeout, signal=signal)
