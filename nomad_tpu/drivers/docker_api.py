"""Minimal Docker Engine API client over the unix socket.

Reference behavior: drivers/docker uses the daemon API for everything
(go-dockerclient); this build's driver shells out to the CLI for
run/stop (documented deviation) but reads OPERATIONAL data — stats,
logs — straight from the engine like the reference does
(drivers/docker/stats.go collects from the stats endpoint;
docklog/docklog.go follows the logs endpoint), because polling
`docker stats` subprocesses is slow and lossy at real collection
intervals.

Stdlib-only: http.client over an AF_UNIX socket.
"""

from __future__ import annotations

import http.client
import json
import socket
import struct
from typing import Dict, Iterator, Optional, Tuple

#: everything a flaky daemon/socket can throw at a caller that wants
#: to fall back rather than fail (half-up proxies raise HTTPException
#: subclasses; truncated bodies raise ValueError via json)
TRANSPORT_ERRORS: Tuple = (OSError, http.client.HTTPException, ValueError)

DEFAULT_SOCKET = "/var/run/docker.sock"
API_VERSION = "v1.40"


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, socket_path: str, timeout: float = 30.0) -> None:
        super().__init__("localhost", timeout=timeout)
        self._socket_path = socket_path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._socket_path)
        self.sock = sock


class EngineError(RuntimeError):
    pass


class DockerEngine:
    """One-call-per-connection client (the engine closes idle conns)."""

    def __init__(self, socket_path: str = DEFAULT_SOCKET,
                 timeout: float = 30.0) -> None:
        self.socket_path = socket_path
        self.timeout = timeout

    def _request(self, method: str, path: str,
                 timeout: Optional[float] = None) -> http.client.HTTPResponse:
        conn = _UnixHTTPConnection(self.socket_path,
                                   timeout or self.timeout)
        conn.request(method, f"/{API_VERSION}{path}")
        resp = conn.getresponse()
        if resp.status >= 400:
            body = resp.read(500).decode(errors="replace")
            conn.close()
            raise EngineError(f"{method} {path}: {resp.status} {body}")
        return resp

    def _json(self, method: str, path: str) -> Dict:
        resp = self._request(method, path)
        try:
            return json.loads(resp.read())
        finally:
            resp.close()

    # -- surface ---------------------------------------------------------

    def ping(self) -> bool:
        try:
            resp = self._request("GET", "/_ping", timeout=5.0)
            ok = resp.read() == b"OK"
            resp.close()
            return ok
        except TRANSPORT_ERRORS + (EngineError,):
            return False

    def version(self) -> Dict:
        return self._json("GET", "/version")

    def stats(self, container: str) -> Dict:
        """One-shot raw stats (the stream=false form the reference's
        collector reads per interval)."""
        return self._json(
            "GET", f"/containers/{container}/stats?stream=false")

    def logs(self, container: str, follow: bool = True,
             stdout: bool = True, stderr: bool = True,
             since: int = 0) -> Iterator:
        """Yield (stream, bytes) frames from the engine's multiplexed
        log stream (docklog.go's source). stream 1=stdout, 2=stderr."""
        q = (f"/containers/{container}/logs?follow={'1' if follow else '0'}"
             f"&stdout={'1' if stdout else '0'}"
             f"&stderr={'1' if stderr else '0'}&since={since}")
        resp = self._request("GET", q, timeout=None if follow else 30.0)

        def read_exact(n: int) -> bytes:
            # resp.read(n) may return short on connection hiccups; a
            # short frame must end the stream, never misalign the next
            # header
            buf = b""
            while len(buf) < n:
                chunk = resp.read(n - len(buf))
                if not chunk:
                    break
                buf += chunk
            return buf

        try:
            while True:
                head = read_exact(8)
                if len(head) < 8:
                    return
                stream, _, _, _, size = struct.unpack(">BBBBI", head)
                if size == 0:
                    continue        # empty frame is not end-of-stream
                data = read_exact(size)
                if len(data) < size:
                    if data:
                        yield stream, data
                    return
                yield stream, data
        finally:
            resp.close()


def compute_cpu_percent(stats: Dict) -> float:
    """CPU percentage from a raw stats sample (drivers/docker/stats.go
    calculateCPUPercent: delta vs precpu over the system delta,
    scaled by online cpus)."""
    try:
        cpu = stats["cpu_stats"]
        pre = stats["precpu_stats"]
        cpu_delta = (cpu["cpu_usage"]["total_usage"]
                     - pre["cpu_usage"]["total_usage"])
        sys_delta = (cpu.get("system_cpu_usage", 0)
                     - pre.get("system_cpu_usage", 0))
        ncpu = cpu.get("online_cpus") or len(
            cpu["cpu_usage"].get("percpu_usage") or [1])
        if cpu_delta > 0 and sys_delta > 0:
            return cpu_delta / sys_delta * ncpu * 100.0
    except (KeyError, TypeError, ZeroDivisionError):
        pass
    return 0.0


def memory_rss(stats: Dict) -> int:
    """Resident memory from a raw sample (stats.go memory usage:
    usage minus the reclaimable page cache when reported)."""
    try:
        mem = stats["memory_stats"]
        usage = int(mem.get("usage", 0))
        detail = mem.get("stats") or {}
        cache = int(detail.get("total_inactive_file")
                    or detail.get("inactive_file") or 0)
        return max(usage - cache, 0)
    except (KeyError, TypeError, ValueError):
        return 0
