"""raw_exec driver: host subprocesses with no isolation.

Reference behavior: drivers/rawexec/driver.go -- launches the command
directly on the host via the shared out-of-process executor
(drivers/shared/executor/executor.go:54), so tasks keep running across
agent restarts and the driver reattaches through RecoverTask using the
persisted TaskHandle. Config stanza: {"command": ..., "args": [...]}.

Two launch paths: the native C++ executor (native/executor.cc, built on
demand) for restart-survivable supervision, or a direct subprocess
fallback when the binary is unavailable.
"""

from __future__ import annotations

import os
import signal as _signal
import subprocess
import threading
import time
from typing import Dict, List, Optional

from nomad_tpu.plugins.base import PLUGIN_TYPE_DRIVER, PluginInfo
from nomad_tpu.plugins.drivers import (
    TASK_STATE_EXITED,
    TASK_STATE_RUNNING,
    DriverCapabilities,
    DriverPlugin,
    ExitResult,
    Fingerprint,
    HEALTH_HEALTHY,
    TaskConfig,
    TaskHandle,
    TaskStatus,
)

_EXECUTOR_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "native")


def executor_path(build: bool = True) -> Optional[str]:
    """Locate (and lazily build) the native executor binary."""
    path = os.path.abspath(os.path.join(_EXECUTOR_SRC, "executor"))
    if os.path.exists(path):
        return path
    if not build:
        return None
    try:
        subprocess.run(
            ["make", "-C", os.path.abspath(_EXECUTOR_SRC)],
            capture_output=True, timeout=60, check=True,
        )
    except Exception:                           # noqa: BLE001
        return None
    return path if os.path.exists(path) else None


class _RawTask:
    """Supervision state for one task (in-memory side)."""

    def __init__(self, config: TaskConfig) -> None:
        self.config = config
        self.pid: Optional[int] = None
        self.pgid: Optional[int] = None
        self.status_path = ""
        self.started_at = time.time()
        self.completed_at = 0.0
        self.exit_result: Optional[ExitResult] = None
        self.done = threading.Event()

    @property
    def state(self) -> str:
        return TASK_STATE_EXITED if self.done.is_set() else TASK_STATE_RUNNING


class RawExecDriver(DriverPlugin):
    name = "raw_exec"
    use_executor = True

    def __init__(self) -> None:
        self._tasks: Dict[str, _RawTask] = {}
        self._lock = threading.Lock()

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name=self.name, type=PLUGIN_TYPE_DRIVER)

    def capabilities(self) -> DriverCapabilities:
        return DriverCapabilities(send_signals=True, exec_=True, fs_isolation="none")

    def fingerprint(self) -> Fingerprint:
        return Fingerprint(
            attributes={f"driver.{self.name}": "1"},
            health=HEALTH_HEALTHY,
            health_description="Healthy",
        )

    def task_config_schema(self) -> Dict:
        return {"command": {"type": "string", "required": True},
                "args": {"type": "list"}}

    # --- launch ---------------------------------------------------------

    def _command(self, config: TaskConfig) -> List[str]:
        cmd = config.driver_config.get("command")
        if not cmd:
            raise ValueError("raw_exec requires config.command")
        return [cmd] + list(config.driver_config.get("args", []))

    def _build_env(self, config: TaskConfig) -> Dict[str, str]:
        """raw_exec inherits the agent environment (no isolation)."""
        env = dict(os.environ)
        env.update(config.env)
        return env

    def start_task(self, config: TaskConfig) -> TaskHandle:
        with self._lock:
            if config.id in self._tasks:
                raise ValueError(f"task {config.id} already started")
        task = _RawTask(config)
        workdir = config.alloc_dir or "/tmp"
        os.makedirs(workdir, exist_ok=True)
        stdout = config.std_out_path or os.path.join(workdir, "stdout")
        stderr = config.std_err_path or os.path.join(workdir, "stderr")
        argv = self._command(config)
        if config.netns:
            # join the alloc's network namespace (network_hook.go);
            # applies to executor and direct paths alike
            argv = ["ip", "netns", "exec", config.netns] + argv
        env = self._build_env(config)

        exe = executor_path() if self.use_executor else None
        if exe is not None:
            task.status_path = os.path.join(
                workdir, f".executor-{config.name}.status"
            )
            # the executor detaches (setsid) and supervises; we only
            # keep its status file
            subprocess.Popen(
                [exe, task.status_path, stdout, stderr, workdir]
                + self._executor_opts(config) + ["--"] + argv,
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                start_new_session=True,
            )
            pid, pgid = self._wait_for_pid(task.status_path)
            task.pid, task.pgid = pid, pgid
            threading.Thread(
                target=self._poll_status, args=(task,), daemon=True
            ).start()
        else:
            with open(stdout, "ab") as out, open(stderr, "ab") as err:
                proc = subprocess.Popen(
                    argv, cwd=workdir, env=env,
                    stdout=out, stderr=err, start_new_session=True,
                )
            task.pid = proc.pid
            task.pgid = proc.pid
            threading.Thread(
                target=self._wait_popen, args=(task, proc), daemon=True
            ).start()

        with self._lock:
            self._tasks[config.id] = task
        return TaskHandle(
            driver=self.name,
            config=config,
            state=TASK_STATE_RUNNING,
            driver_state={
                "pid": task.pid,
                "pgid": task.pgid,
                "status_path": task.status_path,
                "started_at": task.started_at,
            },
        )

    def _executor_opts(self, config: TaskConfig) -> List[str]:
        """Extra executor flags (raw_exec runs without isolation; the
        exec driver overrides with namespaces + cgroup limits)."""
        return []

    @staticmethod
    def _wait_for_pid(status_path: str, timeout: float = 10.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            errors = []
            try:
                with open(status_path) as f:
                    for line in f:
                        if line.startswith("pid "):
                            _, pid, pgid = line.split()
                            return int(pid), int(pgid)
                        if line.startswith("error "):
                            errors.append(line[6:].strip())
                        elif line.startswith("exit "):
                            # executor failed before launching the task
                            detail = "; ".join(errors) or line.strip()
                            raise RuntimeError(
                                f"executor failed to launch task: {detail}"
                            )
            except FileNotFoundError:
                pass
            time.sleep(0.01)
        raise TimeoutError("executor did not report a pid")

    def _poll_status(self, task: _RawTask, interval: float = 0.05) -> None:
        """Watch the executor's status file for the exit record."""
        while not task.done.is_set():
            try:
                with open(task.status_path) as f:
                    for line in f:
                        if line.startswith("exit "):
                            _, code, sig = line.split()
                            task.exit_result = ExitResult(
                                exit_code=int(code), signal=int(sig)
                            )
                            task.completed_at = time.time()
                            task.done.set()
                            return
            except FileNotFoundError:
                pass
            time.sleep(interval)

    @staticmethod
    def _wait_popen(task: _RawTask, proc: subprocess.Popen) -> None:
        code = proc.wait()
        task.exit_result = ExitResult(
            exit_code=max(code, 0), signal=-code if code < 0 else 0
        )
        task.completed_at = time.time()
        task.done.set()

    # --- lifecycle ------------------------------------------------------

    def recover_task(self, handle: TaskHandle) -> None:
        """Reattach using the persisted pid/status file
        (driver.proto:35 RecoverTask + TaskHandle)."""
        with self._lock:
            if handle.config.id in self._tasks:
                return
        task = _RawTask(handle.config)
        task.pid = handle.driver_state.get("pid")
        task.pgid = handle.driver_state.get("pgid")
        task.status_path = handle.driver_state.get("status_path", "")
        task.started_at = handle.driver_state.get("started_at", time.time())
        if task.status_path:
            threading.Thread(
                target=self._poll_status, args=(task,), daemon=True
            ).start()
        elif task.pid is None or not _pid_alive(task.pid):
            task.exit_result = ExitResult(err="task no longer running")
            task.done.set()
        else:
            threading.Thread(
                target=self._poll_pid, args=(task,), daemon=True
            ).start()
        with self._lock:
            self._tasks[handle.config.id] = task

    def _poll_pid(self, task: _RawTask, interval: float = 0.1) -> None:
        while _pid_alive(task.pid):
            time.sleep(interval)
        # exit status unknowable without the executor's status file
        task.exit_result = ExitResult(err="exited while driver was detached")
        task.completed_at = time.time()
        task.done.set()

    def wait_task(self, task_id: str, timeout: Optional[float] = None) -> Optional[ExitResult]:
        task = self._get(task_id)
        if not task.done.wait(timeout):
            return None
        return task.exit_result

    def stop_task(self, task_id: str, timeout: float = 5.0, signal: str = "SIGTERM") -> None:
        task = self._get(task_id)
        if task.done.is_set() or task.pgid is None:
            return
        sig = getattr(_signal, signal, _signal.SIGTERM)
        _kill_group(task.pgid, sig)
        if not task.done.wait(timeout):
            _kill_group(task.pgid, _signal.SIGKILL)
            task.done.wait(2.0)

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        task = self._get(task_id)
        if not task.done.is_set():
            if not force:
                raise RuntimeError("task still running; use force")
            if task.pgid is not None:
                _kill_group(task.pgid, _signal.SIGKILL)
        with self._lock:
            self._tasks.pop(task_id, None)

    def inspect_task(self, task_id: str) -> TaskStatus:
        task = self._get(task_id)
        return TaskStatus(
            id=task_id,
            name=task.config.name,
            state=task.state,
            started_at=task.started_at,
            completed_at=task.completed_at,
            exit_result=task.exit_result,
        )

    def signal_task(self, task_id: str, signal: str) -> None:
        task = self._get(task_id)
        if task.pgid is not None and not task.done.is_set():
            _kill_group(task.pgid, getattr(_signal, signal, _signal.SIGTERM))

    def _exec_context(self, task: _RawTask) -> tuple:
        """(argv_prefix, env) an exec session must run under so it
        shares the task's isolation context. raw_exec has none; the
        exec driver enters the task's namespaces (the reference execs
        inside the container, executor_linux.go Exec)."""
        return [], self._build_env(task.config)

    def exec_task(self, task_id: str, cmd: List[str], timeout: float = 30.0) -> Dict:
        task = self._get(task_id)
        prefix, env = self._exec_context(task)
        proc = subprocess.run(
            prefix + cmd, cwd=task.config.alloc_dir or "/tmp",
            env=env, capture_output=True, timeout=timeout,
        )
        return {
            "stdout": proc.stdout, "stderr": proc.stderr,
            "exit_code": proc.returncode,
        }

    def exec_task_streaming(self, task_id: str, cmd: List[str],
                            tty: bool = False) -> "ExecStream":
        """Interactive exec in the task's context (driver.proto:79
        ExecTaskStreaming): a live process with bidirectional stdio,
        optionally under a pty."""
        task = self._get(task_id)
        prefix, env = self._exec_context(task)
        return ExecStream(prefix + cmd, cwd=task.config.alloc_dir or "/tmp",
                          tty=tty, env=env)

    def task_stats(self, task_id: str) -> Dict:
        task = self._get(task_id)
        stats = {"cpu": {}, "memory": {}}
        if task.pid is not None:
            try:
                with open(f"/proc/{task.pid}/statm") as f:
                    pages = int(f.read().split()[1])
                stats["memory"]["rss"] = pages * os.sysconf("SC_PAGE_SIZE")
            except (FileNotFoundError, ValueError, IndexError):
                pass
        return stats

    def _get(self, task_id: str) -> _RawTask:
        with self._lock:
            task = self._tasks.get(task_id)
        if task is None:
            raise KeyError(f"unknown task {task_id}")
        return task


class ExecStream:
    """One interactive exec session (the driver half of
    ExecTaskStreaming, driver.proto:79).

    Output is pumped by a reader thread into a queue the transport
    drains with ``read_output``; stdin writes go straight to the
    process (pty master when ``tty``)."""

    def __init__(self, cmd: List[str], cwd: str, tty: bool = False,
                 env: Optional[Dict[str, str]] = None) -> None:
        import queue as _queue

        self.tty = tty
        self._q: "_queue.Queue" = _queue.Queue()
        self._master: Optional[int] = None
        if tty:
            import pty

            master, slave = pty.openpty()
            self.proc = subprocess.Popen(
                cmd, cwd=cwd, env=env,
                stdin=slave, stdout=slave, stderr=slave,
                start_new_session=True, close_fds=True,
            )
            os.close(slave)
            self._master = master
            threading.Thread(
                target=self._pump_fd, args=(master, "stdout"),
                daemon=True, name="exec-pty-pump",
            ).start()
        else:
            self.proc = subprocess.Popen(
                cmd, cwd=cwd, env=env,
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, start_new_session=True,
            )
            threading.Thread(
                target=self._pump, args=(self.proc.stdout, "stdout"),
                daemon=True, name="exec-stdout-pump",
            ).start()
            threading.Thread(
                target=self._pump, args=(self.proc.stderr, "stderr"),
                daemon=True, name="exec-stderr-pump",
            ).start()
        threading.Thread(
            target=self._wait, daemon=True, name="exec-wait",
        ).start()

    def _pump(self, f, name: str) -> None:
        try:
            while True:
                data = f.read1(65536) if hasattr(f, "read1") else f.read(65536)
                if not data:
                    break
                self._q.put((name, data))
        except (OSError, ValueError):
            pass
        finally:
            self._q.put((name, b""))            # stream EOF marker

    def _pump_fd(self, fd: int, name: str) -> None:
        try:
            while True:
                data = os.read(fd, 65536)
                if not data:
                    break
                self._q.put((name, data))
        except OSError:
            pass
        finally:
            self._q.put((name, b""))

    def _wait(self) -> None:
        code = self.proc.wait()
        self._q.put(("exited", code))

    # -- transport-facing API -------------------------------------------

    def write_stdin(self, data: bytes) -> None:
        try:
            if self._master is not None:
                os.write(self._master, data)
            elif self.proc.stdin is not None:
                self.proc.stdin.write(data)
                self.proc.stdin.flush()
        except (OSError, ValueError, BrokenPipeError):
            pass

    def close_stdin(self) -> None:
        try:
            if self._master is not None:
                # pty has no half-close; EOT tells line-disciplined
                # programs to stop reading
                os.write(self._master, b"\x04")
            elif self.proc.stdin is not None:
                self.proc.stdin.close()
        except (OSError, ValueError):
            pass

    def resize(self, height: int, width: int) -> None:
        if self._master is None:
            return
        try:
            import fcntl
            import struct as _struct
            import termios

            fcntl.ioctl(
                self._master, termios.TIOCSWINSZ,
                _struct.pack("HHHH", height, width, 0, 0),
            )
        except OSError:
            pass

    def read_output(self, timeout: float = 0.5):
        """Next ('stdout'|'stderr', bytes) chunk, ('exited', code), or
        None on timeout. A b'' chunk marks that stream's EOF."""
        import queue as _queue

        try:
            return self._q.get(timeout=timeout)
        except _queue.Empty:
            return None

    def terminate(self) -> None:
        try:
            if self.proc.poll() is None:
                self.proc.kill()
        except OSError:
            pass
        if self._master is not None:
            try:
                os.close(self._master)
            except OSError:
                pass
            self._master = None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _kill_group(pgid: int, sig) -> None:
    try:
        os.killpg(pgid, sig)
    except ProcessLookupError:
        pass
