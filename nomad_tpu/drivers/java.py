"""java driver: run JVM workloads.

Reference behavior: drivers/java/driver.go -- fingerprints the host JVM
(`java -version` parsed into driver.java.version/runtime/vm attributes,
driver.go javaVersionInfo), launches `java [jvm_options] -jar
<jar_path> [args]` (or `-cp <class_path> <class>`) under the shared
executor WITH resource isolation (the reference java driver uses the
libcontainer executor: PID namespaces + cgroup cpu/memory limits, no
chroot — executor_linux.go via driver.go StartTask), and inherits
raw_exec's supervision/reattach machinery.
"""

from __future__ import annotations

import re
import shutil
import subprocess
from typing import Dict, List, Optional, Tuple

from nomad_tpu.drivers.execdriver import resource_executor_opts
from nomad_tpu.drivers.rawexec import RawExecDriver
from nomad_tpu.plugins.base import PLUGIN_TYPE_DRIVER, PluginInfo
from nomad_tpu.plugins.drivers import (
    HEALTH_HEALTHY,
    HEALTH_UNDETECTED,
    Fingerprint,
    TaskConfig,
)


def parse_java_version(output: str) -> Tuple[str, str, str]:
    """(version, runtime, vm) from `java -version` stderr
    (drivers/java/utils.go parseJavaVersionOutput)."""
    version = runtime = vm = ""
    lines = [ln.strip() for ln in output.splitlines() if ln.strip()]
    if lines:
        m = re.search(r'version "([^"]+)"', lines[0])
        if m:
            version = m.group(1)
    for ln in lines[1:]:
        if "Runtime Environment" in ln or "Server" in ln and not vm:
            if not runtime and "Runtime" in ln:
                runtime = ln
            elif not vm:
                vm = ln
        elif not vm and ("VM" in ln):
            vm = ln
    return version, runtime, vm


class JavaDriver(RawExecDriver):
    name = "java"

    #: overridable for tests (a fake `java` script)
    java_bin: Optional[str] = None

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name=self.name, type=PLUGIN_TYPE_DRIVER)

    def fingerprint(self) -> Fingerprint:
        java = self.java_bin or shutil.which("java")
        if java is None:
            return Fingerprint(health=HEALTH_UNDETECTED,
                               health_description="java not found")
        attrs = {f"driver.{self.name}": "1"}
        try:
            proc = subprocess.run(
                [java, "-version"], capture_output=True, text=True,
                timeout=10,
            )
            version, runtime, vm = parse_java_version(
                proc.stderr or proc.stdout)
            if version:
                attrs["driver.java.version"] = version
            if runtime:
                attrs["driver.java.runtime"] = runtime
            if vm:
                attrs["driver.java.vm"] = vm
        except Exception:                       # noqa: BLE001
            pass
        return Fingerprint(attributes=attrs, health=HEALTH_HEALTHY,
                           health_description="Healthy")

    def task_config_schema(self) -> Dict:
        return {
            "jar_path": {"type": "string"},
            "class": {"type": "string"},
            "class_path": {"type": "string"},
            "jvm_options": {"type": "list"},
            "args": {"type": "list"},
        }

    def _executor_opts(self, config: TaskConfig) -> List[str]:
        """The reference java driver runs the JVM inside the isolating
        executor: PID/mount/IPC namespaces + cgroup cpu/memory limits
        from the task's resources (driver.go StartTask ->
        executor_linux.go). No chroot — the JVM needs the host's
        classpath world."""
        return resource_executor_opts(config, cgroup_prefix="nomad-java")

    def _command(self, config: TaskConfig) -> List[str]:
        cfg = config.driver_config
        argv: List[str] = [self.java_bin or "java"]
        argv.extend(cfg.get("jvm_options") or [])
        if cfg.get("jar_path"):
            argv += ["-jar", cfg["jar_path"]]
        elif cfg.get("class"):
            if cfg.get("class_path"):
                argv += ["-cp", cfg["class_path"]]
            argv.append(cfg["class"])
        else:
            raise ValueError("java driver requires jar_path or class")
        argv.extend(cfg.get("args") or [])
        return argv
