"""java driver: run JVM workloads.

Reference behavior: drivers/java/driver.go -- fingerprints the host JVM
(`java -version` parsed into driver.java.version/runtime/vm attributes)
and launches `java [jvm_options] -jar <jar_path> [args]` (or
`-cp <class_path> <class>`) under the shared executor, inheriting
raw_exec's supervision/reattach machinery.
"""

from __future__ import annotations

import re
import shutil
import subprocess
from typing import Dict, List

from nomad_tpu.drivers.rawexec import RawExecDriver
from nomad_tpu.plugins.base import PLUGIN_TYPE_DRIVER, PluginInfo
from nomad_tpu.plugins.drivers import (
    HEALTH_HEALTHY,
    HEALTH_UNDETECTED,
    Fingerprint,
    TaskConfig,
)


class JavaDriver(RawExecDriver):
    name = "java"

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name=self.name, type=PLUGIN_TYPE_DRIVER)

    def fingerprint(self) -> Fingerprint:
        java = shutil.which("java")
        if java is None:
            return Fingerprint(health=HEALTH_UNDETECTED,
                               health_description="java not found")
        attrs = {f"driver.{self.name}": "1"}
        try:
            out = subprocess.run(
                [java, "-version"], capture_output=True, text=True, timeout=10
            ).stderr
            m = re.search(r'version "([^"]+)"', out)
            if m:
                attrs["driver.java.version"] = m.group(1)
        except Exception:                       # noqa: BLE001
            pass
        return Fingerprint(attributes=attrs, health=HEALTH_HEALTHY,
                           health_description="Healthy")

    def task_config_schema(self) -> Dict:
        return {
            "jar_path": {"type": "string"},
            "class": {"type": "string"},
            "class_path": {"type": "string"},
            "jvm_options": {"type": "list"},
            "args": {"type": "list"},
        }

    def _command(self, config: TaskConfig) -> List[str]:
        cfg = config.driver_config
        argv: List[str] = ["java"]
        argv.extend(cfg.get("jvm_options") or [])
        if cfg.get("jar_path"):
            argv += ["-jar", cfg["jar_path"]]
        elif cfg.get("class"):
            if cfg.get("class_path"):
                argv += ["-cp", cfg["class_path"]]
            argv.append(cfg["class"])
        else:
            raise ValueError("java driver requires jar_path or class")
        argv.extend(cfg.get("args") or [])
        return argv
