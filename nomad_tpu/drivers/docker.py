"""docker driver: container workloads via the docker CLI.

Reference behavior: drivers/docker/ (10.9k LoC against the daemon API)
-- fingerprints the daemon (driver.docker.version; undetected when the
socket is absent), runs containers with resource limits, env, port
publishing, and log collection, and stops via the engine so the
container gets a graceful shutdown window.

This build drives the docker CLI: a foreground ``docker run`` process
is supervised by the shared executor (signals proxy through the CLI),
while stop/destroy go through ``docker stop``/``docker rm`` so
engine-side state is cleaned up. Operational surface beyond run/stop:

- image pulls are singleflighted per image across concurrent tasks
  (coordinator.go), probing ``docker image inspect`` first
- ``task_stats`` reads RAW stats from the engine API over the unix
  socket (drivers/docker/stats.go semantics: cpu-delta math over
  precpu, memory usage net of reclaimable cache; docker_api.py) with
  CLI and process-stats fallbacks
- a detached ``docklog`` subprocess follows the container's log
  stream from the ENGINE into the task log files
  (docklog/docklog.go): output keeps flowing across agent restarts
  independent of the CLI attachment, and recover_task respawns a dead
  docklog; without a live engine socket the foreground ``docker run``
  still writes through the executor into the logmon collector
- interactive exec streams through ``docker exec -i[t]`` INSIDE the
  container (driver.proto:79)

Gated: nodes without a reachable daemon fingerprint as undetected and
never receive docker tasks.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import threading
from typing import Dict, List, Optional

from nomad_tpu.drivers.rawexec import ExecStream, RawExecDriver
from nomad_tpu.plugins.base import PLUGIN_TYPE_DRIVER, PluginInfo
from nomad_tpu.plugins.drivers import (
    HEALTH_HEALTHY,
    HEALTH_UNDETECTED,
    DriverCapabilities,
    Fingerprint,
    NetworkIsolationSpec,
    TaskConfig,
    TaskHandle,
)


def _container_name(config: TaskConfig) -> str:
    return f"nomad-{config.name}-{config.alloc_id[:8] or config.id[:8]}"


def _registry_of(image: str) -> str:
    """Registry host of an image reference (driver.go repository
    parsing): 'gcr.io/proj/app:v1' -> 'gcr.io'; bare names -> the
    default index."""
    first = image.split("/", 1)[0]
    if "/" in image and ("." in first or ":" in first
                        or first == "localhost"):
        return first
    return "https://index.docker.io/v1/"


class ImageCoordinator:
    """Reference-counted image lifecycle (drivers/docker/coordinator.go):
    every running task holds a reference on its image; when the last
    reference drops, removal is scheduled after ``remove_delay`` so a
    rescheduled task can reuse the layer cache; a new reference before
    the deadline cancels the removal."""

    def __init__(self, remove_delay: float = 180.0,
                 cleanup: bool = True, lock_for=None) -> None:
        self.remove_delay = remove_delay
        self.cleanup = cleanup
        self._lock = threading.Lock()
        self._refs: Dict[str, set] = {}
        self._timers: Dict[str, threading.Timer] = {}
        # per-image serialization with the driver's pull/probe path:
        # rmi takes the same lock _ensure_image pulls under, so a
        # concurrent probe can never see the image mid-removal, skip
        # the pull, and then fail its container start
        self._own_locks: Dict[str, threading.Lock] = {}
        self._lock_for = lock_for or self._default_lock_for

    def _default_lock_for(self, image: str) -> threading.Lock:
        with self._lock:
            return self._own_locks.setdefault(image, threading.Lock())

    def use(self, image: str, task_id: str) -> None:
        with self._lock:
            self._refs.setdefault(image, set()).add(task_id)
            timer = self._timers.pop(image, None)
        if timer is not None:
            timer.cancel()

    def release(self, image: str, task_id: str) -> None:
        with self._lock:
            refs = self._refs.get(image)
            if refs is None:
                return
            refs.discard(task_id)
            if refs or not self.cleanup:
                return
            del self._refs[image]
            old = self._timers.pop(image, None)
            timer = threading.Timer(
                self.remove_delay, self._remove, args=(image,))
            timer.daemon = True
            self._timers[image] = timer
        if old is not None:
            old.cancel()
        timer.start()

    def _remove(self, image: str) -> None:
        # the pull lock serializes rmi against _ensure_image's
        # probe+pull, closing the window where a probe sees the image
        # present mid-rmi (the rmi subprocess can take up to 120s)
        with self._lock_for(image):
            with self._lock:
                self._timers.pop(image, None)
                # last-instant re-check: a use() racing the timer fire
                # must win
                if self._refs.get(image):
                    return
            try:
                subprocess.run(["docker", "rmi", image],
                               capture_output=True, timeout=120)
            except Exception:               # noqa: BLE001
                pass

    def shutdown(self) -> None:
        with self._lock:
            timers = list(self._timers.values())
            self._timers.clear()
        for t in timers:
            t.cancel()


class DockerDriver(RawExecDriver):
    name = "docker"

    def __init__(self, options: Optional[Dict[str, str]] = None) -> None:
        super().__init__()
        opts = options or {}
        # Host bind mounts are host-root-equivalent for job submitters,
        # so the reference disables them unless the operator opts in
        # (drivers/docker config "volumes.enabled", default false).
        self.volumes_enabled = str(
            opts.get("docker.volumes.enabled", "false")).lower() in (
                "1", "true", "yes")
        # registry auth backends (driver.go:604
        # resolveRegistryAuthentication): a docker config FILE and/or a
        # credential HELPER configured by the operator; the task's own
        # auth block is checked first
        self.auth_config_file = opts.get("docker.auth.config", "")
        self.auth_helper = opts.get("docker.auth.helper", "")
        # pause/infra container image for driver-created group networks
        # (drivers/docker/network.go, config "infra_image")
        self.infra_image = opts.get(
            "docker.infra_image", "gcr.io/google_containers/pause-amd64:3.3")
        # image refcount GC (coordinator.go): delayed removal after the
        # last task using an image stops
        self.images = ImageCoordinator(
            remove_delay=float(opts.get("docker.cleanup.image.delay",
                                        "180")),
            cleanup=str(opts.get("docker.cleanup.image", "true")).lower()
            in ("1", "true", "yes"),
            lock_for=self._pull_lock_for,
        )

    #: image -> lock: concurrent tasks of one image pull it ONCE
    #: (drivers/docker/coordinator.go singleflight)
    _pull_locks: Dict[str, threading.Lock] = {}
    _pull_locks_guard = threading.Lock()

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name=self.name, type=PLUGIN_TYPE_DRIVER)

    def capabilities(self) -> DriverCapabilities:
        caps = super().capabilities()
        # containers cannot join a client-made netns: docker builds the
        # group sandbox itself (network.go MustInitiateNetwork)
        caps.must_create_network = True
        return caps

    # -- DriverNetworkManager (drivers/docker/network.go) ----------------

    @staticmethod
    def _pause_name(alloc_id: str) -> str:
        return f"nomad-pause-{alloc_id[:8]}"

    def create_network(self, alloc_id: str,
                       port_mappings=None) -> NetworkIsolationSpec:
        """Start the allocation's pause container: every task container
        joins ITS network namespace (``--network container:<pause>``),
        so group tasks share localhost; the scheduler's host-port
        assignments publish on the pause container (the namespace
        owner), exactly like the reference's infra container."""
        name = self._pause_name(alloc_id)
        self._ensure_image(self.infra_image)
        # idempotent: a stale pause container from a crashed prior
        # attempt (or a destroy the agent never ran) would make --name
        # conflict permanently
        subprocess.run(["docker", "rm", "-f", name],
                       capture_output=True, timeout=30)
        argv = ["docker", "run", "-d", "--name", name]
        for host, container in port_mappings or []:
            argv += ["-p", f"{host}:{container}"]
        argv.append(self.infra_image)
        out = subprocess.run(argv, capture_output=True, timeout=120)
        if out.returncode != 0:
            subprocess.run(["docker", "rm", "-f", name],
                           capture_output=True, timeout=30)
            raise RuntimeError(
                f"pause container: "
                f"{out.stderr.decode(errors='replace')[:300]}")
        return NetworkIsolationSpec(
            mode="group", ip=self._sandbox_ip(name),
            labels={"docker_sandbox_container": name})

    def _sandbox_ip(self, name: str) -> str:
        out = subprocess.run(
            ["docker", "inspect", "-f",
             "{{range .NetworkSettings.Networks}}{{.IPAddress}}{{end}}",
             name],
            capture_output=True, text=True, timeout=30)
        return out.stdout.strip() if out.returncode == 0 else ""

    def recover_network(self, alloc_id: str, port_mappings=None
                        ) -> Optional[NetworkIsolationSpec]:
        """Re-adopt a pause container that outlived the agent. The
        container must be RUNNING: containers cannot join the network
        of an exited one, so a stopped sandbox (host reboot) is removed
        and recreated with its original port mappings."""
        name = self._pause_name(alloc_id)
        probe = subprocess.run(
            ["docker", "inspect", "-f", "{{.State.Running}}", name],
            capture_output=True, text=True, timeout=30)
        if probe.returncode != 0:
            return None
        if probe.stdout.strip() != "true":
            return self.create_network(alloc_id, port_mappings)
        return NetworkIsolationSpec(
            mode="group", ip=self._sandbox_ip(name),
            labels={"docker_sandbox_container": name})

    def destroy_network(self, alloc_id: str,
                        spec: NetworkIsolationSpec) -> None:
        name = ((spec.labels or {}).get("docker_sandbox_container")
                if spec is not None else "") or self._pause_name(alloc_id)
        subprocess.run(["docker", "rm", "-f", name],
                       capture_output=True, timeout=30)

    # -- registry authentication (driver.go:604) -------------------------

    def _resolve_registry_auth(self, image: str,
                               task_auth: Optional[Dict] = None
                               ) -> Optional[Dict[str, str]]:
        """Backend chain, first hit wins: the task's own ``auth`` block,
        the operator's docker config file (auths + credHelpers), then
        the operator's credential helper
        (``docker-credential-<helper> get``)."""
        import base64

        registry = _registry_of(image)
        if task_auth and task_auth.get("username"):
            return {"username": str(task_auth["username"]),
                    "password": str(task_auth.get("password", "")),
                    "server": str(task_auth.get("server_address")
                                  or registry)}
        if self.auth_config_file:
            try:
                with open(self.auth_config_file) as f:
                    cfg = json.load(f)
            except (OSError, json.JSONDecodeError):
                cfg = {}
            entry = (cfg.get("auths") or {}).get(registry)
            if entry is None and registry.startswith("https://"):
                entry = (cfg.get("auths") or {}).get(
                    registry.removeprefix("https://"))
            if entry and entry.get("auth"):
                try:
                    user, _, pw = base64.b64decode(
                        entry["auth"]).decode().partition(":")
                    return {"username": user, "password": pw,
                            "server": registry}
                except Exception:       # noqa: BLE001
                    pass
            helper = (cfg.get("credHelpers") or {}).get(registry)
            if helper:
                got = self._run_cred_helper(helper, registry)
                if got:
                    return got
        if self.auth_helper:
            return self._run_cred_helper(self.auth_helper, registry)
        return None

    @staticmethod
    def _run_cred_helper(helper: str, registry: str
                         ) -> Optional[Dict[str, str]]:
        """`docker-credential-<helper> get` speaking the credential
        helper protocol (docker-credential-helpers wire shape)."""
        try:
            out = subprocess.run(
                [f"docker-credential-{helper}", "get"],
                input=registry.encode(), capture_output=True, timeout=30,
            )
            if out.returncode != 0:
                return None
            got = json.loads(out.stdout.decode())
            return {"username": str(got.get("Username", "")),
                    "password": str(got.get("Secret", "")),
                    "server": str(got.get("ServerURL") or registry)}
        except Exception:               # noqa: BLE001
            return None

    # -- image pull coordination (coordinator.go) ------------------------

    @classmethod
    def _pull_lock_for(cls, image: str) -> threading.Lock:
        with cls._pull_locks_guard:
            return cls._pull_locks.setdefault(image, threading.Lock())

    def _ensure_image(self, image: str, timeout: float = 600.0,
                      task_auth: Optional[Dict] = None) -> None:
        with self._pull_lock_for(image):
            probe = subprocess.run(
                ["docker", "image", "inspect", image],
                capture_output=True, timeout=60,
            )
            if probe.returncode == 0:
                return
            auth = self._resolve_registry_auth(image, task_auth)
            argv, cfg_dir = ["docker"], None
            if auth is not None:
                # an ephemeral --config dir carries the credentials to
                # THIS pull only (the API-path X-Registry-Auth analog)
                # without touching the operator's docker login state
                import base64
                import tempfile

                cfg_dir = tempfile.mkdtemp(prefix="nomad-docker-auth-")
                token = base64.b64encode(
                    f"{auth['username']}:{auth['password']}".encode()
                ).decode()
                with open(f"{cfg_dir}/config.json", "w") as f:
                    json.dump(
                        {"auths": {auth["server"]: {"auth": token}}}, f)
                argv += ["--config", cfg_dir]
            try:
                pull = subprocess.run(
                    argv + ["pull", image],
                    capture_output=True, timeout=timeout,
                )
            finally:
                if cfg_dir is not None:
                    shutil.rmtree(cfg_dir, ignore_errors=True)
            if pull.returncode != 0:
                raise RuntimeError(
                    f"docker pull {image}: "
                    f"{pull.stderr.decode(errors='replace')[:300]}"
                )

    #: engine socket; overridable for tests (fake engine)
    engine_socket = "/var/run/docker.sock"

    def _engine(self, ping: bool = True):
        """Engine API client when the daemon socket answers, else
        None (CLI fallbacks remain). ``ping=False`` skips the probe
        roundtrip for callers that already handle call failure."""
        import os

        from nomad_tpu.drivers.docker_api import DockerEngine

        if not os.path.exists(self.engine_socket):
            return None
        try:
            engine = DockerEngine(self.engine_socket)
            if ping and not engine.ping():
                return None
            return engine
        except Exception:                       # noqa: BLE001
            return None

    def start_task(self, config: TaskConfig) -> TaskHandle:
        import os

        image = config.driver_config.get("image")
        if not image:
            raise ValueError("docker driver requires image")
        # reference BEFORE the pull (coordinator.go registers inside
        # PullImage): a pending removal timer is cancelled before the
        # inspect probe can be invalidated by it
        self.images.use(image, config.id)
        try:
            self._ensure_image(image,
                               task_auth=config.driver_config.get("auth"))
            engine_live = self._engine() is not None
            real_out, real_err = config.std_out_path, config.std_err_path
            if engine_live:
                # docklog is the log path (the reference never attaches
                # `docker run` output either); the CLI attachment would
                # write every container line a second time
                config.std_out_path = os.devnull
                config.std_err_path = os.devnull
            try:
                handle = super().start_task(config)
            finally:
                config.std_out_path, config.std_err_path = \
                    real_out, real_err
        except BaseException:
            # a failed start must not strand the reference (the image
            # would be exempt from GC forever)
            self.images.release(image, config.id)
            raise
        if engine_live:
            self._start_docklog(config, handle, engine_checked=True)
        return handle

    # -- docklog (drivers/docker/docklog/docklog.go) ---------------------

    def _start_docklog(self, config: TaskConfig, handle: TaskHandle,
                       since: int = 0, engine_checked: bool = False) -> None:
        """Detached engine-log follower: task output keeps flowing
        across agent restarts independent of the CLI attachment. Only
        when the engine socket is live (CLI-attached logs still work
        through the executor/logmon path otherwise). ``since`` bounds
        a respawned follower so history is not re-appended."""
        import os
        import sys as _sys

        if not engine_checked and self._engine() is None:
            return
        workdir = config.alloc_dir or "/tmp"
        stdout = config.std_out_path or os.path.join(workdir, "stdout")
        stderr = config.std_err_path or os.path.join(workdir, "stderr")
        script = os.path.join(os.path.dirname(__file__), "docklog.py")
        proc = subprocess.Popen(
            [_sys.executable, "-S", script, self.engine_socket,
             _container_name(config), stdout, stderr, str(since)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
        handle.driver_state["docklog_pid"] = proc.pid

    def recover_task(self, handle: TaskHandle) -> None:
        super().recover_task(handle)
        # the recovered task holds its image reference again
        # (coordinator.go re-registers on recovery)
        image = handle.config.driver_config.get("image")
        if image:
            self.images.use(image, handle.config.id)
        # docklog survives with the task; respawn only when it died
        # (docklog.go reattach-or-restart on recover)
        import os

        pid = int(handle.driver_state.get("docklog_pid") or 0)
        alive = False
        if pid > 0:
            try:
                os.kill(pid, 0)
                alive = True
            except OSError:
                alive = False
        if not alive:
            # resume from now: history is already in the files (the
            # reference docklog resumes from a saved timestamp)
            import time as _time

            self._start_docklog(handle.config, handle,
                                since=int(_time.time()))

    def fingerprint(self) -> Fingerprint:
        docker = shutil.which("docker")
        if docker is None:
            return Fingerprint(health=HEALTH_UNDETECTED,
                               health_description="docker not found")
        try:
            out = subprocess.run(
                [docker, "version", "--format", "{{.Server.Version}}"],
                capture_output=True, text=True, timeout=10,
            )
            if out.returncode != 0:
                return Fingerprint(
                    health=HEALTH_UNDETECTED,
                    health_description="docker daemon unreachable",
                )
            version = out.stdout.strip()
        except Exception:                       # noqa: BLE001
            return Fingerprint(health=HEALTH_UNDETECTED,
                               health_description="docker daemon unreachable")
        return Fingerprint(
            attributes={f"driver.{self.name}": "1",
                        "driver.docker.version": version},
            health=HEALTH_HEALTHY,
            health_description="Healthy",
        )

    def task_config_schema(self) -> Dict:
        return {
            "image": {"type": "string", "required": True},
            "command": {"type": "string"},
            "args": {"type": "list"},
            "ports": {"type": "list"},        # port labels to publish
            "volumes": {"type": "list"},      # host:container binds
            "network_mode": {"type": "string"},
        }

    def _command(self, config: TaskConfig) -> List[str]:
        cfg = config.driver_config
        image = cfg.get("image")
        if not image:
            raise ValueError("docker driver requires image")
        argv: List[str] = [
            "docker", "run", "--rm", "--init",
            "--name", _container_name(config),
        ]
        if config.resources.memory_mb:
            argv += ["--memory", f"{config.resources.memory_mb}m"]
        if config.resources.cpu:
            # MHz shares -> relative CPU weight (docker driver
            # cpu_shares mapping)
            argv += ["--cpu-shares", str(config.resources.cpu)]
        for key, value in config.env.items():
            argv += ["-e", f"{key}={value}"]
        sandbox = ""
        if config.network_isolation is not None:
            sandbox = (config.network_isolation.labels or {}).get(
                "docker_sandbox_container", "")
        if sandbox:
            # join the driver-created group namespace; ports publish on
            # the pause container (the namespace owner), so per-task
            # -p flags are invalid here (network.go)
            argv += ["--network", f"container:{sandbox}"]
        elif cfg.get("network_mode"):
            argv += ["--network", cfg["network_mode"]]
        if not sandbox:
            for label in cfg.get("ports") or []:
                for net in config.resources.networks:
                    assigned = net.port_for_label(label)
                    if assigned:
                        for p in (list(net.reserved_ports)
                                  + list(net.dynamic_ports)):
                            if p.label == label:
                                argv += ["-p",
                                         f"{assigned}:{p.to or assigned}"]
        if cfg.get("volumes"):
            if not self.volumes_enabled:
                # reject, never silently drop binds the task depends on
                raise ValueError(
                    "docker volumes are disabled on this client; set "
                    "client option docker.volumes.enabled=true")
            for bind in cfg["volumes"]:
                argv += ["-v", bind]
        argv.append(image)
        if cfg.get("command"):
            argv.append(cfg["command"])
        argv.extend(cfg.get("args") or [])
        return argv

    def _build_env(self, config: TaskConfig) -> Dict[str, str]:
        # env goes into the container via -e flags; the docker CLI
        # itself just needs a sane PATH/HOME
        import os

        return {"PATH": os.environ.get("PATH", "/usr/bin:/bin"),
                "HOME": os.environ.get("HOME", "/tmp")}

    def stop_task(self, task_id: str, timeout: float = 5.0,
                  signal: str = "SIGTERM") -> None:
        task = self._tasks.get(task_id)
        if task is not None:
            subprocess.run(
                ["docker", "stop", "-t", str(int(timeout)),
                 _container_name(task.config)],
                capture_output=True, timeout=timeout + 10,
            )
        super().stop_task(task_id, timeout=timeout, signal=signal)

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        task = self._tasks.get(task_id)
        # super() validates first (a live task without force raises):
        # the container removal and the image-reference drop happen
        # only when the destroy actually goes through
        super().destroy_task(task_id, force=force)
        if task is not None:
            subprocess.run(
                ["docker", "rm", "-f", _container_name(task.config)],
                capture_output=True, timeout=30,
            )
            # the engine closes the log stream when the container goes;
            # docklog exits on its own — nothing to reap here beyond
            # the normal child cleanup
            image = task.config.driver_config.get("image")
            if image:
                self.images.release(image, task.config.id)

    def exec_task(self, task_id: str, cmd: List[str],
                  timeout: float = 30.0) -> Dict:
        task = self._tasks.get(task_id)
        if task is None:
            raise KeyError(f"unknown task {task_id}")
        out = subprocess.run(
            ["docker", "exec", _container_name(task.config)] + cmd,
            capture_output=True, text=True, timeout=timeout,
        )
        return {"stdout": out.stdout, "stderr": out.stderr,
                "exit_code": out.returncode}

    def exec_task_streaming(self, task_id: str, cmd: List[str],
                            tty: bool = False) -> ExecStream:
        """Interactive exec INSIDE the container (driver.proto:79 via
        `docker exec -i[t]`)."""
        task = self._get(task_id)
        flags = ["-it" if tty else "-i"]
        return ExecStream(
            ["docker", "exec", *flags, _container_name(task.config)] + cmd,
            cwd=task.config.alloc_dir or "/tmp", tty=tty,
            env=self._build_env(task.config),
        )

    def task_stats(self, task_id: str) -> Dict:
        """Container stats from the engine API (drivers/docker/stats.go:
        raw cgroup counters + cpu-delta math), falling back to the CLI
        then to process stats."""
        task = self._get(task_id)
        # no ping: the stats call itself is the probe (halves socket
        # traffic on the collection hot path); any transport flake
        # falls back to the CLI below
        engine = self._engine(ping=False)
        if engine is not None:
            from nomad_tpu.drivers.docker_api import (
                TRANSPORT_ERRORS,
                EngineError,
                compute_cpu_percent,
                memory_rss,
            )

            try:
                raw = engine.stats(_container_name(task.config))
                return {
                    "cpu": {"percent": compute_cpu_percent(raw)},
                    "memory": {"rss": memory_rss(raw)},
                }
            except TRANSPORT_ERRORS + (EngineError,):
                pass
        out = subprocess.run(
            ["docker", "stats", "--no-stream", "--format", "{{json .}}",
             _container_name(task.config)],
            capture_output=True, text=True, timeout=30,
        )
        stats: Dict = {"cpu": {}, "memory": {}}
        if out.returncode != 0 or not out.stdout.strip():
            return super().task_stats(task_id)
        try:
            row = json.loads(out.stdout.strip().splitlines()[0])
        except json.JSONDecodeError:
            return super().task_stats(task_id)
        cpu = str(row.get("CPUPerc", "")).rstrip("%")
        try:
            stats["cpu"]["percent"] = float(cpu)
        except ValueError:
            pass
        mem = str(row.get("MemUsage", "")).split("/")[0].strip()
        stats["memory"]["rss"] = _parse_size(mem)
        return stats


_SIZE_UNITS = {"b": 1, "kb": 1000, "kib": 1024, "mb": 1000 ** 2,
               "mib": 1024 ** 2, "gb": 1000 ** 3, "gib": 1024 ** 3}


def _parse_size(text: str) -> int:
    """'21.48MiB' -> bytes (docker stats human units)."""
    import re

    m = re.fullmatch(r"([\d.]+)\s*([A-Za-z]+)", text.strip())
    if not m:
        return 0
    try:
        value = float(m.group(1))
    except ValueError:
        return 0
    return int(value * _SIZE_UNITS.get(m.group(2).lower(), 1))
