"""Built-in task drivers.

Reference behavior: drivers/ (SURVEY.md section 2.8) -- docker, exec,
rawexec, java, qemu, mock, registered in-process via the plugin catalog
(helper/pluginutils/catalog/register.go). Built-ins here: ``mock`` (the
fully scriptable test driver, drivers/mock), ``raw_exec`` (host
subprocesses, drivers/rawexec), ``exec`` (subprocesses with best-effort
isolation, drivers/exec). The shared native executor
(drivers/shared/executor) supervises children from a separate process
so tasks survive agent restarts.
"""

from typing import Dict

from nomad_tpu.plugins.drivers import DriverPlugin


def builtin_drivers() -> Dict[str, DriverPlugin]:
    """catalog/register.go: the in-process driver registry."""
    from nomad_tpu.drivers.mock import MockDriver
    from nomad_tpu.drivers.rawexec import RawExecDriver
    from nomad_tpu.drivers.execdriver import ExecDriver

    return {
        "mock_driver": MockDriver(),
        "raw_exec": RawExecDriver(),
        "exec": ExecDriver(),
    }
