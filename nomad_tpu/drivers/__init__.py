"""Built-in task drivers.

Reference behavior: drivers/ (SURVEY.md section 2.8) -- docker, exec,
rawexec, java, qemu, mock, registered in-process via the plugin catalog
(helper/pluginutils/catalog/register.go). All six are registered here;
fingerprinting gates placement (the scheduler's DriverChecker skips
nodes where a driver is undetected, e.g. no JVM / no qemu binary / no
docker daemon). The shared native executor (drivers/shared/executor)
supervises children from a separate process so tasks survive agent
restarts.
"""

from typing import Dict, Optional

from nomad_tpu.plugins.drivers import DriverPlugin


def builtin_drivers(
    options: Optional[Dict[str, str]] = None,
) -> Dict[str, DriverPlugin]:
    """catalog/register.go: the in-process driver registry.

    ``options`` is the agent's client-options map (config.go Options);
    drivers read their knobs from it, e.g. ``docker.volumes.enabled``.
    """
    from nomad_tpu.drivers.mock import MockDriver
    from nomad_tpu.drivers.rawexec import RawExecDriver
    from nomad_tpu.drivers.execdriver import ExecDriver
    from nomad_tpu.drivers.java import JavaDriver
    from nomad_tpu.drivers.qemu import QemuDriver
    from nomad_tpu.drivers.docker import DockerDriver

    options = options or {}
    return {
        "mock_driver": MockDriver(),
        "raw_exec": RawExecDriver(),
        "exec": ExecDriver(),
        "java": JavaDriver(),
        "qemu": QemuDriver(),
        "docker": DockerDriver(options=options),
    }
