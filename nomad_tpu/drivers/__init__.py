"""Built-in task drivers.

Reference behavior: drivers/ (SURVEY.md section 2.8) -- docker, exec,
rawexec, java, qemu, mock, registered in-process via the plugin catalog
(helper/pluginutils/catalog/register.go). All six are registered here;
fingerprinting gates placement (the scheduler's DriverChecker skips
nodes where a driver is undetected, e.g. no JVM / no qemu binary / no
docker daemon). The shared native executor (drivers/shared/executor)
supervises children from a separate process so tasks survive agent
restarts.
"""

from typing import Dict

from nomad_tpu.plugins.drivers import DriverPlugin


def builtin_drivers() -> Dict[str, DriverPlugin]:
    """catalog/register.go: the in-process driver registry."""
    from nomad_tpu.drivers.mock import MockDriver
    from nomad_tpu.drivers.rawexec import RawExecDriver
    from nomad_tpu.drivers.execdriver import ExecDriver
    from nomad_tpu.drivers.java import JavaDriver
    from nomad_tpu.drivers.qemu import QemuDriver
    from nomad_tpu.drivers.docker import DockerDriver

    return {
        "mock_driver": MockDriver(),
        "raw_exec": RawExecDriver(),
        "exec": ExecDriver(),
        "java": JavaDriver(),
        "qemu": QemuDriver(),
        "docker": DockerDriver(),
    }
