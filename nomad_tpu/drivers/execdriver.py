"""exec driver: subprocesses under namespaces + cgroup limits.

Reference behavior: drivers/exec/driver.go -- like raw_exec but runs
the workload isolated via the shared executor
(drivers/shared/executor/executor_linux.go, libcontainer). The native
executor (native/executor.cc) provides the same primitives directly:
PID+mount+IPC namespaces (the task is pid 1 and its /proc shows only
its own tree), cgroup cpu/memory limits enforced from the task's
``resources`` stanza, and an optional chroot. Capabilities are probed
once per process; environments without namespace privileges degrade
to raw_exec-style supervision (and the fingerprint reflects it), the
same way the reference refuses non-root/cgroup-less clients.
"""

from __future__ import annotations

import functools
import os
import subprocess
from typing import Dict, List

from nomad_tpu.plugins.base import PLUGIN_TYPE_DRIVER, PluginInfo
from nomad_tpu.plugins.drivers import DriverCapabilities, TaskConfig
from nomad_tpu.drivers.rawexec import RawExecDriver


def resource_executor_opts(config, cgroup_prefix: str) -> List[str]:
    """Namespace + cgroup flags for the native executor from a task's
    resources (executor_linux.go resource/namespace wiring) — shared
    by every isolating driver (exec, java)."""
    support = isolation_support()
    opts: List[str] = []
    if support["namespaces"]:
        opts.append("-isolate")
    if support["cgroups"]:
        res = config.resources
        mem = int(getattr(res, "memory_mb", 0) or 0) if res else 0
        cpu = int(getattr(res, "cpu", 0) or 0) if res else 0
        if mem > 0:
            opts += ["-mem_mb", str(mem)]
        if cpu > 0:
            opts += ["-cpu_shares", str(cpu)]
        if mem > 0 or cpu > 0:
            opts += ["-cgroup", f"{cgroup_prefix}-{config.id[:16]}"]
    return opts


@functools.lru_cache(maxsize=1)
def isolation_support() -> Dict[str, bool]:
    """Probe once: can this host unshare namespaces / write cgroups?"""
    ns = False
    try:
        probe = subprocess.run(
            ["unshare", "--pid", "--mount", "--ipc", "--fork",
             "/bin/true"],
            capture_output=True, timeout=10,
        )
        ns = probe.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        ns = False
    cg = False
    for path in ("/sys/fs/cgroup/cgroup.controllers",
                 "/sys/fs/cgroup/memory"):
        if os.path.exists(path):
            cg = os.access(os.path.dirname(path) if path.endswith(
                "cgroup.controllers") else path, os.W_OK)
            if cg:
                break
    return {"namespaces": ns, "cgroups": cg}


class ExecDriver(RawExecDriver):
    name = "exec"

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name=self.name, type=PLUGIN_TYPE_DRIVER)

    def capabilities(self) -> DriverCapabilities:
        return DriverCapabilities(
            send_signals=True, exec_=True, fs_isolation="chroot"
        )

    def _executor_opts(self, config: TaskConfig) -> List[str]:
        """Namespace + cgroup flags for the native executor
        (executor_linux.go resource/namespace wiring)."""
        opts = resource_executor_opts(config, cgroup_prefix="nomad")
        chroot = (config.driver_config or {}).get("chroot")
        if chroot:
            opts += ["-chroot", str(chroot)]
        return opts

    def _exec_context(self, task):
        """Exec sessions join the task's namespaces via nsenter (the
        reference execs inside the container, executor_linux.go Exec)
        and get the task's scrubbed env — never the agent's."""
        env = self._build_env(task.config)
        if isolation_support()["namespaces"] and task.pid:
            prefix = ["nsenter", "-t", str(task.pid),
                      "-p", "-m", "-i", "--"]
            return prefix, env
        return [], env

    def _build_env(self, config: TaskConfig) -> Dict[str, str]:
        env = {
            "PATH": "/usr/local/bin:/usr/bin:/bin",
            "HOME": config.alloc_dir or "/tmp",
            "NOMAD_ALLOC_ID": config.alloc_id,
            "NOMAD_TASK_NAME": config.name,
        }
        env.update(config.env)
        return env
