"""exec driver: subprocesses with best-effort isolation.

Reference behavior: drivers/exec/driver.go -- like raw_exec but runs
the workload in namespaces/cgroups via libcontainer
(executor_linux.go). Container primitives aren't assumed available
here; isolation is best-effort: own session+process group (via the
native executor), working dir confined to the alloc dir, and a scrubbed
environment (exec tasks do not inherit the agent's env). The
fs_isolation capability is reported accordingly.
"""

from __future__ import annotations

from typing import Dict

from nomad_tpu.plugins.base import PLUGIN_TYPE_DRIVER, PluginInfo
from nomad_tpu.plugins.drivers import DriverCapabilities, TaskConfig
from nomad_tpu.drivers.rawexec import RawExecDriver


class ExecDriver(RawExecDriver):
    name = "exec"

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name=self.name, type=PLUGIN_TYPE_DRIVER)

    def capabilities(self) -> DriverCapabilities:
        return DriverCapabilities(
            send_signals=True, exec_=True, fs_isolation="chroot"
        )

    def _build_env(self, config: TaskConfig) -> Dict[str, str]:
        env = {
            "PATH": "/usr/local/bin:/usr/bin:/bin",
            "HOME": config.alloc_dir or "/tmp",
            "NOMAD_ALLOC_ID": config.alloc_id,
            "NOMAD_TASK_NAME": config.name,
        }
        env.update(config.env)
        return env
