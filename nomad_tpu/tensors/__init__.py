"""Tensor flattening contract: structs -> fixed-width device arrays.

This is the TPU-native seam that has no analog in the reference: the
scheduling-relevant state of the cluster (reference structs.NodeResources /
AllocatedResources, SURVEY.md section 2.1 TPU note) flattens into
struct-of-arrays numpy planes with static, bucket-padded shapes so the
JAX kernel in ``nomad_tpu.ops`` never recompiles as the cluster grows.
"""

from nomad_tpu.tensors.schema import (  # noqa: F401
    AskTensor,
    ClusterTensors,
    EvalTensors,
    MAX_RESERVED_PORT_ASKS,
    MAX_SPREADS,
    pad_bucket,
)
