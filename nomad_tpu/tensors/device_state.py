"""Device-resident cluster state: kill the per-wave h2d tax.

PR 2's steady-state TRACE_DECOMP made h2d the dominant cost (30.4% of
wall, 4.48 ms/eval): every coalesced wave re-uploaded the full
node x resource shared planes even though the host side already knew
exactly which rows changed (the incremental ClusterTensors cache and
the usage index's change logs). This module is the device half of that
design: the wave-shared planes — the cluster-static capacity planes
plus the snapshot's gathered utilization (``ClusterTensors.
wave_shared_planes``) — live ON the accelerator as committed arrays,
keyed by ``(uid, structure_version)`` generations, and advance between
waves by uploading only the dirty rows and applying them with a jit'd
scatter (``plane.at[rows].set(vals)``).

Advancement is **functional**: a scatter produces new device arrays
while the previous generation's buffers stay untouched, so a wave
still executing against version N never races version N+1's upload —
the double-buffering that lets the (tiny) h2d of the next wave overlap
the current wave's execute. Resident generations are LRU-bounded;
every miss (unprovable log, permuted rows, pad-bucket change, evicted
base) falls back to a full plane upload, which is bit-identical by
construction and property-tested against a fresh
``ClusterTensors.build`` + upload (tests/test_device_state.py, the
device mirror of tests/test_cluster_delta.py).

Dirty-row provenance:

- utilization planes: ``UsagePlanes.row_events`` (state/usage.py), the
  per-version log of nodes whose rows an alloc transition moved,
  complete above ``row_events_floor``;
- cluster-static planes across a ``structure_version`` fork:
  ``UsagePlanes.node_events``, the same log the host-side
  ``IncrementalClusterCache`` replays — usable on device only when the
  surviving rows kept their positions (additions/updates); a
  compaction that permutes rows falls back to a full upload.

The registry maps *host array identity* -> committed device array, the
same identity contract the wave coalescer's sharing layout is built
on: ``launch_wave`` (and ``default_kernel_launch``) swap a shared host
leaf for its resident device twin, making ``jax.device_put`` a no-op
for every plane that didn't change. Frozen neutral singletons
(ops/kernel.neutral_planes etc.) ride the same registry via a bounded
resident cache — they upload once per process, ever.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from nomad_tpu.tensors.schema import (
    ClusterTensors,
    IncrementalClusterCache,
)

__all__ = ["DeviceClusterState", "default_device_state"]

#: dirty-row scatter batches are bucketed so the jit cache holds a
#: handful of (n_pad, rows-bucket, dtype) programs, not one per count
_MIN_ROW_BUCKET = 8


def _row_bucket(r: int) -> int:
    b = _MIN_ROW_BUCKET
    while b < r:
        b *= 2
    return b


@jax.jit
def _scatter_rows(plane, rows, vals):
    """``plane.at[rows].set(vals)``; padding rows are out of bounds on
    purpose — scatter drops OOB updates, so a bucketed row batch never
    touches rows it wasn't given."""
    return plane.at[rows].set(vals)


class _Generation:
    """One resident (uid, structure_version) generation."""

    __slots__ = ("key", "cluster", "version", "planes", "host_ids")

    def __init__(self, key, cluster, version, planes):
        self.key = key
        self.cluster = cluster          # host build (identity anchor)
        self.version = version          # usage version of the planes
        self.planes: Dict[str, object] = planes   # field -> device array
        self.host_ids: Tuple[int, ...] = ()


class DeviceClusterState:
    """LRU of device-resident wave-shared plane generations."""

    def __init__(self, max_generations: int = 4,
                 max_frozen: int = 256) -> None:
        self._lock = threading.Lock()
        self._gens: "OrderedDict[tuple, _Generation]" = OrderedDict()
        #: uid -> newest resident structure_version (the fork base)
        self._latest: Dict[str, int] = {}
        #: id(host array) -> (host array, device array). Strong host
        #: refs pin ids against reuse; entries leave with their
        #: generation (or the frozen LRU).
        self._registry: Dict[int, tuple] = {}
        self._frozen: "OrderedDict[int, tuple]" = OrderedDict()
        #: id(arr) -> Event for frozen uploads in flight: the upload
        #: itself runs OUTSIDE self._lock (graftcheck R2 — a first-
        #: sight frozen upload under the registry lock stalled every
        #: concurrent snapshot-time advance behind one h2d transfer)
        self._frozen_inflight: Dict[int, threading.Event] = {}
        self.max_generations = max_generations
        self.max_frozen = max_frozen
        self.reset_stats()

    # --- stats ----------------------------------------------------------

    def reset_stats(self) -> None:
        with self._lock:
            self.hits = 0
            self.full_uploads = 0        # generations built by full upload
            self.delta_advances = 0      # usage advances by row scatter
            self.fork_deltas = 0         # structure forks by row scatter
            self.usage_full_uploads = 0  # unprovable row log fallbacks
            self.rows_uploaded = 0
            self.bytes_uploaded = 0      # actual h2d bytes (delta + full)
            self.bytes_full_equiv = 0    # what full re-uploads would cost

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "hits": self.hits,
                "full_uploads": self.full_uploads,
                "delta_advances": self.delta_advances,
                "fork_deltas": self.fork_deltas,
                "usage_full_uploads": self.usage_full_uploads,
                "rows_uploaded": self.rows_uploaded,
                "bytes_uploaded": self.bytes_uploaded,
                "bytes_full_equiv": self.bytes_full_equiv,
                "dirty_row_upload_ratio": (
                    round(self.bytes_uploaded / self.bytes_full_equiv, 4)
                    if self.bytes_full_equiv else 0.0),
                "resident_generations": len(self._gens),
            }

    # --- registry -------------------------------------------------------

    def lookup(self, arr, frozen_ok: bool = True) -> Optional[object]:
        """Committed device twin of ``arr``, or None. With
        ``frozen_ok``, frozen host arrays (read-only singletons) are
        made resident on first sight; mutable arrays are served only
        when a generation registered them.

        Callers pass ``frozen_ok=False`` for the snapshot-plane group:
        gathered utilization planes are ALSO read-only, and a stale
        snapshot's planes (deregistered by a newer advance) must miss
        — not get full-uploaded on the firing thread and pinned into
        the frozen LRU as if they were process-lifetime singletons."""
        if not isinstance(arr, np.ndarray):
            return None
        ent = self._registry.get(id(arr))
        if ent is not None and ent[0] is arr:
            return ent[1]
        if frozen_ok and not arr.flags.writeable:
            return self._frozen_resident(arr)
        return None

    def _frozen_resident(self, arr: np.ndarray):
        # claim under the lock, upload outside it: the device_put of a
        # first-sight frozen singleton must not hold the registry lock
        # (it is shared with the dirty-row advance path every eval
        # thread runs at snapshot time — graftcheck R2). Concurrent
        # callers for the same array wait on the claim's event; a
        # caller who finds the upload failed just misses (residency is
        # an optimization, the host array still works).
        key = id(arr)
        while True:
            with self._lock:
                ent = self._frozen.get(key)
                if ent is not None and ent[0] is arr:
                    self._frozen.move_to_end(key)
                    return ent[1]
                ev = self._frozen_inflight.get(key)
                if ev is None:
                    ev = self._frozen_inflight[key] = threading.Event()
                    break       # this thread owns the upload
            if not ev.wait(timeout=30.0):
                return None     # uploader wedged: serve the host array
        dev = None
        try:
            dev = self._upload({"_frozen": arr})["_frozen"]
            with self._lock:
                self._frozen[key] = (arr, dev)
                self._registry[key] = (arr, dev)
                while len(self._frozen) > self.max_frozen:
                    old_id, (old_arr, _) = self._frozen.popitem(last=False)
                    ent = self._registry.get(old_id)
                    if ent is not None and ent[0] is old_arr:
                        self._registry.pop(old_id, None)
        finally:
            with self._lock:
                self._frozen_inflight.pop(key, None)
            ev.set()
        return dev

    def _register(self, gen: _Generation,
                  host_planes: Dict[str, np.ndarray]) -> None:
        for hid in gen.host_ids:
            self._registry.pop(hid, None)
        ids = []
        for f, host in host_planes.items():
            self._registry[id(host)] = (host, gen.planes[f])
            ids.append(id(host))
        gen.host_ids = tuple(ids)

    def _evict(self, gen: _Generation) -> None:
        for hid in gen.host_ids:
            self._registry.pop(hid, None)
        uid, sv = gen.key
        if self._latest.get(uid) == sv:
            self._latest.pop(uid, None)

    # --- uploads --------------------------------------------------------

    def _upload(self, host_planes: Dict[str, np.ndarray]) -> Dict:
        """Full upload of ``host_planes``; spans + byte-counts the real
        h2d it performs (the kernel profiler's transfer accounting)."""
        from nomad_tpu.telemetry.kernel_profile import profiler
        from nomad_tpu.telemetry.trace import tracer

        n_bytes = sum(a.nbytes for a in host_planes.values())
        # own span name: this upload runs on an EVAL thread at
        # snapshot time, overlapping the in-flight wave — the trace
        # decomposition must not sum it into the wave-critical-path
        # kernel.h2d wall stage
        with tracer.span("state.h2d"):
            dev = {f: jax.device_put(a) for f, a in host_planes.items()}
            if tracer.enabled:
                jax.block_until_ready(list(dev.values()))
        profiler.add_bytes("h2d", n_bytes)
        self.bytes_uploaded += n_bytes
        return dev

    def _scatter(self, planes: Dict, host_planes: Dict[str, np.ndarray],
                 rows) -> Dict:
        """Advance ``planes`` to match ``host_planes`` given that only
        ``rows`` differ: upload rows + per-plane values, scatter on
        device. Row indices are bucketed with out-of-bounds padding
        (dropped by the scatter)."""
        from nomad_tpu.telemetry.kernel_profile import profiler
        from nomad_tpu.telemetry.trace import tracer

        rows = np.asarray(sorted(rows), np.int32)
        any_plane = next(iter(host_planes.values()))
        n_pad = any_plane.shape[0]
        rb = _row_bucket(len(rows))
        rows_p = np.full(rb, n_pad, np.int32)
        rows_p[:len(rows)] = rows
        n_bytes = rows_p.nbytes
        with tracer.span("state.h2d"):
            rows_dev = jax.device_put(rows_p)
            out = dict(planes)
            for f, host in host_planes.items():
                vals = np.zeros(rb, host.dtype)
                vals[:len(rows)] = host[rows]
                n_bytes += vals.nbytes
                out[f] = _scatter_rows(planes[f], rows_dev,
                                       jax.device_put(vals))
            if tracer.enabled:
                jax.block_until_ready(list(out.values()))
        profiler.add_bytes("h2d", n_bytes)
        self.bytes_uploaded += n_bytes
        self.rows_uploaded += int(len(rows)) * len(host_planes)
        return out

    def warm_scatter(self, n_pad: int) -> int:
        """AOT-compile the dirty-row scatter for every row bucket and
        plane dtype of a node size (ops/warmup.py calls this with the
        manifest's node shapes). The scatter is raw ``jax.jit`` — its
        compiles never show in the profiler's miss accounting, but a
        steady burst whose dirty-row count crosses into a fresh bucket
        used to pay a cold compile INSIDE an eval's snapshot phase.
        Returns the number of (bucket, dtype) programs touched."""
        done = 0
        b = _MIN_ROW_BUCKET
        while b <= max(n_pad, _MIN_ROW_BUCKET):
            rows = jax.device_put(np.full(b, n_pad, np.int32))
            for dtype in (np.float32, np.int32):
                plane = jax.device_put(np.zeros(n_pad, dtype))
                vals = jax.device_put(np.zeros(b, dtype))
                jax.block_until_ready(_scatter_rows(plane, rows, vals))
                done += 1
            if b >= n_pad:
                break
            b *= 2
        return done

    # --- the ensure entry point ----------------------------------------

    def ensure(self, cluster: ClusterTensors, usage) -> Optional[_Generation]:
        """Make the wave-shared planes of (cluster, usage) resident and
        registered; called once per eval at snapshot time (cheap
        version-compare on the hot path), so the next wave's h2d —
        now just the dirty rows — runs on an eval thread while the
        previous wave executes."""
        if usage is None or not getattr(usage, "uid", ""):
            return None
        key = (usage.uid, usage.structure_version)
        # lock-free fast path: dict reads are atomic in CPython and a
        # generation's (cluster, version) pair only moves forward, so
        # a racing advance at worst sends us to the locked path. The
        # hits += 1 is a tolerated read-modify-write race (a stats
        # counter, like worker.processed).
        gen = self._gens.get(key)
        if gen is not None and gen.version == usage.version \
                and gen.cluster is cluster:
            self.hits += 1
            return gen
        if gen is not None and gen.cluster is cluster \
                and gen.version > usage.version:
            # an eval still scheduling against an OLDER usage snapshot
            # (pipelined batches, a neighbor's refreshed retry): its
            # wave simply ships host planes. Demoting the generation
            # here would full-upload per interleave and ping-pong the
            # registry between versions.
            return None
        # BLOCKING acquire on purpose: a batch's eval threads all
        # reach here with the same snapshot; the first advances, the
        # rest wait and then hit the double-checked fast path. Waiting
        # is cheaper than it looks — these threads would otherwise
        # park at the wave rendezvous, and a follower that skipped
        # ahead without residency would make its wave ship FULL host
        # planes (measured: h2d share exploded 17x with a try-lock
        # here on the CPU backend).
        with self._lock:
            gen = self._gens.get(key)
            if gen is not None and gen.version == usage.version \
                    and gen.cluster is cluster:
                self._gens.move_to_end(key)
                self.hits += 1
                return gen
            if gen is not None and gen.cluster is cluster \
                    and gen.version > usage.version:
                return None
            host = cluster.wave_shared_planes(usage)
            full_bytes = sum(a.nbytes for a in host.values())
            self.bytes_full_equiv += full_bytes
            if gen is not None and gen.cluster is cluster \
                    and gen.version < usage.version:
                self._advance_usage(gen, host, usage)
            else:
                if gen is not None:
                    # the key is being re-built from a different host
                    # cluster object: retire the old registrations
                    self._evict(gen)
                gen = self._fork_or_build(key, cluster, host, usage)
            self._register(gen, host)
            gen.version = usage.version
            self._gens[key] = gen
            self._gens.move_to_end(key)
            if usage.structure_version >= self._latest.get(usage.uid, -1):
                self._latest[usage.uid] = usage.structure_version
            while len(self._gens) > self.max_generations:
                _, old = self._gens.popitem(last=False)
                self._evict(old)
            return gen

    # --- advance paths --------------------------------------------------

    @staticmethod
    def _usage_rows_changed(usage, since_version: int):
        """Node ids whose utilization rows changed after
        ``since_version``, or None when the row log cannot prove
        completeness (trimmed past the gap, or poisoned by rebuild)."""
        if since_version < getattr(usage, "row_events_floor", 0):
            return None
        return {nid for v, nid in getattr(usage, "row_events", ())
                if v > since_version}

    def _advance_usage(self, gen: _Generation,
                       host: Dict[str, np.ndarray], usage) -> None:
        """Same (uid, structure_version), newer usage version: only
        utilization rows can have moved."""
        changed = self._usage_rows_changed(usage, gen.version)
        usage_host = {f: host[f]
                      for f in ClusterTensors.WAVE_USAGE_FIELDS}
        if changed is None:
            self.usage_full_uploads += 1
            gen.planes.update(self._upload(usage_host))
            return
        rows = {gen.cluster.index[nid] for nid in changed
                if nid in gen.cluster.index}
        if rows:
            gen.planes = self._scatter(gen.planes, usage_host, rows)
        self.delta_advances += 1

    def _fork_or_build(self, key, cluster: ClusterTensors,
                       host: Dict[str, np.ndarray], usage) -> _Generation:
        """A structure_version this state has no generation for: fork
        from the newest resident generation of the same store by
        dirty-row scatter when the node-change log proves the dirty
        set AND surviving rows kept their positions; otherwise a full
        upload."""
        uid, sv = key
        base_sv = self._latest.get(uid)
        base = (self._gens.get((uid, base_sv))
                if base_sv is not None else None)
        if base is not None and base_sv < sv \
                and base.cluster.n_pad == cluster.n_pad:
            forked = self._try_fork(base, cluster, host, usage)
            if forked is not None:
                self.fork_deltas += 1
                return _Generation(key, cluster, usage.version, forked)
        self.full_uploads += 1
        return _Generation(key, cluster, usage.version,
                           self._upload(host))

    def _try_fork(self, base: _Generation, cluster: ClusterTensors,
                  host: Dict[str, np.ndarray], usage) -> Optional[Dict]:
        changed = IncrementalClusterCache._changed_since(
            getattr(usage, "node_events", ()), base.key[1])
        if changed is None:
            return None
        n = cluster.n_real
        stale = []
        for j, nid in enumerate(cluster.node_ids):
            if nid in changed or nid not in base.cluster.index:
                stale.append(j)
            elif base.cluster.index[nid] != j:
                # compaction permuted surviving rows: the device-side
                # scatter cannot express a gather; full upload
                return None
        if len(stale) > max(n // 2, 8):
            return None
        # rows the new build leaves as padding but the base had real
        # nodes in: their new host values are zeros by construction
        rows = set(stale) | set(range(n, base.cluster.n_real))
        dirty_usage = self._usage_rows_changed(usage, base.version)
        if dirty_usage is None:
            static_host = {f: host[f]
                           for f in ClusterTensors.WAVE_STATIC_FIELDS}
            usage_host = {f: host[f]
                          for f in ClusterTensors.WAVE_USAGE_FIELDS}
            planes = dict(base.planes)
            if rows:
                planes = self._scatter(planes, static_host, rows)
            self.usage_full_uploads += 1
            planes.update(self._upload(usage_host))
            return planes
        rows_usage = rows | {cluster.index[nid] for nid in dirty_usage
                             if nid in cluster.index}
        planes = dict(base.planes)
        static_host = {f: host[f]
                       for f in ClusterTensors.WAVE_STATIC_FIELDS}
        usage_host = {f: host[f]
                      for f in ClusterTensors.WAVE_USAGE_FIELDS}
        if rows:
            planes = self._scatter(planes, static_host, rows)
        if rows_usage:
            planes = self._scatter(planes, usage_host, rows_usage)
        return planes


#: process-wide resident state (the batching worker's snapshot path
#: and the wave launcher both consult it)
default_device_state = DeviceClusterState()
