"""Device-resident cluster state: kill the per-wave h2d tax.

PR 2's steady-state TRACE_DECOMP made h2d the dominant cost (30.4% of
wall, 4.48 ms/eval): every coalesced wave re-uploaded the full
node x resource shared planes even though the host side already knew
exactly which rows changed (the incremental ClusterTensors cache and
the usage index's change logs). This module is the device half of that
design: the wave-shared planes — the cluster-static capacity planes
plus the snapshot's gathered utilization (``ClusterTensors.
wave_shared_planes``) — live ON the accelerator as committed arrays,
keyed by ``(uid, structure_version)`` generations, and advance between
waves by uploading only the dirty rows and applying them with a jit'd
scatter (``plane.at[rows].set(vals)``).

Advancement is **functional**: a scatter produces new device arrays
while the previous generation's buffers stay untouched, so a wave
still executing against version N never races version N+1's upload —
the double-buffering that lets the (tiny) h2d of the next wave overlap
the current wave's execute. Resident generations are LRU-bounded;
every miss (unprovable log, permuted rows, pad-bucket change, evicted
base) falls back to a full plane upload, which is bit-identical by
construction and property-tested against a fresh
``ClusterTensors.build`` + upload (tests/test_device_state.py, the
device mirror of tests/test_cluster_delta.py).

Mesh sharding (ISSUE 14): when a device mesh is configured
(``configure_mesh``; the server adopts its wave mesh here), resident
generations are placed with a ``NamedSharding`` that splits the node
axis over the mesh's ``nodes`` axis — each device holds its shard of
every wave-shared plane, and the dirty-row scatter advances THOSE
sharded buffers in place-of-layout (a per-mesh jit with sharded
in/out shardings, so wave-to-wave advancement never gathers a plane
to one device and never reshards). Frozen singletons are placed per
KernelIn-field partition spec (parallel/sharded.shared_field_spec) and
keyed by (array identity, spec), so the same neutral plane can be
resident both unsharded and sharded. Lookups carry the caller's mesh:
a single-device launch never receives a sharded buffer (it would
reshard inside the jit), and vice versa — mismatches just miss and
ship host planes, which is always correct.

Dirty-row provenance:

- utilization planes: ``UsagePlanes.row_events`` (state/usage.py), the
  per-version log of nodes whose rows an alloc transition moved,
  complete above ``row_events_floor``;
- cluster-static planes across a ``structure_version`` fork:
  ``UsagePlanes.node_events``, the same log the host-side
  ``IncrementalClusterCache`` replays — usable on device only when the
  surviving rows kept their positions (additions/updates); a
  compaction that permutes rows falls back to a full upload.

The registry maps *host array identity* -> committed device array, the
same identity contract the wave coalescer's sharing layout is built
on: ``launch_wave`` (and ``default_kernel_launch``) swap a shared host
leaf for its resident device twin, making ``jax.device_put`` a no-op
for every plane that didn't change. Frozen neutral singletons
(ops/kernel.neutral_planes etc.) ride the same registry via a bounded
resident cache — they upload once per process, ever.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from nomad_tpu.tensors.schema import (
    ClusterTensors,
    IncrementalClusterCache,
)

__all__ = ["DeviceClusterState", "default_device_state"]

#: dirty-row scatter batches are bucketed so the jit cache holds a
#: handful of (n_pad, rows-bucket, dtype) programs, not one per count
_MIN_ROW_BUCKET = 8


def _row_bucket(r: int) -> int:
    b = _MIN_ROW_BUCKET
    while b < r:
        b *= 2
    return b


def _scatter_rows_impl(plane, rows, vals):
    """``plane.at[rows].set(vals)``; padding rows are out of bounds on
    purpose — scatter drops OOB updates, so a bucketed row batch never
    touches rows it wasn't given."""
    return plane.at[rows].set(vals)


_scatter_rows = jax.jit(_scatter_rows_impl)

#: per-mesh sharded scatter jits (weak: a freed mesh drops its entry).
#: The plane stays split over the nodes axis IN and OUT — advancement
#: of a sharded generation never gathers the plane to one device; row
#: indices address the GLOBAL node axis and ship replicated, each
#: shard applies the updates that land in its slice.
import weakref

_sharded_scatter_cache: "weakref.WeakKeyDictionary" = \
    weakref.WeakKeyDictionary()


def _sharded_scatter(mesh):
    fn = _sharded_scatter_cache.get(mesh)
    if fn is None:
        from nomad_tpu.parallel.sharded import node_axis_sharding
        from jax.sharding import NamedSharding, PartitionSpec

        plane_s = node_axis_sharding(mesh)
        repl = NamedSharding(mesh, PartitionSpec())
        fn = jax.jit(_scatter_rows_impl,
                     in_shardings=(plane_s, repl, repl),
                     out_shardings=plane_s)
        _sharded_scatter_cache[mesh] = fn
    return fn


def _mesh_match(a, b) -> bool:
    """Two mesh handles name the same placement (None = single device;
    jax Mesh compares by devices + axis names)."""
    if a is None or b is None:
        return a is None and b is None
    return a is b or a == b


class _Generation:
    """One resident (uid, structure_version) generation."""

    __slots__ = ("key", "cluster", "version", "planes", "host_ids",
                 "mesh")

    def __init__(self, key, cluster, version, planes, mesh=None):
        self.key = key
        self.cluster = cluster          # host build (identity anchor)
        self.version = version          # usage version of the planes
        self.planes: Dict[str, object] = planes   # field -> device array
        self.host_ids: Tuple[int, ...] = ()
        self.mesh = mesh                # placement (None = one device)


class DeviceClusterState:
    """LRU of device-resident wave-shared plane generations."""

    def __init__(self, max_generations: int = 4,
                 max_frozen: int = 256, mesh=None) -> None:
        self._lock = threading.Lock()
        self._gens: "OrderedDict[tuple, _Generation]" = OrderedDict()
        #: uid -> newest resident structure_version (the fork base)
        self._latest: Dict[str, int] = {}
        #: id(host array) -> (host array, device array, mesh). Strong
        #: host refs pin ids against reuse; entries leave with their
        #: generation. Generations only — frozen singletons live in
        #: the spec-keyed LRU below.
        self._registry: Dict[int, tuple] = {}
        #: (id(host array), spec key) -> (host array, device array).
        #: The spec key is None for single-device placement or the
        #: field's PartitionSpec tuple under the configured mesh — the
        #: same neutral singleton can be resident under both.
        self._frozen: "OrderedDict[tuple, tuple]" = OrderedDict()
        #: frozen-cache key -> Event for uploads in flight: the upload
        #: itself runs OUTSIDE self._lock (graftcheck R2 — a first-
        #: sight frozen upload under the registry lock stalled every
        #: concurrent snapshot-time advance behind one h2d transfer)
        self._frozen_inflight: Dict[tuple, threading.Event] = {}
        self.max_generations = max_generations
        self.max_frozen = max_frozen
        #: device mesh future generations shard their node axis over
        #: (None = single-device placement, the default)
        self._mesh = mesh
        self.reset_stats()

    # --- mesh -----------------------------------------------------------

    @property
    def mesh(self):
        return self._mesh

    def configure_mesh(self, mesh) -> None:
        """Shard future resident generations' node axis over ``mesh``
        (None restores single-device placement). A CHANGE of placement
        evicts everything resident: a plane placed for the old mesh
        can only mis-serve the new dispatch path. The server adopts
        its wave mesh here when it comes up; tests and the bench mesh
        cell configure/restore around their bursts."""
        with self._lock:
            if _mesh_match(mesh, self._mesh):
                return
            self._mesh = mesh
            for gen in list(self._gens.values()):
                self._evict(gen)
            self._gens.clear()
            self._latest.clear()
            self._registry.clear()
            self._frozen.clear()

    def _node_sharding(self, n_pad: int):
        """NamedSharding for [n_pad] node planes under the configured
        mesh, or None for single-device placement (no mesh, or a node
        axis the mesh's device count does not divide — the launcher
        makes the same divisibility call and falls back unsharded)."""
        mesh = self._mesh
        if mesh is None or mesh.size < 2 or n_pad % mesh.size != 0:
            return None
        from nomad_tpu.parallel.sharded import node_axis_sharding

        return node_axis_sharding(mesh)

    # --- stats ----------------------------------------------------------

    def reset_stats(self) -> None:
        with self._lock:
            self.hits = 0
            self.full_uploads = 0        # generations built by full upload
            self.delta_advances = 0      # usage advances by row scatter
            self.fork_deltas = 0         # structure forks by row scatter
            self.usage_full_uploads = 0  # unprovable row log fallbacks
            self.rows_uploaded = 0
            self.bytes_uploaded = 0      # actual h2d bytes (delta + full)
            self.bytes_full_equiv = 0    # what full re-uploads would cost

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "hits": self.hits,
                "full_uploads": self.full_uploads,
                "delta_advances": self.delta_advances,
                "fork_deltas": self.fork_deltas,
                "usage_full_uploads": self.usage_full_uploads,
                "rows_uploaded": self.rows_uploaded,
                "bytes_uploaded": self.bytes_uploaded,
                "bytes_full_equiv": self.bytes_full_equiv,
                "dirty_row_upload_ratio": (
                    round(self.bytes_uploaded / self.bytes_full_equiv, 4)
                    if self.bytes_full_equiv else 0.0),
                "resident_generations": len(self._gens),
                "mesh_devices": (int(self._mesh.size)
                                 if self._mesh is not None else 0),
            }

    # --- registry -------------------------------------------------------

    def lookup(self, arr, frozen_ok: bool = True, spec=None,
               mesh=None) -> Optional[object]:
        """Committed device twin of ``arr`` placed for ``mesh``, or
        None. With ``frozen_ok``, frozen host arrays (read-only
        singletons) are made resident on first sight; mutable arrays
        are served only when a generation registered them.

        ``mesh``/``spec`` are the caller's dispatch placement: a
        single-device launch (mesh None) never receives a sharded
        buffer, a sharded wave never receives a single-device one —
        either would reshard inside the jit and fork its cache.
        ``spec`` (a PartitionSpec, sharded callers only) is the
        KernelIn field's partition for frozen-singleton placement.

        Callers pass ``frozen_ok=False`` for the snapshot-plane group:
        gathered utilization planes are ALSO read-only, and a stale
        snapshot's planes (deregistered by a newer advance) must miss
        — not get full-uploaded on the firing thread and pinned into
        the frozen LRU as if they were process-lifetime singletons."""
        if not isinstance(arr, np.ndarray):
            return None
        ent = self._registry.get(id(arr))
        if ent is not None and ent[0] is arr \
                and _mesh_match(ent[2], mesh):
            return ent[1]
        if frozen_ok and not arr.flags.writeable:
            # lock-free fast path (like the registry read above): a
            # resident frozen singleton is served without touching the
            # lock the advance path holds — only a MISS pays the
            # claim-and-upload dance. Sharded entries are placed for
            # THIS state's mesh, so a caller on a foreign mesh must
            # fall through (and be rejected by the slow path) — the
            # spec key alone would collide across meshes.
            spec_key = None if (spec is None or mesh is None) \
                else tuple(spec)
            if spec_key is None or _mesh_match(mesh, self._mesh):
                ent = self._frozen.get((id(arr), spec_key))
                if ent is not None and ent[0] is arr:
                    return ent[1]
            return self._frozen_resident(arr, spec, mesh)
        return None

    def _frozen_resident(self, arr: np.ndarray, spec=None, mesh=None):
        # claim under the lock, upload outside it: the device_put of a
        # first-sight frozen singleton must not hold the registry lock
        # (it is shared with the dirty-row advance path every eval
        # thread runs at snapshot time — graftcheck R2). Concurrent
        # callers for the same array wait on the claim's event; a
        # caller who finds the upload failed just misses (residency is
        # an optimization, the host array still works).
        sharding = None
        if mesh is not None:
            # sharded placement only under THIS state's configured
            # mesh: uploading under a foreign mesh would pin arrays no
            # dispatch path of this state ever serves
            if not _mesh_match(mesh, self._mesh) or spec is None:
                return None
            from jax.sharding import NamedSharding

            sharding = NamedSharding(self._mesh, spec)
        spec_key = None if spec is None or mesh is None \
            else tuple(spec)
        key = (id(arr), spec_key)
        while True:
            with self._lock:
                ent = self._frozen.get(key)
                if ent is not None and ent[0] is arr:
                    self._frozen.move_to_end(key)
                    return ent[1]
                ev = self._frozen_inflight.get(key)
                if ev is None:
                    ev = self._frozen_inflight[key] = threading.Event()
                    break       # this thread owns the upload
            if not ev.wait(timeout=30.0):
                return None     # uploader wedged: serve the host array
        dev = None
        try:
            dev = self._upload({"_frozen": arr},
                               sharding=sharding)["_frozen"]
            with self._lock:
                # re-validate placement before inserting: the upload
                # ran off-lock, and a racing configure_mesh may have
                # cleared the cache for a NEW mesh — a sharded buffer
                # placed for the old one must not be re-inserted under
                # a spec key the new mesh's lookups would hit (the key
                # encodes the spec, not the mesh). Unsharded entries
                # stay valid under any mesh.
                if spec_key is None or _mesh_match(mesh, self._mesh):
                    self._frozen[key] = (arr, dev)
                    while len(self._frozen) > self.max_frozen:
                        self._frozen.popitem(last=False)
                else:
                    dev = None      # stale placement: callers miss
        finally:
            with self._lock:
                self._frozen_inflight.pop(key, None)
            ev.set()
        return dev

    def _register(self, gen: _Generation,
                  host_planes: Dict[str, np.ndarray]) -> None:
        for hid in gen.host_ids:
            self._registry.pop(hid, None)
        ids = []
        for f, host in host_planes.items():
            self._registry[id(host)] = (host, gen.planes[f], gen.mesh)
            ids.append(id(host))
        gen.host_ids = tuple(ids)

    def _evict(self, gen: _Generation) -> None:
        for hid in gen.host_ids:
            self._registry.pop(hid, None)
        uid, sv = gen.key
        if self._latest.get(uid) == sv:
            self._latest.pop(uid, None)

    # --- uploads --------------------------------------------------------

    def _upload(self, host_planes: Dict[str, np.ndarray],
                sharding=None) -> Dict:
        """Full upload of ``host_planes`` (placed with ``sharding``
        when given — the mesh path's node-axis split); spans +
        byte-counts the real h2d it performs (the kernel profiler's
        transfer accounting)."""
        from nomad_tpu.telemetry.kernel_profile import profiler
        from nomad_tpu.telemetry.trace import tracer

        n_bytes = sum(a.nbytes for a in host_planes.values())
        # own span name: this upload runs on an EVAL thread at
        # snapshot time, overlapping the in-flight wave — the trace
        # decomposition must not sum it into the wave-critical-path
        # kernel.h2d wall stage
        with tracer.span("state.h2d"):
            if sharding is None:
                dev = {f: jax.device_put(a)
                       for f, a in host_planes.items()}
            else:
                dev = {f: jax.device_put(a, sharding)
                       for f, a in host_planes.items()}
            if tracer.enabled:
                jax.block_until_ready(list(dev.values()))
        profiler.add_bytes("h2d", n_bytes)
        self.bytes_uploaded += n_bytes
        return dev

    def _scatter(self, planes: Dict, host_planes: Dict[str, np.ndarray],
                 rows, mesh=None) -> Dict:
        """Advance ``planes`` to match ``host_planes`` given that only
        ``rows`` differ: upload rows + per-plane values, scatter on
        device. Row indices are bucketed with out-of-bounds padding
        (dropped by the scatter). Sharded generations advance through
        the per-mesh sharded scatter: the plane stays split over the
        nodes axis end to end, only the dirty rows and their GLOBAL
        indices ship (replicated — they are a few KB)."""
        from nomad_tpu.telemetry.kernel_profile import profiler
        from nomad_tpu.telemetry.trace import tracer

        scatter = _scatter_rows if mesh is None else _sharded_scatter(mesh)
        repl = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(mesh, PartitionSpec())
        rows = np.asarray(sorted(rows), np.int32)
        any_plane = next(iter(host_planes.values()))
        n_pad = any_plane.shape[0]
        rb = _row_bucket(len(rows))
        rows_p = np.full(rb, n_pad, np.int32)
        rows_p[:len(rows)] = rows
        n_bytes = rows_p.nbytes
        with tracer.span("state.h2d"):
            rows_dev = jax.device_put(rows_p) if repl is None \
                else jax.device_put(rows_p, repl)
            out = dict(planes)
            for f, host in host_planes.items():
                vals = np.zeros(rb, host.dtype)
                vals[:len(rows)] = host[rows]
                n_bytes += vals.nbytes
                vals_dev = jax.device_put(vals) if repl is None \
                    else jax.device_put(vals, repl)
                out[f] = scatter(planes[f], rows_dev, vals_dev)
            if tracer.enabled:
                jax.block_until_ready(list(out.values()))
        profiler.add_bytes("h2d", n_bytes)
        self.bytes_uploaded += n_bytes
        self.rows_uploaded += int(len(rows)) * len(host_planes)
        return out

    def warm_scatter(self, n_pad: int) -> int:
        """AOT-compile the dirty-row scatter for every row bucket and
        plane dtype of a node size (ops/warmup.py calls this with the
        manifest's node shapes), including the sharded variant when a
        mesh is configured. The scatter is raw ``jax.jit`` — its
        compiles never show in the profiler's miss accounting, but a
        steady burst whose dirty-row count crosses into a fresh bucket
        used to pay a cold compile INSIDE an eval's snapshot phase.
        Returns the number of (bucket, dtype) programs touched."""
        done = 0
        sharding = self._node_sharding(n_pad)
        variants = [(_scatter_rows, None)]
        if sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            variants.append((_sharded_scatter(self._mesh),
                             NamedSharding(self._mesh, PartitionSpec())))
        b = _MIN_ROW_BUCKET
        while b <= max(n_pad, _MIN_ROW_BUCKET):
            for scatter, repl in variants:
                rows_h = np.full(b, n_pad, np.int32)
                rows = jax.device_put(rows_h) if repl is None \
                    else jax.device_put(rows_h, repl)
                for dtype in (np.float32, np.int32):
                    if repl is None:
                        plane = jax.device_put(np.zeros(n_pad, dtype))
                        vals = jax.device_put(np.zeros(b, dtype))
                    else:
                        plane = jax.device_put(np.zeros(n_pad, dtype),
                                               sharding)
                        vals = jax.device_put(np.zeros(b, dtype), repl)
                    jax.block_until_ready(scatter(plane, rows, vals))
                    done += 1
            if b >= n_pad:
                break
            b *= 2
        return done

    # --- the ensure entry point ----------------------------------------

    def ensure(self, cluster: ClusterTensors, usage) -> Optional[_Generation]:
        """Make the wave-shared planes of (cluster, usage) resident and
        registered; called once per eval at snapshot time (cheap
        version-compare on the hot path), so the next wave's h2d —
        now just the dirty rows — runs on an eval thread while the
        previous wave executes."""
        if usage is None or not getattr(usage, "uid", ""):
            return None
        key = (usage.uid, usage.structure_version)
        # lock-free fast path: dict reads are atomic in CPython and a
        # generation's (cluster, version) pair only moves forward, so
        # a racing advance at worst sends us to the locked path. The
        # hits += 1 is a tolerated read-modify-write race (a stats
        # counter, like worker.processed).
        gen = self._gens.get(key)
        if gen is not None and gen.version == usage.version \
                and gen.cluster is cluster:
            self.hits += 1
            return gen
        if gen is not None and gen.cluster is cluster \
                and gen.version > usage.version:
            # an eval still scheduling against an OLDER usage snapshot
            # (pipelined batches, a neighbor's refreshed retry): its
            # wave simply ships host planes. Demoting the generation
            # here would full-upload per interleave and ping-pong the
            # registry between versions.
            return None
        # BLOCKING acquire on purpose: a batch's eval threads all
        # reach here with the same snapshot; the first advances, the
        # rest wait and then hit the double-checked fast path. Waiting
        # is cheaper than it looks — these threads would otherwise
        # park at the wave rendezvous, and a follower that skipped
        # ahead without residency would make its wave ship FULL host
        # planes (measured: h2d share exploded 17x with a try-lock
        # here on the CPU backend).
        with self._lock:
            gen = self._gens.get(key)
            if gen is not None and gen.version == usage.version \
                    and gen.cluster is cluster:
                self._gens.move_to_end(key)
                self.hits += 1
                return gen
            if gen is not None and gen.cluster is cluster \
                    and gen.version > usage.version:
                return None
            host = cluster.wave_shared_planes(usage)
            full_bytes = sum(a.nbytes for a in host.values())
            self.bytes_full_equiv += full_bytes
            if gen is not None and gen.cluster is cluster \
                    and gen.version < usage.version:
                self._advance_usage(gen, host, usage)
            else:
                if gen is not None:
                    # the key is being re-built from a different host
                    # cluster object: retire the old registrations
                    self._evict(gen)
                gen = self._fork_or_build(key, cluster, host, usage)
            self._register(gen, host)
            gen.version = usage.version
            self._gens[key] = gen
            self._gens.move_to_end(key)
            if usage.structure_version >= self._latest.get(usage.uid, -1):
                self._latest[usage.uid] = usage.structure_version
            while len(self._gens) > self.max_generations:
                _, old = self._gens.popitem(last=False)
                self._evict(old)
            return gen

    # --- advance paths --------------------------------------------------

    @staticmethod
    def _usage_rows_changed(usage, since_version: int):
        """Node ids whose utilization rows changed after
        ``since_version``, or None when the row log cannot prove
        completeness (trimmed past the gap, or poisoned by rebuild)."""
        if since_version < getattr(usage, "row_events_floor", 0):
            return None
        return {nid for v, nid in getattr(usage, "row_events", ())
                if v > since_version}

    def _gen_sharding(self, gen: _Generation):
        if gen.mesh is None:
            return None
        from nomad_tpu.parallel.sharded import node_axis_sharding

        return node_axis_sharding(gen.mesh)

    def _advance_usage(self, gen: _Generation,
                       host: Dict[str, np.ndarray], usage) -> None:
        """Same (uid, structure_version), newer usage version: only
        utilization rows can have moved. A sharded generation advances
        sharded — the scatter and the unprovable-log full-upload
        fallback both keep the generation's placement."""
        changed = self._usage_rows_changed(usage, gen.version)
        usage_host = {f: host[f]
                      for f in ClusterTensors.WAVE_USAGE_FIELDS}
        if changed is None:
            self.usage_full_uploads += 1
            gen.planes.update(self._upload(
                usage_host, sharding=self._gen_sharding(gen)))
            return
        rows = {gen.cluster.index[nid] for nid in changed
                if nid in gen.cluster.index}
        if rows:
            gen.planes = self._scatter(gen.planes, usage_host, rows,
                                       mesh=gen.mesh)
        self.delta_advances += 1

    def _fork_or_build(self, key, cluster: ClusterTensors,
                       host: Dict[str, np.ndarray], usage) -> _Generation:
        """A structure_version this state has no generation for: fork
        from the newest resident generation of the same store by
        dirty-row scatter when the node-change log proves the dirty
        set AND surviving rows kept their positions; otherwise a full
        upload. Placement follows the configured mesh (the fork path
        requires the base's placement to match — the same n_pad under
        the same mesh always does)."""
        sharding = self._node_sharding(cluster.n_pad)
        gen_mesh = self._mesh if sharding is not None else None
        uid, sv = key
        base_sv = self._latest.get(uid)
        base = (self._gens.get((uid, base_sv))
                if base_sv is not None else None)
        if base is not None and base_sv < sv \
                and base.cluster.n_pad == cluster.n_pad \
                and _mesh_match(base.mesh, gen_mesh):
            forked = self._try_fork(base, cluster, host, usage)
            if forked is not None:
                self.fork_deltas += 1
                return _Generation(key, cluster, usage.version, forked,
                                   mesh=gen_mesh)
        self.full_uploads += 1
        return _Generation(key, cluster, usage.version,
                           self._upload(host, sharding=sharding),
                           mesh=gen_mesh)

    def _try_fork(self, base: _Generation, cluster: ClusterTensors,
                  host: Dict[str, np.ndarray], usage) -> Optional[Dict]:
        changed = IncrementalClusterCache._changed_since(
            getattr(usage, "node_events", ()), base.key[1])
        if changed is None:
            return None
        n = cluster.n_real
        stale = []
        for j, nid in enumerate(cluster.node_ids):
            if nid in changed or nid not in base.cluster.index:
                stale.append(j)
            elif base.cluster.index[nid] != j:
                # compaction permuted surviving rows: the device-side
                # scatter cannot express a gather; full upload
                return None
        if len(stale) > max(n // 2, 8):
            return None
        # rows the new build leaves as padding but the base had real
        # nodes in: their new host values are zeros by construction
        rows = set(stale) | set(range(n, base.cluster.n_real))
        dirty_usage = self._usage_rows_changed(usage, base.version)
        if dirty_usage is None:
            static_host = {f: host[f]
                           for f in ClusterTensors.WAVE_STATIC_FIELDS}
            usage_host = {f: host[f]
                          for f in ClusterTensors.WAVE_USAGE_FIELDS}
            planes = dict(base.planes)
            if rows:
                planes = self._scatter(planes, static_host, rows,
                                       mesh=base.mesh)
            self.usage_full_uploads += 1
            planes.update(self._upload(
                usage_host, sharding=self._gen_sharding(base)))
            return planes
        rows_usage = rows | {cluster.index[nid] for nid in dirty_usage
                             if nid in cluster.index}
        planes = dict(base.planes)
        static_host = {f: host[f]
                       for f in ClusterTensors.WAVE_STATIC_FIELDS}
        usage_host = {f: host[f]
                      for f in ClusterTensors.WAVE_USAGE_FIELDS}
        if rows:
            planes = self._scatter(planes, static_host, rows,
                                   mesh=base.mesh)
        if rows_usage:
            planes = self._scatter(planes, usage_host, rows_usage,
                                   mesh=base.mesh)
        return planes


#: process-wide resident state (the batching worker's snapshot path
#: and the wave launcher both consult it)
default_device_state = DeviceClusterState()
