"""NodeTensor / AskTensor / EvalTensors: the flattening contract.

Reference mapping (SURVEY.md section 2.1 "TPU note"): structs.NodeResources
and structs.AllocatedResources flatten to fixed-width f32/i32 planes --
cpu shares, memory MB, disk MB, port-bitmap words, per-request device
counts -- so feasibility and scoring become elementwise ops on device.
Ragged data (regex/version constraints, attribute strings, device
attributes) is evaluated host-side per computed node class (the
eligibility-cache idea, reference scheduler/feasible.go:1050) and enters
the kernel only as boolean mask planes or integer bucket ids.

Shapes are bucket-padded (``pad_bucket``) so XLA compiles once per size
bucket, not once per cluster size.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Static widths (kernel recompiles if these change; they are framework
# constants, not per-cluster values). Asks exceeding a width raise
# AskLimitError -- the scheduler surfaces it as an eval failure rather
# than silently mis-scheduling.
MAX_RESERVED_PORT_ASKS = 16   # reserved-port asks per task group
MAX_DEV_REQS = 4              # device requests per task group
MAX_SPREADS = 4               # spread stanzas per task group (job+tg merged)
SPREAD_BUCKETS = 128          # distinct attribute values per spread stanza
PORT_WORDS = 65536 // 32      # u32 words covering the port space


class AskLimitError(ValueError):
    """A task group exceeds a static kernel width (device requests,
    spread stanzas). The reference has no such limits (iterators are
    unbounded); the tensor formulation trades that for static shapes."""


import threading as _threading  # noqa: E402

#: guards ClusterTensors' identity-shared lazy caches (gathered usage
#: planes): identity sharing is load-bearing for wave upload layout
_GATHER_LOCK = _threading.Lock()

_MIN_BUCKET = 64


def pad_bucket(n: int) -> int:
    """Round up to the next power of two (min 64) for static shapes."""
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


@dataclass
class ClusterTensors:
    """Per-snapshot node planes, node axis padded to ``n_pad``.

    Built once per scheduling snapshot (and incrementally updatable);
    shared by every evaluation scheduled against that snapshot.
    Capacities are net of node-reserved resources (the subtraction in
    reference funcs.go:199-204 is pre-applied).
    """

    n_real: int
    n_pad: int
    node_ids: List[str]                      # host-side, len n_real
    index: Dict[str, int]                    # node id -> row
    cap_cpu: np.ndarray                      # f32[n_pad]
    cap_mem: np.ndarray                      # f32[n_pad]
    cap_disk: np.ndarray                     # f32[n_pad]
    ready: np.ndarray                        # bool[n_pad]
    port_words: np.ndarray                   # u32[n_pad, PORT_WORDS]
    free_dyn: np.ndarray                     # i32[n_pad] free dynamic ports
    free_cores: np.ndarray                   # i32[n_pad] unreserved core count
    shares_per_core: np.ndarray              # f32[n_pad]
    # host-side ragged companions (never shipped to device)
    datacenters: List[str] = field(default_factory=list)
    node_classes: List[str] = field(default_factory=list)
    computed_classes: List[str] = field(default_factory=list)
    node_pools: List[str] = field(default_factory=list)
    # node-static planes + caches added for the per-eval fast path
    avail_mbits: Optional[np.ndarray] = None      # i32[n_pad] total net mbits
    nodes_by_id: Dict[str, object] = field(default_factory=dict)
    _dc_arr: Optional[np.ndarray] = None          # U-dtype datacenter per row
    _pool_arr: Optional[np.ndarray] = None
    _usage_perm: Optional[Tuple[int, np.ndarray, np.ndarray]] = None
    _class_rows: Optional[Dict[str, List[int]]] = None

    _gathered_usage: Optional[Tuple[int, tuple]] = None
    #: guards _gathered_usage recomputes (see gathered_usage); set by
    #: the builders, None falls back to the module-wide _GATHER_LOCK
    _gather_lock: Optional[object] = None

    # graft: frozen
    def gathered_usage(self, usage) -> tuple:
        """(used_cpu, used_mem, used_disk, used_cores, used_mbits)
        gathered to cluster rows — READ-ONLY arrays cached per usage
        ``version`` and shared by identity across every eval scheduled
        against that snapshot. The wave launcher ships identity-shared
        planes to the device ONCE per wave instead of once per member;
        mutators (retry bookkeeping) must copy-on-write.

        The recompute is double-checked under a lock: identity IS the
        contract here — two eval threads racing a version bump used to
        each build their own (equal) tuples, the wave launcher saw
        distinct objects, fell back to the stacked layout, and
        compiled a whole extra XLA variant for one batch. The lock is
        per-instance where the builders install one (the race is
        per-instance); the module lock is only the fallback for
        directly-constructed instances (bench synthetics)."""
        cached = self._gathered_usage
        if cached is not None and cached[0] == usage.version:
            return cached[1]
        with (self._gather_lock or _GATHER_LOCK):
            cached = self._gathered_usage
            if cached is not None and cached[0] == usage.version:
                return cached[1]
            version = usage.version
            perm, valid = self.usage_perm(usage)
            planes = (
                np.where(valid, usage.used_cpu[perm], 0.0).astype(np.float32),
                np.where(valid, usage.used_mem[perm], 0.0).astype(np.float32),
                np.where(valid, usage.used_disk[perm], 0.0).astype(np.float32),
                np.where(valid, usage.used_cores[perm], 0).astype(np.int32),
                np.where(valid, usage.used_mbits[perm], 0).astype(np.int32),
            )
            for p in planes:
                p.setflags(write=False)
            object.__setattr__(self, "_gathered_usage", (version, planes))
            return planes

    #: KernelIn field -> ClusterTensors plane for the cluster-static
    #: half of the wave-shared group (parallel/coalesce._SHAREABLE_
    #: FIELDS). Single source of truth for the device-resident state
    #: (tensors/device_state.py) and its property tests: these arrays
    #: reach build_kernel_in identity-preserved (np.asarray with a
    #: matching dtype is a no-op), so a device-resident copy keyed by
    #: host identity serves every wave of the snapshot.
    WAVE_STATIC_FIELDS = {
        "cap_cpu": "cap_cpu", "cap_mem": "cap_mem",
        "cap_disk": "cap_disk", "free_cores": "free_cores",
        "shares_per_core": "shares_per_core",
        "avail_mbits": "avail_mbits", "free_dyn": "free_dyn",
    }
    #: KernelIn field order of the gathered_usage tuple (the dynamic
    #: half of the wave-shared group)
    WAVE_USAGE_FIELDS = ("used_cpu", "used_mem", "used_disk",
                         "used_cores", "used_mbits")

    def wave_shared_planes(self, usage) -> Dict[str, np.ndarray]:
        """KernelIn field -> host plane for every wave-shared leaf of
        this (cluster build, usage snapshot) pair — exactly the arrays
        an eval's ``build_kernel_in`` ships by identity when its plan
        is empty (stack.py wave-shared build)."""
        planes = {f: getattr(self, c)
                  for f, c in self.WAVE_STATIC_FIELDS.items()}
        for f, arr in zip(self.WAVE_USAGE_FIELDS,
                          self.gathered_usage(usage)):
            planes[f] = arr
        return planes

    def class_rows(self) -> Dict[str, List[int]]:
        """computed class -> real-node rows, cached on the cluster build
        (the class-eligibility walk needs it once per EVAL; rebuilding
        the O(N) grouping per eval showed in the wave profile)."""
        if self._class_rows is None:
            rows: Dict[str, List[int]] = {}
            for i, cc in enumerate(self.computed_classes):
                rows.setdefault(cc, []).append(i)
            object.__setattr__(self, "_class_rows", rows)
        return self._class_rows

    def usage_perm(self, usage) -> Tuple[np.ndarray, np.ndarray]:
        """Map cluster rows -> usage-plane rows (gather index + validity).

        Cached per usage ``structure_version``; the node set cannot
        change within one version, so the mapping is stable.
        """
        cached = self._usage_perm
        if cached is not None and cached[0] == usage.structure_version:
            return cached[1], cached[2]
        perm = np.zeros(self.n_pad, np.int32)
        valid = np.zeros(self.n_pad, bool)
        for i in range(self.n_real):
            row = usage.rows.get(self.node_ids[i], -1)
            if 0 <= row < usage.n:
                perm[i] = row
                valid[i] = True
        object.__setattr__(
            self, "_usage_perm", (usage.structure_version, perm, valid)
        )
        return perm, valid

    def dc_pool_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized datacenter/pool companions (readyNodesInDCs mask)."""
        if self._dc_arr is None:
            dc = np.array(
                self.datacenters + [""] * (self.n_pad - self.n_real))
            pool = np.array(
                list(self.node_pools) + [""] * (self.n_pad - self.n_real))
            object.__setattr__(self, "_dc_arr", dc)
            object.__setattr__(self, "_pool_arr", pool)
        return self._dc_arr, self._pool_arr

    def _flatten_row(self, i: int, node) -> None:
        """Flatten one structs.Node into row ``i`` of the plane arrays
        (shared by the full build and the dirty-row delta path). The
        NetworkIndex port scan here is the dominant per-node cost of a
        cluster build — exactly what the delta path avoids paying for
        unchanged nodes."""
        from nomad_tpu.structs.network import NetworkIndex

        res = node.node_resources
        rsv = node.reserved_resources
        self.cap_cpu[i] = max(res.cpu.cpu_shares - rsv.cpu_shares, 0)
        self.cap_mem[i] = max(res.memory.memory_mb - rsv.memory_mb, 0)
        self.cap_disk[i] = max(res.disk.disk_mb - rsv.disk_mb, 0)
        self.ready[i] = node.ready()
        idx = NetworkIndex()
        idx.set_node(node)
        w64 = idx.port_words()            # u64[1024]
        self.port_words[i] = w64.view(np.uint32)
        self.free_dyn[i] = idx.free_dynamic_count()
        self.free_cores[i] = len(
            set(res.cpu.reservable_cpu_cores) - set(rsv.reserved_cpu_cores)
        )
        self.shares_per_core[i] = res.cpu.shares_per_core()
        self.avail_mbits[i] = sum(net.mbits for net in res.networks)
        self.node_ids[i] = node.id
        self.datacenters[i] = node.datacenter
        self.node_classes[i] = node.node_class
        self.computed_classes[i] = node.computed_class or node.compute_class()
        self.node_pools[i] = node.node_pool

    @classmethod
    def _empty(cls, n: int, npad: int) -> "ClusterTensors":
        return cls(
            n_real=n, n_pad=npad,
            node_ids=[""] * n, index={},
            cap_cpu=np.zeros(npad, np.float32),
            cap_mem=np.zeros(npad, np.float32),
            cap_disk=np.zeros(npad, np.float32),
            ready=np.zeros(npad, bool),
            port_words=np.zeros((npad, PORT_WORDS), np.uint32),
            free_dyn=np.zeros(npad, np.int32),
            free_cores=np.zeros(npad, np.int32),
            shares_per_core=np.zeros(npad, np.float32),
            datacenters=[""] * n, node_classes=[""] * n,
            computed_classes=[""] * n, node_pools=[""] * n,
            avail_mbits=np.zeros(npad, np.int32),
            _gather_lock=_threading.Lock(),
        )

    @classmethod
    def build(cls, nodes: Sequence) -> "ClusterTensors":
        """Flatten structs.Node rows. Nodes keep their given order; the
        caller owns any shuffling (reference util.go:464 shuffleNodes is
        unnecessary under global argmax selection)."""
        n = len(nodes)
        out = cls._empty(n, pad_bucket(n))
        for i, node in enumerate(nodes):
            out._flatten_row(i, node)
        out.index = {nid: i for i, nid in enumerate(out.node_ids)}
        out.nodes_by_id = {nd.id: nd for nd in nodes}
        return out

    _PLANE_FIELDS = ("cap_cpu", "cap_mem", "cap_disk", "ready",
                     "port_words", "free_dyn", "free_cores",
                     "shares_per_core", "avail_mbits")
    _RAGGED_FIELDS = ("node_ids", "datacenters", "node_classes",
                      "computed_classes", "node_pools")

    def rebuild_delta(self, nodes: Sequence,
                      changed_ids) -> Optional["ClusterTensors"]:
        """A fresh ClusterTensors for the new node table, re-flattening
        ONLY the rows in ``changed_ids`` (plus additions); every other
        row is gathered from this build by numpy memcpy. Returns None
        when a delta is not worth it or not possible (pad-bucket
        change, or more than half the rows dirty) — the caller falls
        back to ``build``.

        The result is bit-identical to ``ClusterTensors.build(nodes)``:
        unchanged rows were computed from the same node objects (the
        store's change log guarantees untouched ids kept their rows'
        inputs), additions/removals reproduce the store's dict-order
        compaction, and dirty rows run the same flatten."""
        n = len(nodes)
        npad = pad_bucket(n)
        if npad != self.n_pad:
            return None
        if self.n_real == 0:
            # nothing to gather from (the ragged lists are empty, so
            # even placeholder row indices for stale rows would be out
            # of range); a fresh build of a tiny cluster is cheap
            return None
        stale: List[int] = []
        perm = np.zeros(n, np.int64)
        for j, node in enumerate(nodes):
            i = self.index.get(node.id, -1)
            if i < 0 or node.id in changed_ids:
                stale.append(j)
            else:
                perm[j] = i
        if len(stale) > max(n // 2, 8):
            return None
        out = ClusterTensors._empty(n, npad)
        for f in self._PLANE_FIELDS:
            old = getattr(self, f)
            new = getattr(out, f)
            new[:n] = old[perm]
        for f in self._RAGGED_FIELDS:
            old = getattr(self, f)
            setattr(out, f, [old[i] for i in perm])
        for j in stale:
            out._flatten_row(j, nodes[j])
        out.index = {nid: i for i, nid in enumerate(out.node_ids)}
        out.nodes_by_id = {nd.id: nd for nd in nodes}
        return out


@dataclass
class AskTensor:
    """Node-independent flattening of one task group's resource ask.

    The per-task loop in reference rank.go:349-500 collapses: tasks of a
    group are summed host-side (cpu/mem; group disk; group+task ports;
    device request counts) because the kernel places whole groups.
    """

    cpu: float = 0.0                 # summed task cpu shares (MHz)
    mem: float = 0.0                 # summed task memory MB
    disk: float = 0.0                # group ephemeral disk MB
    cores: int = 0                   # summed reserved-core asks
    n_dyn_ports: int = 0
    reserved_ports: List[int] = None     # host-side full list of asks
    port_mask: np.ndarray = None         # u32[PORT_WORDS] bits of ALL asks
    n_dev_reqs: int = 0
    dev_counts: np.ndarray = None        # i32[MAX_DEV_REQS], 0 pad
    total_mbits: int = 0

    @classmethod
    def build(cls, tg) -> "AskTensor":
        a = cls()
        a.reserved_ports = []
        a.port_mask = np.zeros(PORT_WORDS, np.uint32)
        a.dev_counts = np.zeros(MAX_DEV_REQS, np.int32)
        a.disk = float(tg.ephemeral_disk.size_mb)

        ndev = 0
        for net in tg.networks:
            a.n_dyn_ports += len(net.dynamic_ports)
            a.total_mbits += net.mbits
            a.reserved_ports += [p.value for p in net.reserved_ports]
        for task in tg.tasks:
            r = task.resources
            if r.cores > 0:
                a.cores += r.cores
            else:
                a.cpu += float(r.cpu)
            a.mem += float(r.memory_mb)
            for net in r.networks:
                a.n_dyn_ports += len(net.dynamic_ports)
                a.total_mbits += net.mbits
                a.reserved_ports += [p.value for p in net.reserved_ports]
            for dev in r.devices:
                if ndev >= MAX_DEV_REQS:
                    raise AskLimitError(
                        f"task group {tg.name!r} has more than "
                        f"{MAX_DEV_REQS} device requests"
                    )
                a.dev_counts[ndev] = dev.count
                ndev += 1
        a.n_dev_reqs = ndev
        for port in a.reserved_ports:
            a.port_mask[port >> 5] |= np.uint32(1 << (port & 31))
        return a


@dataclass
class SpreadTensor:
    """One spread stanza flattened to bucket arrays.

    ``bucket_id[n]`` maps each node's attribute value into the stanza's
    value table (-1 when the node lacks the attribute); ``counts[b]``
    is existing+proposed allocs per value (reference propertyset.go);
    ``desired[b]`` is the target count per value, or -1 everywhere for
    even-spread mode (no targets specified, reference spread.go:193).
    """

    bucket_id: np.ndarray        # i32[n_pad]
    counts: np.ndarray           # f32[SPREAD_BUCKETS]
    desired: np.ndarray          # f32[SPREAD_BUCKETS]; -1 = even-spread mode
    weight_frac: float = 1.0     # weight / sumSpreadWeights
    even: bool = False


@dataclass
class EvalTensors:
    """Everything one (evaluation, task group) pair ships to the kernel.

    The boolean/score planes are the tensorized residue of the
    feasibility+rank iterator chain (reference stack.go:344-439):
    ``base_mask`` folds RandomIterator eligibility, class-level constraint
    checks, driver checks, distinct_hosts/property and volume checks;
    ``aff_score``/``penalty``/``job_tg_count`` feed the soft-score planes.
    """

    base_mask: np.ndarray            # bool[n_pad]
    used_cpu: np.ndarray             # f32[n_pad] proposed utilization
    used_mem: np.ndarray             # f32[n_pad]
    used_disk: np.ndarray            # f32[n_pad]
    used_mbits: np.ndarray           # i32[n_pad]
    avail_mbits: np.ndarray          # i32[n_pad]
    used_cores: np.ndarray           # i32[n_pad] count of reserved cores used
    port_conflict_words: np.ndarray  # u32[n_pad, PORT_WORDS] in-plan port bits
    free_dyn_delta: np.ndarray       # i32[n_pad] dyn ports consumed in-plan
    dev_free: np.ndarray             # f32[n_pad, MAX_DEV_REQS] per-request
    dev_aff_score: np.ndarray        # f32[n_pad]
    has_dev_affinity: bool
    job_tg_count: np.ndarray         # i32[n_pad] same job+tg proposed allocs
    job_any_count: np.ndarray        # i32[n_pad] job allocs on node (any tg)
    distinct_hosts_job: bool         # job-level distinct_hosts constraint
    distinct_hosts_tg: bool          # tg-level distinct_hosts constraint
    penalty: np.ndarray              # bool[n_pad] rescheduling penalty nodes
    aff_score: np.ndarray            # f32[n_pad] normalized affinity score
    has_affinities: bool
    spreads: List[SpreadTensor]
    ask: AskTensor
    desired_count: int               # tg.count (anti-affinity denominator)
    algorithm: str = "binpack"       # binpack | spread (cluster config)
    #: bool[n_pad] overlay for reserved-port asks: nodes whose LIVE
    #: allocs already hold an asked port (from the usage index's port
    #: bitmaps — state/usage.py). The static node plane only covers
    #: agent-reserved ports; without this the kernel picks occupied
    #: nodes and placement burns an assigner-fail + masked relaunch.
    port_live_conflict: Optional[np.ndarray] = None


class IncrementalClusterCache:
    """ClusterTensors cache keyed on the state store's identity, with
    dirty-node delta refresh.

    The batching worker used to pay a full O(nodes) Python rebuild
    (NetworkIndex port scan per node) every batch whose snapshot's
    ``structure_version`` moved — and on a live cluster it moves every
    heartbeat-driven status write. This cache replays the usage
    index's node-change log (state/usage.py ``node_events``) between
    the cached build's version and the snapshot's, re-flattening only
    the logged rows (``ClusterTensors.rebuild_delta``). A poisoned or
    trimmed log, a pad-bucket change, or majority churn falls back to
    the full build. Delta results are bit-identical to a fresh build
    and keyed per (uid, structure_version), so wave members keep
    sharing one object by identity."""

    def __init__(self, max_entries: int = 8) -> None:
        self._lock = _threading.Lock()
        #: (uid, structure_version) -> ClusterTensors. Versioned keys
        #: matter: a batch still scheduling against an OLDER snapshot
        #: than the newest cached one must keep getting one identical
        #: object per call (identity sharing is the wave launcher's
        #: upload layout), not a fresh rebuild per eval.
        self._entries: Dict[Tuple[str, int], ClusterTensors] = {}
        #: uid -> newest cached structure_version (the delta base)
        self._latest: Dict[str, int] = {}
        self.max_entries = max_entries
        # observability (asserted by tests, handy under a profiler)
        self.hits = 0
        self.delta_builds = 0
        self.full_builds = 0

    def get(self, state) -> ClusterTensors:
        u = getattr(state, "usage", None)
        if u is None or not u.uid:
            self.full_builds += 1
            return ClusterTensors.build(state.nodes())
        key = (u.uid, u.structure_version)
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self.hits += 1
                return hit
            base_sv = self._latest.get(u.uid)
            base = (self._entries.get((u.uid, base_sv))
                    if base_sv is not None else None)
        nodes = state.nodes()
        built: Optional[ClusterTensors] = None
        if base is not None and base_sv < u.structure_version:
            changed = self._changed_since(
                getattr(u, "node_events", ()), base_sv)
            if changed is not None:
                built = base.rebuild_delta(nodes, changed)
        if built is not None:
            self.delta_builds += 1
        else:
            built = ClusterTensors.build(nodes)
            self.full_builds += 1
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                # a racing thread cached this exact version first: keep
                # ITS object so every caller of the version shares one
                return hit
            self._entries[key] = built
            if u.structure_version >= self._latest.get(u.uid, -1):
                self._latest[u.uid] = u.structure_version
            while len(self._entries) > self.max_entries:
                old_key = next(iter(self._entries))
                self._entries.pop(old_key)
                if self._latest.get(old_key[0]) == old_key[1]:
                    self._latest.pop(old_key[0], None)
        return built

    @staticmethod
    def _changed_since(events, since_sv: int):
        """Node ids changed after ``since_sv`` per the log, or None
        when the log cannot prove completeness (poison entry, trimmed
        tail, or no events despite a version bump)."""
        if not events:
            return None
        changed = set()
        seen_floor = None
        for sv, nid in events:
            if seen_floor is None:
                seen_floor = sv
            if sv <= since_sv:
                continue
            if nid is None:
                return None
            changed.add(nid)
        # the log's oldest entry must not postdate the gap start, or
        # trimmed entries may hide changes
        if seen_floor is None or seen_floor > since_sv + 1:
            return None
        return changed


#: process-wide incremental cache (the batching worker's
#: cluster_provider and the direct scheduler path both consult it)
default_incremental_cluster_cache = IncrementalClusterCache()
