"""Output formatting helpers for the CLI.

Reference behavior: the Go CLI renders aligned key=value rows and
column tables via helper/flatmap + mitchellh/columnize (used across
command/*.go, e.g. formatKV/formatList in command/helpers.go).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional, Sequence


def format_kv(rows: Sequence[str]) -> str:
    """Align 'Key|Value' rows on the pipe, like formatKV."""
    pairs = [r.split("|", 1) for r in rows]
    width = max((len(p[0]) for p in pairs), default=0)
    out = []
    for p in pairs:
        if len(p) == 1:
            out.append(p[0])
        else:
            out.append(f"{p[0]:<{width}}  = {p[1]}")
    return "\n".join(out)


def format_list(rows: Sequence[str]) -> str:
    """Align pipe-separated columns, like formatList (columnize)."""
    if not rows:
        return ""
    table = [r.split("|") for r in rows]
    ncols = max(len(r) for r in table)
    widths = [0] * ncols
    for r in table:
        for i, cell in enumerate(r):
            widths[i] = max(widths[i], len(cell))
    lines = []
    for r in table:
        line = "  ".join(f"{cell:<{widths[i]}}" for i, cell in enumerate(r))
        lines.append(line.rstrip())
    return "\n".join(lines)


def short_id(full: Optional[str], length: int = 8) -> str:
    """First 8 chars of a UUID, like limit(id, shortId)."""
    return (full or "")[:length]


def format_time(unix_ns_or_s: Optional[float]) -> str:
    if not unix_ns_or_s:
        return "N/A"
    v = float(unix_ns_or_s)
    if v > 1e15:  # nanoseconds
        v /= 1e9
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(v))


def format_ago(unix_s: Optional[float]) -> str:
    if not unix_s:
        return "N/A"
    d = max(0.0, time.time() - float(unix_s))
    if d < 60:
        return f"{int(d)}s ago"
    if d < 3600:
        return f"{int(d // 60)}m{int(d % 60)}s ago"
    return f"{int(d // 3600)}h{int((d % 3600) // 60)}m ago"


def dict_rows(items: Iterable[Dict[str, Any]], cols: Sequence[str],
              header: Optional[Sequence[str]] = None) -> str:
    rows = ["|".join(header or cols)]
    for it in items:
        rows.append("|".join(str(it.get(c, "")) for c in cols))
    return format_list(rows)
